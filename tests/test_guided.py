"""Guided (grammar-constrained) decoding on the continuous engine: FSM
masking exactness, pattern conformance across cache modes, speculative
composition, and registration bookkeeping."""

from __future__ import annotations

import json
import re

import jax
import numpy as np
import pytest

from ditl_tpu.config import ModelConfig
from ditl_tpu.data.tokenizer import ByteTokenizer
from ditl_tpu.infer import grammar as G
from ditl_tpu.infer.continuous import ContinuousEngine
from ditl_tpu.infer.engine import GenerateConfig
from ditl_tpu.models import llama


@pytest.fixture(scope="module")
def setup():
    cfg = ModelConfig(
        vocab_size=512,
        hidden_size=64,
        intermediate_size=128,
        num_layers=2,
        num_heads=4,
        num_kv_heads=2,
        head_dim=16,
        max_seq_len=128,
        dtype="float32",
        param_dtype="float32",
    )
    params = llama.init_params(jax.random.key(0), cfg)
    tok = ByteTokenizer()
    return params, cfg, tok


def _engine(params, cfg, tok, **kw):
    kw.setdefault("n_slots", 2)
    kw.setdefault("decode_chunk", 4)
    kw.setdefault("gen", GenerateConfig(max_new_tokens=16, temperature=0.0))
    kw.setdefault("fsm_capacity", 1024)
    return ContinuousEngine(params, cfg, tok, **kw)


@pytest.mark.slow
def test_unconstrained_rows_bit_exact_vs_unguided(setup):
    """A guided-capacity engine serving NO grammar must produce tokens
    bit-identical to a guided-off engine (the FREE row is an identity
    mask)."""
    params, cfg, tok = setup
    prompts = ["hello world", "abc def"]
    gen = GenerateConfig(max_new_tokens=12)
    plain = ContinuousEngine(
        params, cfg, tok, n_slots=2, decode_chunk=4, gen=gen,
    ).generate(prompts)
    guided = _engine(params, cfg, tok, gen=gen).generate(prompts)
    assert guided == plain


def test_regex_constrained_output_matches(setup):
    params, cfg, tok = setup
    g = G.compile_regex(r"[0-9]{3}-[0-9]{4}", tok)
    eng = _engine(params, cfg, tok)
    out = eng.generate(["call me at", "the number is"], grammar=g)
    for text in out:
        assert re.fullmatch(r"[0-9]{3}-[0-9]{4}", text), out
    # bounded grammar: single accepting sink => generation stopped at EOS,
    # not the token budget
    assert all(len(t) == 8 for t in out)


@pytest.mark.slow
def test_mixed_batch_free_rows_unaffected(setup):
    """One constrained + one free request sharing decode ticks: the free
    row's output is identical to an all-free engine run."""
    params, cfg, tok = setup
    g = G.compile_regex(r"(yes|no)", tok)
    free_alone = _engine(params, cfg, tok).generate(["tell me"])[0]
    eng = _engine(params, cfg, tok)
    rid_c = eng.submit([tok.bos_id] + tok.encode("answer:"), grammar=g)
    rid_f = eng.submit([tok.bos_id] + tok.encode("tell me"))
    res = eng.run()
    assert tok.decode(res[rid_f]) == free_alone
    assert tok.decode(res[rid_c]) in ("yes", "no")


@pytest.mark.slow
def test_schema_constrained_json(setup):
    params, cfg, tok = setup
    schema = {"enum": ["red", "green", "blue"]}
    g = G.compile_json_schema(schema, tok)
    eng = _engine(params, cfg, tok)
    out = eng.generate(["pick a color"], grammar=g)[0]
    assert json.loads(out) in ("red", "green", "blue")


@pytest.mark.slow
def test_json_mode_output_is_valid_prefix(setup):
    """json_object mode on a random-weight model: every emitted byte walks
    the JSON DFA live (the guarantee is valid-prefix always, full validity
    when EOS lands inside the budget)."""
    params, cfg, tok = setup
    g = G.compile_json(tok, max_depth=3)
    eng = _engine(
        params, cfg, tok,
        gen=GenerateConfig(max_new_tokens=24, temperature=0.0),
    )
    out = eng.generate(["emit json"], grammar=g)[0]
    data = out.encode()
    s = 0
    for b in data:
        s = int(g.byte_next[s, b])
        assert s >= 0, f"dead byte in {out!r}"
    try:
        json.loads(out)
    except ValueError:
        assert len(eng.tokenizer.encode(out)) >= 24  # budget-truncated


@pytest.mark.slow
def test_sampled_constrained(setup):
    params, cfg, tok = setup
    g = G.compile_regex(r"[ab]{2,6}", tok)
    eng = _engine(params, cfg, tok)
    rid = eng.submit(
        [tok.bos_id] + tok.encode("x"), grammar=g, temperature=0.9, seed=7,
    )
    out = tok.decode(eng.run()[rid])
    assert re.fullmatch(r"[ab]{2,6}", out), out


@pytest.mark.slow
def test_paged_constrained(setup):
    params, cfg, tok = setup
    g = G.compile_regex(r"[0-9]{2}(px|em)", tok)
    eng = _engine(
        params, cfg, tok, cache_mode="paged", page_size=16, max_cache_len=64,
    )
    out = eng.generate(["width:", "height:"], grammar=g)
    for text in out:
        assert re.fullmatch(r"[0-9]{2}(px|em)", text), out


@pytest.mark.slow
def test_spec_guided_greedy_exact(setup):
    """Speculative ticks under a grammar emit token-identical output to
    plain guided ticks (f32, greedy)."""
    params, cfg, tok = setup
    g = G.compile_regex(r"[a-z ]{1,30}", tok)
    prompts = ["the cat sat on the", "a b a b a b"]
    plain = _engine(params, cfg, tok).generate(prompts, grammar=g)
    spec = _engine(
        params, cfg, tok, speculative=True, spec_k=4, spec_threshold=0.0,
    ).generate(prompts, grammar=g)
    assert spec == plain
    for t in spec:
        assert re.fullmatch(r"[a-z ]{1,30}", t), spec


@pytest.mark.slow
def test_spec_paged_guided(setup):
    params, cfg, tok = setup
    g = G.compile_regex(r"-?[0-9]{1,6}", tok)
    plain = _engine(
        params, cfg, tok, cache_mode="paged", page_size=16, max_cache_len=64,
    ).generate(["n ="], grammar=g)
    spec = _engine(
        params, cfg, tok, cache_mode="paged", page_size=16, max_cache_len=64,
        speculative=True, spec_k=4, spec_threshold=0.0,
    ).generate(["n ="], grammar=g)
    assert spec == plain
    assert re.fullmatch(r"-?[0-9]{1,6}", spec[0])


@pytest.mark.slow
def test_chunked_prefill_constrained(setup):
    params, cfg, tok = setup
    g = G.compile_regex(r"(foo|bar){1,4}", tok)
    long_prompt = "word " * 12
    ref = _engine(params, cfg, tok).generate([long_prompt], grammar=g)[0]
    chunked = _engine(params, cfg, tok, prefill_chunk=16).generate(
        [long_prompt], grammar=g
    )[0]
    assert chunked == ref
    assert re.fullmatch(r"(foo|bar){1,4}", ref)


@pytest.mark.slow
def test_logprobs_compose_with_grammar(setup):
    params, cfg, tok = setup
    g = G.compile_regex(r"[0-9]{4}", tok)
    eng = _engine(params, cfg, tok, logprobs_k=3)
    rid = eng.submit(
        [tok.bos_id] + tok.encode("year:"), grammar=g, logprobs=2,
    )
    while eng.pending:
        eng.step()
    req = eng._completed[rid]
    assert re.fullmatch(r"[0-9]{4}", tok.decode(req.tokens))
    assert len(req.lp_token) >= len(req.tokens)
    # engine stores logprobs_k-wide rows; the serving layer slices to N
    assert all(len(r) == 3 for r in req.lp_top_ids[: len(req.tokens)])


def test_registration_bookkeeping(setup):
    params, cfg, tok = setup
    eng = _engine(params, cfg, tok, fsm_capacity=64)
    g1 = G.compile_regex(r"[ab]+", tok)
    b1 = eng.register_grammar(g1)
    assert b1 == 2  # after FREE + DEAD
    assert eng.register_grammar(g1) == b1  # dedup by content
    g2 = G.compile_regex(r"[cd]+", tok)
    b2 = eng.register_grammar(g2)
    assert b2 > b1
    stats = eng.stats()["guided"]
    assert stats["grammars_registered"] == 2
    big = G.compile_json(tok, max_depth=3)  # hundreds of states
    with pytest.raises(ValueError, match="fsm_capacity exhausted"):
        eng.register_grammar(big)
    # int start-state submission round-trips
    rid = eng.submit([tok.bos_id] + tok.encode("q"), grammar=b1)
    out = tok.decode(eng.run()[rid])
    assert re.fullmatch(r"[ab]+", out) or out == ""


def test_guided_off_engine_rejects_grammar(setup):
    params, cfg, tok = setup
    eng = ContinuousEngine(params, cfg, tok, n_slots=1)
    g = G.compile_regex(r"a+", ByteTokenizer())
    with pytest.raises(ValueError, match="fsm_capacity"):
        eng.submit([3], grammar=g)


@pytest.mark.slow
def test_server_guided_routes(setup):
    """HTTP layer: guided_regex, response_format json_object, guided_json
    schema, streaming with a grammar, and the 400 for unarmed servers."""
    import threading
    import urllib.error
    import urllib.request

    from ditl_tpu.infer.continuous import ThreadedEngine
    from ditl_tpu.infer.engine import Generator
    from ditl_tpu.infer.server import make_server

    params, cfg, tok = setup
    threaded = ThreadedEngine(
        _engine(params, cfg, tok, n_slots=4, fsm_capacity=4096)
    )
    server = make_server(
        Generator(params, cfg, tok), host="127.0.0.1", port=0,
        threaded_engine=threaded, default_max_tokens=16,
    )
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()

    def post(path, body, expect_error=False):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}{path}", data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=120) as resp:
                return resp.status, json.loads(resp.read())
        except urllib.error.HTTPError as e:
            assert expect_error
            return e.code, json.loads(e.read())

    try:
        # guided_regex on completions
        status, out = post("/v1/completions", {
            "prompt": "pin:", "guided_regex": "[0-9]{4}", "max_tokens": 12,
        })
        assert status == 200
        assert re.fullmatch(r"[0-9]{4}", out["choices"][0]["text"])
        # response_format json_object on chat completions
        status, out = post("/v1/chat/completions", {
            "messages": [{"role": "user", "content": "emit json"}],
            "response_format": {"type": "json_object"}, "max_tokens": 20,
        })
        assert status == 200
        text = out["choices"][0]["message"]["content"]
        g = G.compile_json(tok)
        s = 0
        for b in text.encode():
            s = int(g.byte_next[s, b])
            assert s >= 0, text
        # guided_json schema
        status, out = post("/v1/completions", {
            "prompt": "color:", "max_tokens": 12,
            "guided_json": {"enum": ["on", "off"]},
        })
        assert status == 200
        assert json.loads(out["choices"][0]["text"]) in ("on", "off")
        # response_format json_schema: strict-mode object (order-free,
        # bounded integer, anyOf) through the full HTTP path
        status, out = post("/v1/chat/completions", {
            "messages": [{"role": "user", "content": "extract"}],
            "max_tokens": 48,
            "response_format": {"type": "json_schema", "json_schema": {
                "name": "rec", "schema": {
                    "type": "object",
                    "properties": {
                        "n": {"type": "integer", "minimum": 0,
                              "maximum": 99},
                        "u": {"anyOf": [{"const": "a"}, {"const": "b"}]},
                    },
                    "required": ["n", "u"],
                    "additionalProperties": False,
                },
            }},
        })
        assert status == 200
        text = out["choices"][0]["message"]["content"]
        if out["choices"][0]["finish_reason"] == "stop":
            doc = json.loads(text)
            assert set(doc) == {"n", "u"}
            assert 0 <= doc["n"] <= 99 and doc["u"] in ("a", "b")
        # streaming + grammar: SSE chunks concatenate to a full match
        import http.client

        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
        conn.request(
            "POST", "/v1/completions",
            json.dumps({"prompt": "id:", "guided_regex": "[a-f]{6}",
                        "max_tokens": 10, "stream": True}),
            {"Content-Type": "application/json"},
        )
        resp = conn.getresponse()
        assert resp.status == 200
        acc = ""
        for raw in resp.read().decode().splitlines():
            if raw.startswith("data: ") and raw != "data: [DONE]":
                acc += json.loads(raw[6:])["choices"][0]["text"]
        conn.close()
        assert re.fullmatch(r"[a-f]{6}", acc), acc
        # bad spec -> 400
        status, out = post("/v1/completions", {
            "prompt": "x", "guided_regex": "([unclosed",
        }, expect_error=True)
        assert status == 400
        # two specs at once -> 400
        status, _ = post("/v1/completions", {
            "prompt": "x", "guided_regex": "a+",
            "response_format": {"type": "json_object"},
        }, expect_error=True)
        assert status == 400
    finally:
        server.shutdown()
        threaded.close()


@pytest.mark.slow
def test_server_unarmed_guided_400(setup):
    """A server whose engine lacks fsm_capacity answers 400, not 500."""
    import threading
    import urllib.error
    import urllib.request

    from ditl_tpu.infer.continuous import ThreadedEngine
    from ditl_tpu.infer.engine import Generator
    from ditl_tpu.infer.server import make_server

    params, cfg, tok = setup
    threaded = ThreadedEngine(
        ContinuousEngine(params, cfg, tok, n_slots=2,
                         gen=GenerateConfig(max_new_tokens=8))
    )
    server = make_server(
        Generator(params, cfg, tok), host="127.0.0.1", port=0,
        threaded_engine=threaded,
    )
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/completions",
            data=json.dumps({"prompt": "x", "guided_regex": "a+"}).encode(),
            headers={"Content-Type": "application/json"},
        )
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=60)
        assert ei.value.code == 400
        assert "fsm-capacity" in json.loads(ei.value.read())["error"]["message"]
    finally:
        server.shutdown()
        threaded.close()


def test_unreachable_grammar_raises():
    """A grammar no token path can complete fails at COMPILE time (liveness
    trim), not by stranding a slot at serve time."""

    class TwoTok:  # vocab: only "ab" exists as a real token
        vocab_size = 4
        pad_id, bos_id, eos_id = 0, 1, 2

        def decode(self, ids):
            return "ab" if ids == [3] else ""

        def encode(self, text):
            raise NotImplementedError

    with pytest.raises(ValueError, match="admits no completion"):
        G.compile_regex(r"abc", TwoTok())
