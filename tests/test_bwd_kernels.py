"""Pallas fused-backward kernels (ops/mlp_bwd.py, ops/projection.py).

Gradient agreement at three levels, interpret-mode on CPU so the same
assertions run in tier-1 (and as real Mosaic kernels on TPU):

1. kernel vs the einsum-spelled VJP (ops/mlp.py's "xla" backward) — the
   two implementations behind the same custom-VJP seam must agree;
2. kernel vs plain autodiff through the op;
3. full-model ``loss_fn`` grads with the Pallas flags vs the pinned
   defaults, single-device AND on the 8-virtual-device DP/FSDP/TP mesh —
   the composition the kernels must survive in training (the shard_map
   wrapper's psum of replicated-weight grads, the activation constraints,
   remat).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.flatten_util
import jax.numpy as jnp
import numpy as np
import pytest

from ditl_tpu.config import MeshConfig, ModelConfig
from ditl_tpu.models import llama
from ditl_tpu.ops import mlp_bwd
from ditl_tpu.ops import projection as projmod
from ditl_tpu.ops.mlp import mlp_block, mlp_gu
from ditl_tpu.runtime.mesh import build_mesh
from ditl_tpu.train.step import loss_fn

pytestmark = pytest.mark.pallas

B, S, D, F = 2, 32, 256, 128
MLP_BLOCKS = (64, 128, 128)
PROJ_BLOCKS = (64, 128)


def _identity(t):
    return t


@pytest.fixture(scope="module")
def tensors():
    key = jax.random.key(0)
    h = jax.random.normal(jax.random.fold_in(key, 1), (B, S, D), jnp.float32)
    w_gu = jax.random.normal(jax.random.fold_in(key, 2), (D, 2 * F)) * 0.05
    w_down = jax.random.normal(jax.random.fold_in(key, 3), (F, D)) * 0.05
    g = jax.random.normal(jax.random.fold_in(key, 4), (B, S, D), jnp.float32)
    return h, w_gu, w_down, g


def test_supports_rejects_unaligned_shapes():
    assert mlp_bwd.supports(B * S, D, F, MLP_BLOCKS)
    assert not mlp_bwd.supports(B * S, D, 96, MLP_BLOCKS)   # F not lane-tiled
    assert not mlp_bwd.supports(B * S - 1, D, F, MLP_BLOCKS)
    assert projmod.supports(B * S, D, 2 * F, PROJ_BLOCKS)
    assert not projmod.supports(B * S, 200, 2 * F, PROJ_BLOCKS)


def test_fused_mlp_bwd_matches_einsum_vjp(tensors):
    """Level 1: the Pallas kernels vs the einsum-spelled backward — the
    exact pair an on-chip A/B compares."""
    h, w_gu, w_down, g = tensors
    gu = jnp.einsum("bsd,df->bsf", h, w_gu)
    gate, up = jnp.split(gu, 2, axis=-1)
    dh_p, dwgu_p, dwdn_p = mlp_bwd.fused_mlp_bwd(
        h, w_gu, w_down, gate, up, g, blocks=MLP_BLOCKS
    )
    # The einsum spelling, inlined (ops/mlp.py _bwd with constrain=identity).
    sg = jax.nn.sigmoid(gate)
    silu_gate = gate * sg
    inner = silu_gate * up
    dwdn = jnp.einsum("bsf,bsd->fd", inner, g)
    dinner = jnp.einsum("bsd,fd->bsf", g, w_down)
    dgu = jnp.concatenate(
        [dinner * up * (sg * (1.0 + gate * (1.0 - sg))), dinner * silu_gate],
        axis=-1,
    )
    dwgu = jnp.einsum("bsd,bsf->df", h, dgu)
    dh = jnp.einsum("bsf,df->bsd", dgu, w_gu)
    np.testing.assert_allclose(np.asarray(dh_p), np.asarray(dh),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(dwgu_p), np.asarray(dwgu),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(dwdn_p), np.asarray(dwdn),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("blocks", [MLP_BLOCKS, (16, 128, 256)])
def test_mlp_gu_pallas_matches_autodiff(tensors, blocks):
    """Level 2: grads through the op vs autodiff of the plain forward."""
    h, w_gu, w_down, _ = tensors

    def ref(h, a, b):
        gu = jnp.einsum("bsd,df->bsf", h, a)
        gate, up = jnp.split(gu, 2, axis=-1)
        return jnp.sum(jnp.einsum("bsf,fd->bsd", jax.nn.silu(gate) * up, b) ** 2)

    def pallas(h, a, b):
        return jnp.sum(mlp_gu(_identity, h, a, b, "pallas", blocks) ** 2)

    g_ref = jax.grad(ref, argnums=(0, 1, 2))(h, w_gu, w_down)
    g_pal = jax.grad(jax.jit(pallas), argnums=(0, 1, 2))(h, w_gu, w_down)
    for r, p in zip(g_ref, g_pal):
        np.testing.assert_allclose(np.asarray(p), np.asarray(r),
                                   rtol=1e-4, atol=1e-5)


def test_mlp_gu_pallas_falls_back_on_untileable_shapes(tensors):
    """Shapes supports() rejects keep working through the einsum backward
    (the dispatch is a fallback, not a crash; bench records which ran)."""
    h, w_gu, w_down, _ = tensors
    w_gu_odd = w_gu[:, : 2 * 96]  # F=96: not lane-tileable
    w_down_odd = w_down[:96]

    def f(impl):
        return jax.grad(
            lambda h: jnp.sum(
                mlp_gu(_identity, h, w_gu_odd, w_down_odd, impl, ()) ** 2
            )
        )(h)

    np.testing.assert_allclose(np.asarray(f("pallas")), np.asarray(f("xla")),
                               rtol=1e-5, atol=1e-6)


def test_projection_pallas_matches_autodiff(tensors):
    h, *_ = tensors
    w = jax.random.normal(jax.random.key(9), (D, 2 * F)) * 0.05

    def ref(x, w):
        return jnp.sum(jnp.einsum("bsd,df->bsf", x, w) ** 2)

    def pallas(x, w):
        return jnp.sum(
            projmod.projection(x, w, bwd_impl="pallas", blocks=PROJ_BLOCKS) ** 2
        )

    g_ref = jax.grad(ref, argnums=(0, 1))(h, w)
    g_pal = jax.grad(jax.jit(pallas), argnums=(0, 1))(h, w)
    for r, p in zip(g_ref, g_pal):
        np.testing.assert_allclose(np.asarray(p), np.asarray(r),
                                   rtol=1e-4, atol=1e-5)


def _pallas_cfg(cfg):
    return dataclasses.replace(
        cfg, mlp_bwd_impl="pallas", proj_bwd_impl="pallas",
        mlp_bwd_block_n=32, mlp_bwd_block_f=128, mlp_bwd_block_d=128,
        proj_bwd_block_n=32, proj_bwd_block_d=128,
    )


@pytest.fixture(scope="module")
def model_cfg():
    # Tile-able dims (D, F, head projections all 128-multiples), f32 so the
    # comparison is exact-to-accumulation-order.
    return ModelConfig(
        vocab_size=512, hidden_size=256, intermediate_size=128, num_layers=2,
        num_heads=4, num_kv_heads=2, head_dim=64, max_seq_len=64,
        dtype="float32", param_dtype="float32", fused_gate_up=True,
    )


def test_full_model_grads_match_xla(model_cfg):
    """Level 3 (single device): loss_fn grads, Pallas backward vs default."""
    params = llama.init_params(jax.random.key(0), model_cfg)
    rng = np.random.default_rng(0)
    batch = {
        "input_ids": jnp.asarray(rng.integers(3, 500, size=(2, 16)), jnp.int32),
        "loss_mask": jnp.ones((2, 16), jnp.float32),
    }
    pcfg = _pallas_cfg(model_cfg)
    l_ref, g_ref = jax.value_and_grad(
        lambda p: loss_fn(p, batch, model_cfg)[0]
    )(params)
    l, g = jax.value_and_grad(lambda p: loss_fn(p, batch, pcfg)[0])(params)
    np.testing.assert_allclose(float(l), float(l_ref), rtol=1e-6)
    flat, _ = jax.flatten_util.ravel_pytree(g)
    flat_ref, _ = jax.flatten_util.ravel_pytree(g_ref)
    np.testing.assert_allclose(np.asarray(flat), np.asarray(flat_ref),
                               rtol=1e-4, atol=1e-6)


@pytest.mark.parametrize("mesh_cfg,expect_mlp", [
    # DP/FSDP: the Pallas path is ACTIVE (weights replicated inside the
    # island = FSDP's own per-use all-gather cost model).
    (MeshConfig(data=2, fsdp=4), "pallas"),
    # TP shards the weights the wrapper would replicate: the gate keeps the
    # GSPMD backward (running the kernel would silently de-shard TP's
    # compute while bench records "pallas").
    (MeshConfig(data=2, fsdp=2, tensor=2), "xla"),
])
def test_full_model_grads_on_dp_fsdp_tp_mesh(model_cfg, devices8, mesh_cfg,
                                             expect_mlp):
    """Level 3 (sharded): the kernels compose with DP/FSDP/TP — the
    shard_map wrapper's weight-grad psum, GSPMD constraints around it, and
    remat all active where the gate admits the kernel, and the documented
    fallback where it does not. Compares against the single-device XLA
    backward either way."""
    from ditl_tpu.ops.mlp import effective_bwd_impl

    mesh = build_mesh(mesh_cfg)
    pcfg = _pallas_cfg(model_cfg)
    assert effective_bwd_impl(
        "pallas", 8, 16, model_cfg.hidden_size, model_cfg.intermediate_size,
        (32, 128, 128), mesh,
    ) == expect_mlp
    params = llama.init_params(jax.random.key(0), model_cfg)
    rng = np.random.default_rng(1)
    batch = {
        "input_ids": jnp.asarray(rng.integers(3, 500, size=(8, 16)), jnp.int32),
        "loss_mask": jnp.ones((8, 16), jnp.float32),
    }
    l_ref, g_ref = jax.value_and_grad(
        lambda p: loss_fn(p, batch, model_cfg)[0]
    )(params)
    with mesh:
        l, g = jax.jit(jax.value_and_grad(
            lambda p: loss_fn(p, batch, pcfg, mesh=mesh)[0]
        ))(params)
    np.testing.assert_allclose(float(l), float(l_ref), rtol=1e-5)
    # Per-leaf comparison (ravel_pytree over mesh-sharded leaves misorders
    # data on this jax version — the leaves themselves are correct).
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
        ),
        g, g_ref,
    )


def test_sharded_kernel_ops_match_plain(devices8):
    """The op-level shard_map dispatch itself (no model around it):
    batch-sharded activations, replicated weights, psummed wgrads (DP/FSDP
    mesh — the gate admits the kernel here, see the TP case above)."""
    mesh = build_mesh(MeshConfig(data=4, fsdp=2))
    key = jax.random.key(0)
    h = jax.random.normal(jax.random.fold_in(key, 1), (4, 16, D), jnp.float32)
    w_gu = jax.random.normal(jax.random.fold_in(key, 2), (D, 2 * F)) * 0.05
    w_down = jax.random.normal(jax.random.fold_in(key, 3), (F, D)) * 0.05

    def mesh_loss(h, a, b):
        return jnp.sum(mlp_block(
            _identity, h, a, b, bwd_impl="pallas",
            bwd_blocks=(16, 128, 128), mesh=mesh,
        ) ** 2)

    def plain_loss(h, a, b):
        return jnp.sum(mlp_block(_identity, h, a, b, bwd_impl="xla") ** 2)

    with mesh:
        lm, gm = jax.jit(
            jax.value_and_grad(mesh_loss, argnums=(0, 1, 2))
        )(h, w_gu, w_down)
    lp, gp = jax.jit(
        jax.value_and_grad(plain_loss, argnums=(0, 1, 2))
    )(h, w_gu, w_down)
    np.testing.assert_allclose(float(lm), float(lp), rtol=1e-5)
    for a, b in zip(gm, gp):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_config_validation():
    with pytest.raises(ValueError, match="fused_gate_up"):
        ModelConfig(mlp_bwd_impl="pallas")
    with pytest.raises(ValueError, match="xla|pallas"):
        ModelConfig(mlp_bwd_impl="cuda")
    with pytest.raises(ValueError, match="MoE|dense"):
        ModelConfig(num_experts=4, fused_gate_up=True, mlp_bwd_impl="pallas")
    with pytest.raises(ValueError, match="mlp_bwd_block_n"):
        ModelConfig(fused_gate_up=True, mlp_bwd_impl="pallas",
                    mlp_bwd_block_n=-256)


def test_effective_impl_tracks_dispatch_gates(devices8):
    """The predicate bench.py records must agree with what the dispatch
    actually runs — including the mesh batch-divisibility gate."""
    from ditl_tpu.ops.mlp import effective_bwd_impl

    mesh = build_mesh(MeshConfig(data=8))
    assert effective_bwd_impl("pallas", 8, S, D, F, MLP_BLOCKS, mesh) == "pallas"
    # batch 6 % dp 8 != 0: the dispatch keeps the einsum backward.
    assert effective_bwd_impl("pallas", 6, S, D, F, MLP_BLOCKS, mesh) == "xla"
    # Tensor parallelism: the kernel would de-shard TP's weights — gated.
    tp_mesh = build_mesh(MeshConfig(data=2, tensor=4))
    assert effective_bwd_impl("pallas", 8, S, D, F, MLP_BLOCKS, tp_mesh) == "xla"
    # Untileable F without a mesh: same verdict as mlp_gu's fallback.
    assert effective_bwd_impl("pallas", 2, S, D, 96, MLP_BLOCKS) == "xla"
    assert effective_bwd_impl("xla", 8, S, D, F, MLP_BLOCKS, mesh) == "xla"


def test_bench_records_per_projection_layout():
    import bench

    # Unfused qkv with nkv*hd = 96: wk/wv cannot tile even though the
    # fused-sum shape could — the record must not claim a clean "pallas".
    cfg = ModelConfig(
        vocab_size=512, hidden_size=256, intermediate_size=128, num_layers=2,
        num_heads=4, num_kv_heads=3, head_dim=32, max_seq_len=64,
        dtype="float32", param_dtype="float32", fused_gate_up=True,
        proj_bwd_impl="pallas",
    )
    eff = bench._effective_bwd_impls(cfg, 2, 32)
    assert eff["proj"] == "mixed"  # wq/wo tile (128), wk/wv (96) do not


def test_proj_pallas_rejects_quantized_weights(model_cfg):
    from ditl_tpu.ops.quant import quantize_weights

    cfg = dataclasses.replace(model_cfg, proj_bwd_impl="pallas")
    params = quantize_weights(llama.init_params(jax.random.key(0), cfg))
    with pytest.raises(ValueError, match="float weights"):
        llama.forward(params, jnp.ones((1, 8), jnp.int32), cfg)
