"""Benchmark: fine-tune tokens/sec/chip + MFU (the BASELINE.json metric).

Runs a real Llama-style fine-tune (forward + backward + optimizer update,
bf16 compute, remat, Pallas flash attention) on the available TPU chip(s).
The reference publishes no performance numbers (SURVEY.md §6,
``BASELINE.json.published == {}``), so ``vs_baseline`` compares against this
repo's own round-1 number (33,162 tokens/sec/chip on the 350M config).

Honesty properties (round-2 fixes):
- **Distinct data every step**: batches are drawn from a fixed random bigram
  chain (next = cur*31 + eps mod V, eps uniform in [0, 8)), so the loss has a
  real floor (ln 8 ≈ 2.08 conditional entropy) the model must *learn* toward —
  a loss that fails to fall, or goes NaN, is a training-correctness regression
  this bench now catches. No batch is ever repeated.
- **MFU is reported** (analytic model FLOPs / measured step time / chip peak),
  so every round is held to hardware utilization, not just raw tokens/sec.
- **Param count is measured** from the real tree, not a label.

Prints exactly ONE JSON line to stdout; all logging goes to stderr.
``--infer`` switches to the decode benchmark (tokens/sec, lock-step
Generator, optionally ``--quantize int8``) — same one-JSON-line contract.
"""

from __future__ import annotations

import json
import statistics
import sys
import time

# Round-1 measured baseline for the default (350M fine-tune) config.
R01_BASELINE_TPS = 33162.0

# bf16 peak TFLOP/s per chip by device kind (jax.devices()[0].device_kind).
_PEAK_FLOPS = (
    ("v5 lite", 197e12),
    ("v5litepod", 197e12),
    ("v5e", 197e12),
    ("v6 lite", 918e12),
    ("v6e", 918e12),
    ("v5p", 459e12),
    ("v5", 459e12),
    ("v4", 275e12),
)


def _peak_flops(device) -> float | None:
    kind = getattr(device, "device_kind", "").lower()
    for key, peak in _PEAK_FLOPS:
        if key in kind:
            return peak
    return None


def _model_flops_per_token(cfg, seq: int) -> float:
    """Analytic matmul FLOPs per token for one forward pass (2 FLOPs/MAC).

    Counts projections, causal attention dots (average context (S+1)/2), MLP,
    and the lm head. Backward is 2x forward; remat recompute is NOT counted
    (MFU measures useful FLOPs, so remat shows up as lost utilization)."""
    d, hd = cfg.hidden_size, cfg.head_dim
    nh, nkv, f = cfg.num_heads, cfg.num_kv_heads, cfg.intermediate_size
    qkvo = 2 * d * (nh * hd) * 2 + 2 * d * (nkv * hd) * 2  # wq+wo, wk+wv
    attn = 4 * ((seq + 1) / 2) * (nh * hd)  # qk^T + pv at avg causal context
    mlp = 3 * 2 * d * f
    per_layer = qkvo + attn + mlp
    head = 2 * d * cfg.vocab_size
    return cfg.num_layers * per_layer + head


def _bigram_batches(rng, n_steps: int, batch: int, seq: int, vocab: int):
    """(n_steps, batch, seq) token windows from a fixed bigram chain: the
    data-generating process is learnable (cond. entropy ln 8) but every batch
    is distinct, so the loss falls only if training actually works."""
    import numpy as np

    # Chain over a 4096-token subset of the vocab: the transition table is
    # small enough to be visibly learned within the bench's ~140 steps, so a
    # broken optimizer shows up as a flat loss curve immediately.
    chain_vocab = min(4096, vocab)
    starts = rng.integers(0, chain_vocab, size=(n_steps, batch, 1))
    eps = rng.integers(0, 8, size=(n_steps, batch, seq - 1))
    toks = np.empty((n_steps, batch, seq), dtype=np.int64)
    toks[..., :1] = starts
    for t in range(1, seq):
        toks[..., t] = (toks[..., t - 1] * 31 + eps[..., t - 1]) % chain_vocab
    return toks.astype(np.int32)


def _model_cfg(name: str, platform: str):
    import dataclasses

    from ditl_tpu.config import ModelConfig

    if name == "350m":
        cfg = ModelConfig(
            name="bench-350m", vocab_size=32768, hidden_size=1024,
            intermediate_size=2816, num_layers=24, num_heads=16, num_kv_heads=8,
            head_dim=64, max_seq_len=1024, dtype="bfloat16",
            param_dtype="float32",
            # "dots" saves matmul outputs (recompute only elementwise in bwd)
            # and measured fastest on v5e; "none" exceeds compile memory.
            remat="dots",
            attention_impl="flash",
            # Measured on v5e (BASELINE.md r2 sweep): 1024-token tiles beat
            # the 512 default by ~4% end-to-end at seq 1024 (whole-sequence
            # tiles; fewer grid steps, no online-softmax rescale passes).
            flash_block_q=1024, flash_block_kv=1024,
            # Fused blockwise CE: was a memory-only lever in r1, now matches
            # or beats naive at 32k vocab after the r2 sweep.
            loss_impl="fused", loss_block_tokens=2048,
        )
        batch, seq, optimizer = 8, 1024, "adamw"
    elif name == "1b3":
        # Closest 1-chip proxy to the 8B/70B north-star configs (VERDICT r1
        # item 4): bf16 params + adafactor (factored second moment) + fused
        # blockwise CE keep a ~1.3B model + grads + optimizer inside one
        # v5e's 16G HBM at seq 2048.
        cfg = ModelConfig(
            name="bench-1b3", vocab_size=32768, hidden_size=2048,
            intermediate_size=5632, num_layers=24, num_heads=16, num_kv_heads=8,
            head_dim=128, max_seq_len=2048, dtype="bfloat16",
            param_dtype="bfloat16", remat="dots", attention_impl="flash",
            flash_block_q=1024, flash_block_kv=1024,
            loss_impl="fused", loss_block_tokens=2048,
        )
        batch, seq, optimizer = 4, 2048, "adafactor"
    else:
        raise SystemExit(f"unknown --model {name!r} (350m|1b3)")
    if platform != "tpu":  # CPU smoke path: shrink everything
        cfg = dataclasses.replace(cfg, num_layers=2, hidden_size=256,
                                  intermediate_size=688, vocab_size=4096,
                                  num_heads=4, num_kv_heads=2, head_dim=64)
        batch, seq = 2, 128
    return cfg, batch, seq, optimizer


def bench_infer(quantize: bool, kv_quant: bool = False) -> int:
    import jax

    from ditl_tpu.config import ModelConfig
    from ditl_tpu.data.tokenizer import ByteTokenizer
    from ditl_tpu.infer.engine import GenerateConfig, Generator
    from ditl_tpu.models import llama

    platform = jax.devices()[0].platform
    cfg = ModelConfig(
        name="bench-350m", vocab_size=32768, hidden_size=1024,
        intermediate_size=2816, num_layers=24, num_heads=16, num_kv_heads=8,
        head_dim=64, max_seq_len=1024, dtype="bfloat16", param_dtype="float32",
        attention_impl="xla", kv_cache_dtype="int8" if kv_quant else "",
    )
    batch, max_new = (8, 128) if platform == "tpu" else (2, 16)
    if platform != "tpu":
        import dataclasses

        cfg = dataclasses.replace(cfg, num_layers=2, hidden_size=256,
                                  intermediate_size=688, vocab_size=4096)
    params = llama.init_params(jax.random.key(0), cfg)
    params_m = llama.num_params(params) / 1e6
    if quantize:
        from ditl_tpu.ops.quant import quantize_weights

        params = quantize_weights(params)
    tok = ByteTokenizer()
    prompts = [[tok.bos_id] + list(range(10, 70))] * batch
    gen = GenerateConfig(max_new_tokens=max_new, temperature=1.0, seed=1)
    g = Generator(params, cfg, tok)
    g.generate_tokens(prompts, gen)  # compile
    times = []
    for _ in range(3):
        t = time.perf_counter()
        g.generate_tokens(prompts, gen)
        times.append(time.perf_counter() - t)
    dt = statistics.median(times)
    print(json.dumps({
        "metric": "decode tokens/sec (Llama-style %dM, batch %d%s%s)" % (
            round(params_m), batch, ", int8" if quantize else "",
            ", int8-kv" if kv_quant else ""),
        "value": round(max_new * batch / dt, 1),
        "unit": "tokens/sec",
        "vs_baseline": 1.0,
        "params_m": round(params_m, 1),
        "platform": platform,
    }))
    return 0


def main(model_name: str = "350m") -> int:
    import jax
    import numpy as np

    from ditl_tpu.config import MeshConfig, TrainConfig
    from ditl_tpu.data.loader import make_global_batch
    from ditl_tpu.models import llama
    from ditl_tpu.runtime.mesh import build_mesh
    from ditl_tpu.train.state import create_train_state
    from ditl_tpu.train.step import make_multi_step

    n_chips = len(jax.devices())
    platform = jax.devices()[0].platform
    print(f"bench: {n_chips} {platform} device(s)", file=sys.stderr)

    cfg, batch, seq, optimizer = _model_cfg(model_name, platform)
    tcfg = TrainConfig(total_steps=1000, warmup_steps=10, optimizer=optimizer)
    mesh = build_mesh(MeshConfig())

    chunk = 20 if platform == "tpu" else 3
    n_windows = 6 if platform == "tpu" else 2
    rng = np.random.default_rng(0)
    # One stacked (chunk, B, S) window per timed iteration — every step of
    # every window sees distinct, learnable data (see _bigram_batches).
    all_tokens = _bigram_batches(rng, chunk * (n_windows + 1), batch, seq,
                                 cfg.vocab_size)
    ones = np.ones((chunk, batch, seq), np.float32)
    segs = np.ones((chunk, batch, seq), np.int32)
    pos = np.tile(np.arange(seq, dtype=np.int32), (chunk, batch, 1))

    def window(i):
        toks = all_tokens[i * chunk:(i + 1) * chunk]
        return {
            "input_ids": toks,
            "loss_mask": ones,
            "labels": np.zeros((chunk, batch), np.int32),
            "segment_ids": segs,
            "positions": pos,
        }

    example = {k: v[0] for k, v in window(0).items()}
    gb = make_global_batch(mesh, example)

    # The whole window of `chunk` optimizer steps is ONE compiled program
    # (lax.scan over stacked batches, train/step.make_multi_step) — the device
    # runs autonomously with zero host dispatch between steps; the same
    # mechanism the trainer exposes as `train.steps_per_call`.
    t0 = time.perf_counter()
    state = create_train_state(jax.random.key(0), cfg, tcfg)
    params_m = llama.num_params(state.params) / 1e6
    multi = make_multi_step(cfg, tcfg, mesh, gb, chunk)
    state, metrics = multi(state, make_global_batch(mesh, window(0)))
    loss_start = float(metrics["loss"][0])
    float(metrics["loss"][-1])  # full host sync (block_until_ready alone does
    # not guarantee completion through remote-device transports)
    print(f"bench: compile+first window {time.perf_counter() - t0:.1f}s "
          f"({params_m:.1f}M params)", file=sys.stderr)

    # Pre-stage every window on device before timing: distinct data per step
    # stays honest, while the host->device copy is excluded — the trainer's
    # prefetch pipeline (data/loader.py) overlaps it with compute in real runs.
    staged = [make_global_batch(mesh, window(i)) for i in range(1, n_windows + 1)]
    jax.block_until_ready(staged)
    times = []
    for stacked in staged:
        t = time.perf_counter()
        state, metrics = multi(state, stacked)
        float(metrics["loss"][-1])  # sync
        times.append((time.perf_counter() - t) / chunk)
    p50 = statistics.median(times)
    final_loss = float(metrics["loss"][-1])
    tokens_per_step = batch * seq
    tps_chip = tokens_per_step / p50 / n_chips
    print(f"bench: step_time_p50={p50 * 1e3:.1f}ms "
          f"loss {loss_start:.4f} -> {final_loss:.4f}", file=sys.stderr)
    if not (final_loss < loss_start and np.isfinite(final_loss)):
        print("bench: WARNING loss did not fall — training regression?",
              file=sys.stderr)

    result = {
        "metric": "fine-tune tokens/sec/chip (Llama-style %dM, bf16, seq %d)"
                  % (round(params_m), seq),
        "value": round(tps_chip, 1),
        "unit": "tokens/sec/chip",
        "vs_baseline": round(tps_chip / R01_BASELINE_TPS, 4)
                       if (model_name == "350m" and platform == "tpu") else 1.0,
        "step_time_p50_ms": round(p50 * 1e3, 2),
        "n_chips": n_chips,
        "platform": platform,
        "params_m": round(params_m, 1),
        "loss_start": round(loss_start, 4),
        "final_loss": round(final_loss, 4),
    }
    peak = _peak_flops(jax.devices()[0])
    if peak:
        train_flops_per_token = 3 * _model_flops_per_token(cfg, seq)
        result["mfu"] = round(tps_chip * train_flops_per_token / peak, 4)
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    import argparse

    parser = argparse.ArgumentParser(prog="bench.py")
    parser.add_argument("--infer", action="store_true",
                        help="decode benchmark instead of the fine-tune one")
    parser.add_argument("--model", choices=("350m", "1b3"), default="350m",
                        help="fine-tune bench model size")
    parser.add_argument("--quantize", choices=("int8",), default=None,
                        help="weight-only quantization (only with --infer)")
    parser.add_argument("--kv-quant", choices=("int8",), default=None,
                        help="int8 KV-cache quantization (only with --infer)")
    args = parser.parse_args()
    if (args.quantize or args.kv_quant) and not args.infer:
        parser.error("--quantize/--kv-quant require --infer")
    if args.infer:
        sys.exit(bench_infer(quantize=args.quantize == "int8",
                             kv_quant=args.kv_quant == "int8"))
    sys.exit(main(args.model))
