"""Benchmark: fine-tune tokens/sec/chip (the BASELINE.json metric).

Runs a real Llama-style fine-tune step (forward + backward + AdamW update,
bf16 compute / f32 masters, remat, sequence packing shapes) on the available
TPU chip(s) and reports the BASELINE.json headline metric. The reference
publishes no performance numbers (SURVEY.md §6, ``BASELINE.json.published ==
{}``), so ``vs_baseline`` is reported against the forward baseline defined in
BASELINE.md — 1.0 until a prior round's number exists to compare against.

Prints exactly ONE JSON line to stdout; all logging goes to stderr.
``--infer`` switches to the decode benchmark (tokens/sec, lock-step
Generator, optionally ``--quantize int8``) — same one-JSON-line contract.
"""

from __future__ import annotations

import json
import statistics
import sys
import time


def bench_infer(quantize: bool, kv_quant: bool = False) -> int:
    import jax

    from ditl_tpu.config import ModelConfig
    from ditl_tpu.data.tokenizer import ByteTokenizer
    from ditl_tpu.infer.engine import GenerateConfig, Generator
    from ditl_tpu.models import llama

    platform = jax.devices()[0].platform
    cfg = ModelConfig(
        name="bench-420m", vocab_size=32768, hidden_size=1024,
        intermediate_size=2816, num_layers=24, num_heads=16, num_kv_heads=8,
        head_dim=64, max_seq_len=1024, dtype="bfloat16", param_dtype="float32",
        attention_impl="xla", kv_cache_dtype="int8" if kv_quant else "",
    )
    batch, max_new = (8, 128) if platform == "tpu" else (2, 16)
    if platform != "tpu":
        import dataclasses

        cfg = dataclasses.replace(cfg, num_layers=2, hidden_size=256,
                                  intermediate_size=688, vocab_size=4096)
    params = llama.init_params(jax.random.key(0), cfg)
    if quantize:
        from ditl_tpu.ops.quant import quantize_weights

        params = quantize_weights(params)
    tok = ByteTokenizer()
    prompts = [[tok.bos_id] + list(range(10, 70))] * batch
    gen = GenerateConfig(max_new_tokens=max_new, temperature=1.0, seed=1)
    g = Generator(params, cfg, tok)
    g.generate_tokens(prompts, gen)  # compile
    times = []
    for _ in range(3):
        t = time.perf_counter()
        g.generate_tokens(prompts, gen)
        times.append(time.perf_counter() - t)
    dt = statistics.median(times)
    print(json.dumps({
        "metric": "decode tokens/sec (Llama-style 420M, batch %d%s%s)" % (
            batch, ", int8" if quantize else "",
            ", int8-kv" if kv_quant else ""),
        "value": round(max_new * batch / dt, 1),
        "unit": "tokens/sec",
        "vs_baseline": 1.0,
        "platform": platform,
    }))
    return 0


def main() -> int:
    import jax
    import numpy as np

    import jax.numpy as jnp

    from ditl_tpu.config import MeshConfig, ModelConfig, TrainConfig
    from ditl_tpu.data.loader import make_global_batch
    from ditl_tpu.runtime.mesh import build_mesh
    from ditl_tpu.train.state import create_train_state
    from ditl_tpu.train.step import make_multi_step

    n_chips = len(jax.devices())
    platform = jax.devices()[0].platform
    print(f"bench: {n_chips} {platform} device(s)", file=sys.stderr)

    # ~420M-param Llama-style model: big enough to exercise the MXU, small
    # enough that params+adam state fit a single v5e chip's HBM.
    cfg = ModelConfig(
        name="bench-420m",
        vocab_size=32768,
        hidden_size=1024,
        intermediate_size=2816,
        num_layers=24,
        num_heads=16,
        num_kv_heads=8,
        head_dim=64,
        max_seq_len=1024,
        dtype="bfloat16",
        param_dtype="float32",
        # "dots" saves matmul outputs (recompute only elementwise in bwd) and
        # measured fastest on v5e; "none" exceeds this chip's compile memory.
        remat="dots",
        # Pallas FlashAttention kernel: +42% over the XLA einsum path on v5e
        # (31.9k vs 22.5k tokens/sec/chip at batch 8, seq 1024).
        attention_impl="flash",
    )
    batch, seq = (8, 1024) if platform == "tpu" else (2, 128)
    if platform != "tpu":  # CPU smoke path: shrink everything
        import dataclasses

        cfg = dataclasses.replace(cfg, num_layers=2, hidden_size=256,
                                  intermediate_size=688, vocab_size=4096)
    tcfg = TrainConfig(total_steps=1000, warmup_steps=10)
    mesh = build_mesh(MeshConfig())

    rng = np.random.default_rng(0)
    host_batch = {
        "input_ids": rng.integers(3, cfg.vocab_size, size=(batch, seq)).astype(np.int32),
        "loss_mask": np.ones((batch, seq), np.float32),
        "labels": np.zeros((batch,), np.int32),
        "segment_ids": np.ones((batch, seq), np.int32),
        "positions": np.tile(np.arange(seq, dtype=np.int32), (batch, 1)),
    }
    gb = make_global_batch(mesh, host_batch)

    # The whole window of `chunk` optimizer steps is ONE compiled program
    # (lax.scan over stacked batches, train/step.make_multi_step) — the device
    # runs autonomously with zero host dispatch between steps; the same
    # mechanism the trainer exposes as `train.steps_per_call`.
    chunk = 20 if platform == "tpu" else 3
    stacked = jax.tree.map(
        lambda x: jnp.stack([x] * chunk, axis=0), gb
    )
    t0 = time.perf_counter()
    state = create_train_state(jax.random.key(0), cfg, tcfg)
    multi = make_multi_step(cfg, tcfg, mesh, gb, chunk)
    state, metrics = multi(state, stacked)  # compile + first window
    float(metrics["loss"][-1])  # full host sync (block_until_ready alone does
    # not guarantee completion through remote-device transports)
    print(f"bench: compile+first window {time.perf_counter() - t0:.1f}s", file=sys.stderr)

    n_windows = 6 if platform == "tpu" else 2
    times = []
    for _ in range(n_windows):
        t = time.perf_counter()
        state, metrics = multi(state, stacked)
        float(metrics["loss"][-1])  # sync
        times.append((time.perf_counter() - t) / chunk)
    p50 = statistics.median(times)
    metrics = {k: v[-1] for k, v in metrics.items()}
    tokens_per_step = batch * seq
    tps_chip = tokens_per_step / p50 / n_chips
    print(
        f"bench: step_time_p50={p50 * 1e3:.1f}ms loss={float(metrics['loss']):.4f}",
        file=sys.stderr,
    )

    result = {
        "metric": "fine-tune tokens/sec/chip (Llama-style 420M, bf16, seq 1024)",
        "value": round(tps_chip, 1),
        "unit": "tokens/sec/chip",
        "vs_baseline": 1.0,
        "step_time_p50_ms": round(p50 * 1e3, 2),
        "n_chips": n_chips,
        "platform": platform,
        "final_loss": round(float(metrics["loss"]), 4),
    }
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    import argparse

    parser = argparse.ArgumentParser(prog="bench.py")
    parser.add_argument("--infer", action="store_true",
                        help="decode benchmark instead of the fine-tune one")
    parser.add_argument("--quantize", choices=("int8",), default=None,
                        help="weight-only quantization (only with --infer)")
    parser.add_argument("--kv-quant", choices=("int8",), default=None,
                        help="int8 KV-cache quantization (only with --infer)")
    args = parser.parse_args()
    if (args.quantize or args.kv_quant) and not args.infer:
        parser.error("--quantize/--kv-quant require --infer")
    if args.infer:
        sys.exit(bench_infer(quantize=args.quantize == "int8",
                             kv_quant=args.kv_quant == "int8"))
    sys.exit(main())
