"""Benchmark: fine-tune tokens/sec/chip + MFU (the BASELINE.json metric).

Runs a real Llama-style fine-tune (forward + backward + optimizer update,
bf16 compute, remat, Pallas flash attention) on the available TPU chip(s).
The reference publishes no performance numbers (SURVEY.md §6,
``BASELINE.json.published == {}``), so ``vs_baseline`` compares against this
repo's own prior rounds: the default config is the 1.27B north-star proxy
(56% MFU on v5e) anchored to round 2's judge-verified 14,160 tokens/sec/chip;
``--model 350m`` keeps the round-1 continuity config (anchor 33,162).

Honesty properties (round-2 fixes):
- **Distinct data every step**: batches are drawn from a fixed random bigram
  chain (next = cur*31 + eps mod V, eps uniform in [0, 8)), so the loss has a
  real floor (ln 8 ≈ 2.08 conditional entropy) the model must *learn* toward —
  a loss that fails to fall, or goes NaN, is a training-correctness regression
  this bench now catches. No batch is ever repeated.
- **MFU is reported** (analytic model FLOPs / measured step time / chip peak),
  so every round is held to hardware utilization, not just raw tokens/sec.
- **Param count is measured** from the real tree, not a label.

Prints exactly ONE JSON line to stdout; all logging goes to stderr.
``--infer`` switches to the decode benchmark (tokens/sec, lock-step
Generator, optionally ``--quantize int8``) — same one-JSON-line contract.
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import time

# Round-over-round anchors, both measured on this project's 1x v5e chip and
# re-verified by the round-2 judge: the 1.27B north-star proxy (r2) and the
# 350M config (r1).
R02_1B3_BASELINE_TPS = 14160.0
R01_350M_BASELINE_TPS = 33162.0


def _chaos_result() -> dict:
    """`{"chaos": ...}` when a fault plane is armed (bench --chaos), else
    empty — a perf row measured under injected faults is only
    interpretable with the injected-fault counts attached (ISSUE 5)."""
    from ditl_tpu.chaos import injected_summary

    summary = injected_summary()
    return {"chaos": summary} if summary is not None else {}


def _incidents_now() -> int:
    """Run-start baseline for `_incident_result` — captured at the top of
    every bench run so in-process sweep cells never inherit earlier
    cells' incident counts (incidents_total() is process-cumulative)."""
    from ditl_tpu.telemetry.incident import incidents_total

    return incidents_total()


def _incident_result(since: int = 0) -> dict:
    """`{"incidents": N}` — bundles assembled by any incident manager in
    this process during THIS run (delta vs the `since` baseline, ISSUE 10
    satellite). ALWAYS embedded, zero included: telemetry/perf_compare.py
    treats new incidents on the new side as a "now fails"-class
    regression, so a perf PR that wins its numbers by provoking anomaly
    storms fails the gate — and that needs healthy baselines to carry an
    explicit 0."""
    from ditl_tpu.telemetry.incident import incidents_total

    return {"incidents": max(0, incidents_total() - since)}


_ANALYSIS_CLEAN: bool | None = None


def _analysis_clean() -> bool:
    """True when the invariant lint (`python -m ditl_tpu.analysis`,
    ISSUE 11) passes over the installed package. Computed once per
    process — the tree does not change mid-bench — and stamped on every
    row so `perf_compare` treats a newly-dirty tree as a "now fails"
    regression, like incidents. An analyzer crash stamps False
    (conservative: a gate that cannot run must not read as clean)."""
    global _ANALYSIS_CLEAN
    if _ANALYSIS_CLEAN is None:
        try:
            import ditl_tpu
            from ditl_tpu.analysis import run as _run_lint

            pkg_dir = os.path.dirname(os.path.abspath(ditl_tpu.__file__))
            _ANALYSIS_CLEAN = not _run_lint(pkg_dir)
        except Exception:  # noqa: BLE001 - the stamp must never kill a bench
            _ANALYSIS_CLEAN = False
    return _ANALYSIS_CLEAN


def _record_meta() -> dict:
    """Schema + provenance stamp for every bench JSON row (ISSUE 7
    satellite): records are versioned and name the code revision they were
    measured at, so `perf_compare` can refuse cross-schema diffs and a row
    pasted into BASELINE.md stays attributable. `analysis_clean` rides
    along (ISSUE 11) so perf artifacts also certify the invariant lint."""
    from ditl_tpu.telemetry.perf import SWEEP_SCHEMA, git_rev

    return {"schema": SWEEP_SCHEMA, "git_rev": git_rev(),
            "analysis_clean": _analysis_clean()}

# bf16 peak TFLOP/s per chip, EXACT device_kind match (lowercased). A
# substring table silently mis-scaled MFU when device_kind strings
# reshuffled; unknown kinds now warn loudly and omit MFU instead of
# guessing (VERDICT r2 weak #5).
_PEAK_FLOPS = {
    "tpu v5 lite": 197e12,
    "tpu v5e": 197e12,
    "tpu v5litepod": 197e12,
    "tpu v6 lite": 918e12,
    "tpu v6e": 918e12,
    "tpu v5p": 459e12,
    "tpu v5": 459e12,
    "tpu v4": 275e12,
    "tpu v4 lite": 138e12,
}


def _peak_flops(device) -> float | None:
    kind = getattr(device, "device_kind", "").lower().strip()
    peak = _PEAK_FLOPS.get(kind)
    if peak is None and kind.startswith("tpu"):
        print(
            f"bench: WARNING unknown TPU device_kind {kind!r} — peak FLOP/s "
            f"unknown, MFU omitted (add it to bench._PEAK_FLOPS)",
            file=sys.stderr,
        )
    return peak


def _model_flops_per_token(cfg, seq: int) -> float:
    """Analytic matmul FLOPs per token for one forward pass (2 FLOPs/MAC).

    Counts projections, causal attention dots (average context (S+1)/2), MLP,
    and the lm head. Backward is 2x forward; remat recompute is NOT counted
    (MFU measures useful FLOPs, so remat shows up as lost utilization)."""
    d, hd = cfg.hidden_size, cfg.head_dim
    nh, nkv, f = cfg.num_heads, cfg.num_kv_heads, cfg.intermediate_size
    qkvo = 2 * d * (nh * hd) * 2 + 2 * d * (nkv * hd) * 2  # wq+wo, wk+wv
    attn = 4 * ((seq + 1) / 2) * (nh * hd)  # qk^T + pv at avg causal context
    mlp = 3 * 2 * d * f
    per_layer = qkvo + attn + mlp
    head = 2 * d * cfg.vocab_size
    return cfg.num_layers * per_layer + head


def _bigram_batches(rng, n_steps: int, batch: int, seq: int, vocab: int):
    """(n_steps, batch, seq) token windows from a fixed bigram chain: the
    data-generating process is learnable (cond. entropy ln 8) but every batch
    is distinct, so the loss falls only if training actually works."""
    import numpy as np

    # Chain over a 4096-token subset of the vocab: the transition table is
    # small enough to be visibly learned within the bench's ~140 steps, so a
    # broken optimizer shows up as a flat loss curve immediately.
    chain_vocab = min(4096, vocab)
    starts = rng.integers(0, chain_vocab, size=(n_steps, batch, 1))
    eps = rng.integers(0, 8, size=(n_steps, batch, seq - 1))
    toks = np.empty((n_steps, batch, seq), dtype=np.int64)
    toks[..., :1] = starts
    for t in range(1, seq):
        toks[..., t] = (toks[..., t - 1] * 31 + eps[..., t - 1]) % chain_vocab
    return toks.astype(np.int32)


def _model_cfg(name: str, platform: str):
    import dataclasses

    from ditl_tpu.config import ModelConfig

    if name == "350m":
        cfg = ModelConfig(
            name="bench-350m", vocab_size=32768, hidden_size=1024,
            intermediate_size=2816, num_layers=24, num_heads=16, num_kv_heads=8,
            head_dim=64, max_seq_len=1024, dtype="bfloat16",
            param_dtype="float32",
            # "dots" saves matmul outputs (recompute only elementwise in bwd)
            # and measured fastest on v5e; "none" exceeds compile memory.
            remat="dots",
            attention_impl="flash",
            # Measured on v5e (BASELINE.md r2 sweep): 1024-token tiles beat
            # the 512 default by ~4% end-to-end at seq 1024 (whole-sequence
            # tiles; fewer grid steps, no online-softmax rescale passes).
            flash_block_q=1024, flash_block_kv=1024,
            # Fused blockwise CE: was a memory-only lever in r1, now matches
            # or beats naive at 32k vocab after the r2 sweep.
            loss_impl="fused", loss_block_tokens=2048,
        )
        batch, seq, optimizer = 8, 1024, "adamw"
    elif name == "1b3":
        # Closest 1-chip proxy to the 8B/70B north-star configs (VERDICT r1
        # item 4): bf16 params + adafactor (factored second moment) + fused
        # blockwise CE keep a ~1.3B model + grads + optimizer inside one
        # v5e's 16G HBM at seq 2048.
        cfg = ModelConfig(
            name="bench-1b3", vocab_size=32768, hidden_size=2048,
            intermediate_size=5632, num_layers=24, num_heads=16, num_kv_heads=8,
            head_dim=128, max_seq_len=2048, dtype="bfloat16",
            param_dtype="bfloat16",
            # r5: fused gate|up layout + the dots_inputs remat policy
            # (save the norm outputs feeding the projections) measured
            # -19 ms/step TOGETHER on v5e (582 -> 563; each alone is
            # noise) — the first bite out of the r4 roofline's backward-
            # scheduling residual (experiments/bwd_levers.py receipts in
            # BASELINE.md). Same math: fused layout is bit-exact.
            remat="dots_inputs", fused_gate_up=True,
            attention_impl="flash",
            flash_block_q=1024, flash_block_kv=1024,
            # r3 sweep: CE block 4096 is +0.5% over 2048 (8192 matches
            # 4096); 2048-token flash tiles exceed v5e's 16M scoped VMEM,
            # remat=attn loses 6%, batch 6/8 at s2048 exceed HBM. The
            # b8 x s1024 SHAPE reaches 60.2% MFU (BASELINE.md) but changes
            # the workload, so the pinned config keeps s2048 for an honest
            # round-over-round vs_baseline.
            loss_impl="fused", loss_block_tokens=4096,
        )
        batch, seq, optimizer = 4, 2048, "adafactor"
    else:
        raise SystemExit(f"unknown --model {name!r} (350m|1b3)")
    if platform != "tpu":  # CPU smoke path: shrink everything
        cfg = dataclasses.replace(cfg, num_layers=2, hidden_size=256,
                                  intermediate_size=688, vocab_size=4096,
                                  num_heads=4, num_kv_heads=2, head_dim=64)
        batch, seq = 2, 128
    return cfg, batch, seq, optimizer


def _bigram_tokens(rng, batch: int, n: int, vocab: int):
    """(batch, n) windows of a PEAKED bigram chain over tokens
    [16, vocab): next = 16 + ((cur-16) + 17 + eps) mod (vocab-16), with
    eps = 0 w.p. 0.65 (the mode a trained model locks onto). Predictable
    to a model that learned the domain, but trajectories from fresh random
    starts share almost no verbatim n-grams — the regime where
    prompt-lookup speculation cannot draft and a draft MODEL can. The
    chain is AFFINE (+17), not multiplicative: the Carmichael function of
    a highly-composite modulus is tiny (lambda(1008) = 12), so x -> g*x
    chains collapse into cycles shorter than one generation and become
    lookup's best case."""
    import numpy as np

    m = vocab - 16
    starts = rng.integers(0, m, size=(batch,))
    eps = rng.choice(8, size=(batch, n - 1), p=[0.65] + [0.05] * 7)
    x = np.empty((batch, n), np.int64)
    x[:, 0] = starts
    for t in range(1, n):
        x[:, t] = (x[:, t - 1] + 17 + eps[:, t - 1]) % m
    return (16 + x).astype(np.int32)


def _domain_finetune(params, cfg, n_steps: int, batch: int, seq: int,
                     make_batch, label: str):
    """Briefly fine-tune ``params`` on batches from ``make_batch(rng)`` —
    shared trainer harness for the workload-specific tune-ups below."""
    import jax
    import numpy as np

    from ditl_tpu.config import MeshConfig, TrainConfig
    from ditl_tpu.data.loader import make_global_batch
    from ditl_tpu.runtime.mesh import build_mesh
    from ditl_tpu.train.state import create_train_state
    from ditl_tpu.train.step import make_train_step

    tcfg = TrainConfig(total_steps=max(n_steps, 2), warmup_steps=1,
                       learning_rate=1e-3, optimizer="adamw")
    mesh = build_mesh(MeshConfig())
    rng = np.random.default_rng(1)
    host = {
        "input_ids": np.zeros((batch, seq), np.int32),
        "loss_mask": np.ones((batch, seq), np.float32),
        "labels": np.zeros((batch,), np.int32),
        "segment_ids": np.ones((batch, seq), np.int32),
        "positions": np.tile(np.arange(seq, dtype=np.int32), (batch, 1)),
    }
    gb = make_global_batch(mesh, host)
    state = create_train_state(jax.random.key(7), cfg, tcfg)
    state = state.replace(params=params)
    step = make_train_step(cfg, tcfg, mesh, gb)
    for _ in range(n_steps):
        host["input_ids"] = make_batch(rng)
        state, metrics = step(state, make_global_batch(mesh, host))
    loss = float(metrics["loss"])
    print(f"bench: {label} fine-tune {n_steps} steps, loss {loss:.3f}",
          file=sys.stderr)
    return state.params


def _bigram_finetune(params, cfg, vocab: int, n_steps: int, batch: int,
                     seq: int):
    return _domain_finetune(
        params, cfg, n_steps, batch, seq,
        lambda rng: _bigram_tokens(rng, batch, seq, vocab), "bigram",
    )


def _repetitive_finetune(params, cfg, pattern, n_steps: int, batch: int,
                         seq: int):
    """Briefly fine-tune the bench model on sequences that repeat
    ``pattern`` — the reproducible stand-in for the repetitive-continuation
    serving regime (code edits, RAG quoting, structured output) where
    prompt-lookup speculation pays. Returns the tuned params (bf16/f32 as
    configured). ~n_steps x one train step of wall clock."""
    import numpy as np

    p = np.asarray(pattern, np.int32)

    def make_batch(rng):
        offs = rng.integers(0, len(p), size=batch)
        return np.stack([
            np.resize(np.roll(p, -int(o)), seq) for o in offs
        ]).astype(np.int32)

    return _domain_finetune(params, cfg, n_steps, batch, seq, make_batch,
                            "repetitive")


def bench_infer(engine: str = "lockstep", cache: str = "contiguous",
                quantize: bool = False, kv_quant: bool = False,
                speculative: bool = False, workload: str = "random",
                slots: int = 8, decode_chunk: int = 16,
                page_size: int = 256, moe: bool = False,
                prompt_len: int = 0, max_new: int = 0,
                temperature: float = 0.0, guided: str = "",
                spec_draft: bool = False, pipeline: bool = False,
                admission: str = "reserve", pages: int = 0,
                compile_cache_dir: str = "") -> int:
    """Decode/serving benchmark — one JSON line. Every serving claim in
    BASELINE.md is reproducible from here: ``--engine continuous`` ticks the
    production slot engine (``--cache paged`` for the page pool + Pallas
    paged-attention kernel, ``--kv-quant int8`` for int8 pools,
    ``--speculative`` for speculative ticks), ``--infer-workload repetitive``
    fine-tunes briefly on a repeating pattern and prompts with it — the
    regime where prompt-lookup acceptance pays (the A/B against the same
    command without ``--speculative`` is the speculation headline)."""
    import dataclasses

    import jax

    from ditl_tpu.config import ModelConfig
    from ditl_tpu.data.tokenizer import ByteTokenizer
    from ditl_tpu.models import llama
    from ditl_tpu.runtime.distributed import enable_compile_cache

    enable_compile_cache(compile_cache_dir)
    _inc0 = _incidents_now()
    platform = jax.devices()[0].platform
    cfg = ModelConfig(
        name="bench-moe" if moe else "bench-350m", vocab_size=32768,
        hidden_size=1024,
        # MoE variant: 8 experts, top-2 — per-token FLOPs comparable to the
        # dense config, ~2.3B total params (the Mixtral shape at bench
        # scale; BASELINE.json north star Mixtral-8x7B).
        intermediate_size=1408 if moe else 2816,
        num_experts=8 if moe else 0,
        num_experts_per_tok=2 if moe else 0,
        num_layers=24, num_heads=16, num_kv_heads=8,
        head_dim=64,
        max_seq_len=max(1024, prompt_len + (max_new or 128) + 1),
        dtype="bfloat16", param_dtype="float32",
        attention_impl="xla", kv_cache_dtype="int8" if kv_quant else "",
    )
    batch = slots if platform == "tpu" else 2
    max_new_explicit = bool(max_new)  # 0 = not passed on the CLI
    max_new = max_new or (128 if platform == "tpu" else 16)
    if platform != "tpu":
        cfg = dataclasses.replace(cfg, num_layers=2, hidden_size=256,
                                  intermediate_size=688, vocab_size=4096)
        page_size = min(page_size, 64)
        max_new = min(max_new, 16)
    params = llama.init_params(jax.random.key(0), cfg)
    params_m = llama.num_params(params) / 1e6
    import numpy as np

    rng = np.random.default_rng(3)
    if workload == "repetitive":
        # A fixed 48-token pattern; prompts repeat it (~256 tokens on TPU)
        # and the briefly-tuned model continues it — acceptance comes from
        # the WORKLOAD's self-similarity, with generation quality pinned by
        # actual training, not by hand-feeding the drafter.
        pattern = rng.integers(16, min(4096, cfg.vocab_size),
                               size=48).tolist()
        n_steps, seq = (40, 512) if platform == "tpu" else (4, 64)
        params = _repetitive_finetune(params, cfg, pattern, n_steps,
                                      batch, seq)
        plen = prompt_len or (256 if platform == "tpu" else 32)
        if not max_new_explicit:
            max_new = 192 if platform == "tpu" else 16
        prompts = []
        for i in range(batch):
            roll = pattern[i % len(pattern):] + pattern[: i % len(pattern)]
            prompts.append((roll * (plen // len(roll) + 1))[:plen])
    elif workload == "bigram":
        # Draft-model speculation's own turf: the peaked bigram domain is
        # PREDICTABLE to a model trained on it, but prompts are NOVEL
        # trajectories (fresh rng) sharing almost no verbatim n-grams with
        # themselves or their continuations — prompt-lookup has nothing to
        # draft from, so its acceptance collapses while a domain-tuned
        # draft model keeps agreeing with the target.
        # ~4080 transition rows x ~400 visits each: enough for the 350M
        # target AND the 12M drafter to put their argmax on the chain's
        # mode, which is what deterministic-proposal rejection sampling
        # pays for (acceptance/token ~= p_T(draft)).
        chain_vocab = min(4096, cfg.vocab_size)
        n_steps, seq = (400, 512) if platform == "tpu" else (4, 64)
        params = _bigram_finetune(params, cfg, chain_vocab, n_steps,
                                  batch, seq)
        plen = prompt_len or (256 if platform == "tpu" else 32)
        if not max_new_explicit:
            max_new = 192 if platform == "tpu" else 16
        if temperature <= 0.0:
            raise SystemExit(
                "--infer-workload bigram needs --temperature > 0: the "
                "greedy argmax path of a deterministic chain self-cycles "
                "(period <= lambda(m)), turning the workload into prompt-"
                "lookup's best case and invalidating the draft-vs-lookup "
                "split it exists to measure (BASELINE.md r4)"
            )
        novel = np.random.default_rng(1234)  # disjoint from training rng(1)
        prompts = _bigram_tokens(novel, batch, plen, chain_vocab).tolist()
    elif workload == "random":
        plen = prompt_len or 61
        prompts = [
            [1] + rng.integers(4, min(4096, cfg.vocab_size),
                               size=plen - 1).tolist()
            for _ in range(batch)
        ]
    else:
        raise SystemExit(f"unknown --infer-workload {workload!r}")
    if spec_draft and (not speculative or engine != "continuous"):
        raise SystemExit(
            "--spec-draft needs --speculative --engine continuous"
        )
    draft_params = draft_cfg = None
    if spec_draft:
        # A ~10x-smaller DRAFT model for model-based speculation. On the
        # repetitive workload it is fine-tuned on the same pattern as the
        # target, so its greedy predictions track the target's — the
        # acceptance lever that works off workload PREDICTABILITY rather
        # than verbatim self-similarity (prompt-lookup's requirement).
        draft_cfg = dataclasses.replace(
            cfg, name="bench-draft", hidden_size=512, intermediate_size=1408,
            num_layers=6, num_heads=8, num_kv_heads=4,
            num_experts=0, num_experts_per_tok=0,
        )
        if platform != "tpu":
            draft_cfg = dataclasses.replace(
                draft_cfg, num_layers=1, hidden_size=128,
                intermediate_size=344,
            )
        draft_params = llama.init_params(jax.random.key(11), draft_cfg)
        if workload == "repetitive":
            draft_params = _repetitive_finetune(
                draft_params, draft_cfg, pattern, n_steps, batch, seq
            )
        elif workload == "bigram":
            # SAME chain space as the target's tune-up above — the whole
            # acceptance lever is the two models agreeing on the domain.
            draft_params = _bigram_finetune(
                draft_params, draft_cfg, chain_vocab, n_steps, batch, seq,
            )
    if quantize:
        from ditl_tpu.ops.quant import quantize_weights

        params = quantize_weights(params)
    tok = ByteTokenizer()

    if engine == "continuous":
        from ditl_tpu.infer.continuous import ContinuousEngine
        from ditl_tpu.infer.engine import GenerateConfig

        grammar = None
        if guided:
            # "--guided json" = the json_object grammar; anything else is a
            # regex. "--guided '(.|\n)*'" is the all-permissive grammar —
            # its mask is a no-op on every token, so the A/B against the
            # same command without --guided isolates the FSM machinery's
            # own cost (one table-row gather + where per step).
            from ditl_tpu.infer import grammar as gmod

            grammar = (gmod.compile_json(tok) if guided == "json"
                       else gmod.compile_regex(guided, tok))

        def make_engine():
            return ContinuousEngine(
                params, cfg, tok, n_slots=slots, decode_chunk=decode_chunk,
                cache_mode=cache, page_size=page_size,
                gen=GenerateConfig(max_new_tokens=max_new),
                speculative=speculative,
                # The bench measures the speculative path itself; the
                # auto-decision's own probing is pinned by tests.
                # bigram keeps the AUTO decision: the claim under test is
                # that lookup acceptance collapses and auto-disables while
                # the draft model keeps paying — forcing every tick
                # speculative would measure lookup drafting garbage.
                spec_threshold=(
                    0.0 if speculative and workload != "bigram" else None
                ),
                fsm_capacity=(grammar.n_states + 2) if grammar else 0,
                draft_params=draft_params, draft_cfg=draft_cfg,
                pipeline_ticks=pipeline,
                admission=admission, n_pages=pages or None,
            )

        def run_once(eng):
            for i, p in enumerate(prompts):
                eng.submit(list(p), max_new_tokens=max_new,
                           temperature=temperature, seed=i,
                           grammar=grammar)
            out = eng.run()
            return sum(len(v) for v in out.values())

        def reset_prefix_state(eng):
            # Every timed iteration measures a COLD-prefix run: drop the
            # content cache so paged iterations don't silently become
            # prefix-cache benchmarks (programs stay compiled — only the
            # host-side allocator resets; pages are fully rewritten before
            # any read).
            if cache == "paged":
                from ditl_tpu.infer.paged_cache import PageAllocator

                # Keep the eviction callback wired (ISSUE 8/13): the
                # engine's constructor hooks it, and a bare replacement
                # would silently zero evictions in the row's telemetry
                # snapshot (and unhook the host-tier spill path).
                eng.allocator = PageAllocator(
                    eng.n_pages, on_evict=eng._on_pages_evicted,
                    group_payload=lambda eng=eng: (
                        eng.host_tier is not None
                        or bool(eng._handoff_pids)
                    ),
                )
                eng._table[:] = 0
                eng._slot_pages = [[] for _ in range(eng.n_slots)]

        eng = make_engine()
        run_once(eng)  # compile every program in the path
        times, tokens = [], 0
        for _ in range(5):
            reset_prefix_state(eng)
            t = time.perf_counter()
            tokens = run_once(eng)
            times.append(time.perf_counter() - t)
        dt = statistics.median(times)
        extra = {}
        # Telemetry snapshot (ISSUE 3 satellite): the engine's cumulative
        # serving metrics — TTFT/TPOT/e2e histogram stats and the
        # operational counters — ride the bench JSON so BENCH_r*.json rows
        # carry latency attribution, not just throughput.
        extra["telemetry"] = eng.metrics.summary()
        if guided:
            extra["guided"] = guided
        if speculative:
            st = eng.stats()["speculative"]
            extra["spec_acceptance"] = (
                round(st["acceptance_ema"], 2)
                if st["acceptance_ema"] is not None else None
            )
            extra["drafter"] = st["drafter"]
    else:
        from ditl_tpu.infer.engine import GenerateConfig, Generator

        if speculative:
            raise SystemExit(
                "--speculative with --engine lockstep: use the continuous "
                "engine (or infer/speculative.SpeculativeGenerator directly)"
            )
        if guided:
            raise SystemExit(
                "--guided requires --engine continuous (the FSM mask rides "
                "the slot scheduler's decode ticks)"
            )
        if pipeline:
            raise SystemExit(
                "--pipeline requires --engine continuous (lockstep has no "
                "tick loop to double-buffer)"
            )
        if admission != "reserve" or pages:
            raise SystemExit(
                "--admission/--pages require --engine continuous --cache "
                "paged (lockstep has no page pool)"
            )
        gen = GenerateConfig(max_new_tokens=max_new,
                             temperature=0.0 if workload == "repetitive" else 1.0,
                             seed=1)
        g = Generator(params, cfg, tok)
        g.generate_tokens(prompts, gen)  # compile
        times, tokens = [], 0
        for _ in range(5):
            t = time.perf_counter()
            out = g.generate_tokens(prompts, gen)
            tokens = sum(len(v) for v in out)
            times.append(time.perf_counter() - t)
        dt = statistics.median(times)
        extra = {}
    label = "%s%s%s%s%s%s%s%s" % (
        engine,
        "/paged" if cache == "paged" else "",
        ", int8" if quantize else "",
        ", int8-kv" if kv_quant else "",
        ", speculative" if speculative else "",
        (", T=%.2g" % temperature) if temperature else "",
        ", pipelined" if pipeline else "",
        ", optimistic" if admission == "optimistic" else "",
    )
    arch = "MoE 8x top-2" if moe else "Llama-style"
    print(json.dumps({
        "metric": "decode tokens/sec (%s %dM, batch %d, ctx %d+%d, %s, %s)"
                  % (arch, round(params_m), batch, len(prompts[0]), max_new,
                     label, workload),
        **_record_meta(),
        "value": round(tokens / dt, 1),
        "unit": "tokens/sec",
        "vs_baseline": 1.0,
        "vs_baseline_key": "self",
        "params_m": round(params_m, 1),
        "platform": platform,
        "generated_tokens": tokens,
        **extra,
        **_chaos_result(),
        **_incident_result(_inc0),
    }))
    return 0


def run_gateway_bench(n_replicas: int, slots: int = 4, decode_chunk: int = 8,
                      prompt_len: int = 0, max_new: int = 0,
                      router: str = "affinity",
                      compile_cache_dir: str = "",
                      trace_out: str = "",
                      prefill_chunk: int = -1,
                      token_budget: int = -1,
                      roles: str = "",
                      mixed_trace: bool = False,
                      host_tier_mb: float = 0.0,
                      kv_handoff: bool = False,
                      kvtier_overrides: dict | None = None,
                      journal_dir: str = "",
                      _model_overrides: dict | None = None) -> dict:
    """Fleet-level serving benchmark (ISSUE 4 satellite): N in-process
    continuous-engine replicas behind the gateway, driven over real HTTP
    with a prefix-grouped workload (the regime cache-affinity routing
    exists for). Records fleet throughput, the measured affinity hit-rate,
    and retry counts in a bench row dict so BENCH_r*.json rows can track
    fleet-level numbers round over round.

    ``roles`` (ISSUE 9) arms a heterogeneous fleet: a comma-separated role
    per replica (gateway/roles.py; shorter specs pad with hybrid), each
    replica's engine knobs derived via role_knobs from the base
    slots/prefill_chunk/token_budget. ``mixed_trace`` adds long batch-class
    prompts alongside the interactive short streams — the
    disagg-vs-homogeneous A/B workload; the row then carries per-class
    TTFT/interference p95s (perf_compare-gated on the interactive pair),
    the worst single interactive interference observation, ``fleet_roles``
    and per-role serving sub-blocks.

    ``host_tier_mb`` (ISSUE 13) arms each engine's host-RAM prefix-cache
    tier — the on-vs-off pair on a working set sized past the HBM pool is
    THE tier A/B (the serving block's hit ratio + host_tier_hit_ratio /
    swap_in_p95_s gate it); ``kv_handoff`` arms the /internal KV endpoints
    on every replica and the gateway's transfer-cost-model orchestration
    (``kvtier_overrides`` tunes the KVTierConfig floors; ``journal_dir``
    records the per-request ``kv.handoff.*`` decision events), and the row
    gains a schema-stamped ``kv_handoff`` block with the fallback ratio
    perf_compare gates.

    ``_model_overrides`` shrinks the bench
    model (tier-1 acceptance drills only — a published row must not use
    it)."""
    import dataclasses
    from concurrent.futures import ThreadPoolExecutor

    import jax

    from ditl_tpu.config import GatewayConfig, ModelConfig
    from ditl_tpu.data.tokenizer import ByteTokenizer
    from ditl_tpu.gateway import (
        Fleet, GatewayMetrics, InProcessReplica, make_gateway, parse_roles,
        role_knobs,
    )
    from ditl_tpu.infer.continuous import ContinuousEngine, ThreadedEngine
    from ditl_tpu.infer.engine import GenerateConfig, Generator
    from ditl_tpu.infer.server import make_server
    from ditl_tpu.models import llama
    from ditl_tpu.runtime.distributed import enable_compile_cache
    from ditl_tpu.telemetry.serving import (
        serving_bench_summary, snapshot_serving,
    )

    enable_compile_cache(compile_cache_dir)
    _inc0 = _incidents_now()
    platform = jax.devices()[0].platform
    cfg = ModelConfig(
        name="bench-350m", vocab_size=32768, hidden_size=1024,
        intermediate_size=2816, num_layers=24, num_heads=16, num_kv_heads=8,
        head_dim=64, max_seq_len=1024, dtype="bfloat16", param_dtype="float32",
    )
    max_new = max_new or (128 if platform == "tpu" else 8)
    plen = prompt_len or (64 if platform == "tpu" else 24)
    if platform != "tpu":
        cfg = dataclasses.replace(cfg, num_layers=2, hidden_size=256,
                                  intermediate_size=688, vocab_size=4096)
    if _model_overrides:
        cfg = dataclasses.replace(cfg, **_model_overrides)
    role_list = parse_roles(roles, n_replicas)
    params = llama.init_params(jax.random.key(0), cfg)
    tok = ByteTokenizer()
    shared_gen = Generator(params, cfg, tok)  # tokenize/metadata routes only
    n_requests = n_replicas * slots * 2
    # Mixed traces add one long batch prompt per replica on top of the
    # short streams; every request must fit in one replica's admission
    # queue (a worst-case affinity pileup must spill, not 429 the bench).
    total_requests = n_requests + (n_replicas if mixed_trace else 0)
    # Pinned serving config (ISSUE 8): paged KV (so the prefix-cache hit
    # ratio the row embeds is a real measured number, not vacuously zero)
    # with chunked prefill ON at a page-size-aligned default and a per-tick
    # token budget — the budgeted scheduler makes chunking strictly
    # beneficial (decode-ready slots never starve behind a prefill), and
    # the row records the interference p50/p95 the budget bounds. Pass 0
    # to either knob for the unbudgeted/unchunked A/B; perf_compare gates
    # the serving block either way.
    page_size = 64 if platform == "tpu" else 16
    if prefill_chunk < 0:
        prefill_chunk = 256 if platform == "tpu" else 16
    if token_budget < 0:
        token_budget = slots * decode_chunk + max(prefill_chunk, page_size)
    # --trace-out (ISSUE 6): arm request tracing across the gateway and
    # every replica engine; after the run the merged journals export to
    # Chrome-trace JSON (open at ui.perfetto.dev) — the per-request
    # timeline artifact behind the bench row's aggregate numbers.
    trace_dir = ""
    tracers: list = [None] * n_replicas
    gw_tracer = None
    trace_journals: list = []
    if trace_out:
        import os
        import tempfile

        from ditl_tpu.telemetry.journal import EventJournal
        from ditl_tpu.telemetry.tracing import Tracer

        trace_dir = tempfile.mkdtemp(prefix="ditl-bench-trace-")
        tracers = []
        for i in range(n_replicas):
            j = EventJournal(
                os.path.join(trace_dir, f"events-replica-{i}.jsonl"),
                source=f"replica-{i}",
            )
            trace_journals.append(j)
            tracers.append(Tracer(j))
        gw_journal = EventJournal(
            os.path.join(trace_dir, "events-gateway.jsonl"),
            source="gateway",
        )
        trace_journals.append(gw_journal)
        gw_tracer = Tracer(gw_journal)
    # Per-replica engine knobs from the role (gateway/roles.py): hybrid =
    # the base config untouched, prefill_heavy = fewer slots / 4x chunk /
    # 4x budget / 2x pages, decode_heavy = 2x slots with the tightest legal
    # budget. Pages are made explicit so the scale applies to the same
    # contiguous-equivalent default the engine would have picked.
    maxp = -(-cfg.max_seq_len // page_size)
    knob_list = [
        role_knobs(role, n_slots=slots, decode_chunk=decode_chunk,
                   prefill_chunk=prefill_chunk, token_budget=token_budget)
        for role in role_list
    ]
    engines = [
        ThreadedEngine(ContinuousEngine(
            params, cfg, tok, n_slots=k["n_slots"],
            decode_chunk=decode_chunk,
            gen=GenerateConfig(max_new_tokens=max_new),
            max_queue=total_requests,
            cache_mode="paged", page_size=page_size,
            n_pages=int(k["pages_scale"] * (k["n_slots"] * maxp + 1)),
            prefill_chunk=k["prefill_chunk"],
            token_budget=k["token_budget"],
            host_tier_mb=host_tier_mb,
            spill_max_pages_per_tick=(kvtier_overrides or {}).get(
                "spill_max_pages_per_tick", 32),
            tracer=tracers[i],
        ))
        for i, k in enumerate(knob_list)
    ]

    def factory(eng, role):
        # make_server derives its tracer from the engine's, so replica
        # server.request spans land in the same per-replica journal.
        return lambda: make_server(shared_gen, port=0, threaded_engine=eng,
                                   default_max_tokens=max_new, role=role,
                                   kv_handoff=kv_handoff)

    fleet = Fleet([
        InProcessReplica(f"r{i}", factory(eng, role_list[i]),
                         role=role_list[i])
        for i, eng in enumerate(engines)
    ])
    fleet.start_all(wait_healthy_s=30.0)
    metrics = GatewayMetrics()
    # Key on exactly the shared group prefix (plen tokens): the default 32
    # would swallow the unique suffix whenever plen < 32 (the CPU smoke),
    # making every key distinct and the affinity A/B meaningless.
    gwcfg = GatewayConfig(router=router, affinity_prefix_tokens=plen)
    kvtier_cfg = None
    gw_journal = None
    if kv_handoff:
        from ditl_tpu.config import KVTierConfig
        from ditl_tpu.telemetry.journal import EventJournal

        kvtier_cfg = KVTierConfig(
            handoff=True, **(kvtier_overrides or {})
        )
        if journal_dir:
            import os as _os

            gw_journal = EventJournal(
                _os.path.join(journal_dir, "events-gateway-kv.jsonl"),
                source="gateway",
            )
    server = make_gateway(fleet, config=gwcfg, metrics=metrics, port=0,
                          tracer=gw_tracer, kvtier=kvtier_cfg,
                          journal=gw_journal)
    import threading

    threading.Thread(target=server.serve_forever, daemon=True).start()
    port = server.server_address[1]

    # Prefix-grouped workload: n_replicas * 2 groups x slots requests, each
    # sharing its group's long prefix — the fleet analog of the paged
    # prefix-reuse regime. Shuffled deterministically so groups interleave.
    # With mixed_trace the shorts become explicit interactive-class STREAMS
    # (alternating generation lengths — identical max_new would march the
    # fleet in synchronized admit/decode cohorts where prefills never
    # co-schedule against live decodes, hiding exactly the interference
    # this A/B measures) and one long batch-class prompt per replica rides
    # along (4x plen, distinct prefixes — the longs must not seed the
    # groups' caches), submitted LAST so batch work lands while the
    # interactive streams are mid-decode: the disagg-vs-homogeneous A/B
    # workload.
    groups = n_replicas * 2
    long_plen = plen * 4
    prompts = []
    for g in range(groups):
        prefix = " ".join(f"g{g}tok{j}" for j in range(plen))
        for i in range(max(1, n_requests // groups)):
            mt = max_new * 2 if mixed_trace and i % 2 else max_new
            prompts.append((f"{prefix} q{i}",
                            "interactive" if mixed_trace else None, mt))
    import random as _random

    _random.Random(7).shuffle(prompts)
    if mixed_trace:
        prompts += [
            (" ".join(f"long{g}tok{j}" for j in range(long_plen)),
             "batch", max_new)
            for g in range(n_replicas)
        ]

    import urllib.request

    def one(item):
        prompt, slo_class, max_tokens = item
        body = {"prompt": prompt, "max_tokens": max_tokens}
        if slo_class:
            body["slo_class"] = slo_class
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/completions",
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"}, method="POST",
        )
        with urllib.request.urlopen(req, timeout=600) as resp:
            return json.loads(resp.read())["usage"]["completion_tokens"]

    # Group-length warm prompt (distinct from every group prefix): the
    # paged chunked-prefill programs are keyed by (chunk, ctx-pages)
    # bucket, so a short warm-up would leave the long-prompt buckets to
    # compile inside the timed region. Mixed traces additionally warm the
    # LONG-prompt bucket on every replica that can receive batch work
    # (hybrid/prefill_heavy — role steering keeps longs off decode_heavy).
    warm_prompt = " ".join(f"warmtok{j}" for j in range(plen))
    warm_long = " ".join(f"warmlongtok{j}" for j in range(long_plen))

    def warm(view):
        # Compile each engine OUTSIDE the timed region by hitting every
        # replica directly — routed warm-ups would herd on whatever subset
        # the policy picks (affinity hashes a handful of prompts to
        # arbitrary homes), leaving cold engines to compile inside the
        # timed section by a policy-dependent amount, which would corrupt
        # the router A/B this bench exists for. The second warm prompt is
        # the PREFIX-HIT admission shape: a group's second request
        # prefix-matches its group's published pages and prefills only the
        # short suffix — a DIFFERENT program than the whole-prompt warm.
        # Without it that suffix program compiles inside the timed region
        # (seconds on CPU) and lands as a fake multi-second interference
        # observation on whichever decode co-scheduled with it — the
        # compile-shaped flake the disagg A/B kept tripping. The warm
        # prefix is distinct from every group prefix, so no group cache is
        # seeded, and the serving block's post-warm snapshot excludes the
        # warm-up hit tokens either way.
        warms = [warm_prompt, f"{warm_prompt} q0"]
        if mixed_trace and view.role != "decode_heavy":
            warms.append(warm_long)
        for p in warms:
            req = urllib.request.Request(
                f"http://{view.address[0]}:{view.address[1]}/v1/completions",
                data=json.dumps(
                    {"prompt": p, "max_tokens": max_new}
                ).encode(),
                headers={"Content-Type": "application/json"}, method="POST",
            )
            with urllib.request.urlopen(req, timeout=600) as resp:
                resp.read()

    bundles_by_role: dict = {}
    for role, eng in zip(role_list, engines):
        bundles_by_role.setdefault(role, []).append(eng._engine.metrics)
    with ThreadPoolExecutor(max_workers=n_replicas * slots) as pool:
        list(pool.map(warm, fleet.views()))
        # Snapshot AFTER warm-up: the gated serving block must cover the
        # timed region only (warm TTFTs are compile seconds, and the warm
        # prompts' misses would deflate the hit ratio). Per-role snapshots
        # scope the role sub-blocks identically, and the worst-observation
        # trackers reset so they too cover only the timed region.
        serving_base = snapshot_serving(
            [eng._engine.metrics for eng in engines]
        )
        role_base = {
            role: snapshot_serving(b) for role, b in bundles_by_role.items()
        }
        for eng in engines:
            eng._engine.interference_max_s = 0.0
            eng._engine.interference_max_by_class = {}
        t0 = time.perf_counter()
        tokens = sum(pool.map(one, prompts))
        dt = time.perf_counter() - t0
    summary = metrics.summary()
    trace_extra = {}
    if trace_out:
        from ditl_tpu.telemetry.trace_export import (
            load_trace_records, to_chrome_trace, trace_ids,
        )

        for j in trace_journals:
            j.close()
        records = load_trace_records(trace_dir)
        with open(trace_out, "w") as f:
            json.dump(to_chrome_trace(records), f)
        trace_extra = {"trace": {
            "out": trace_out,
            "traces": len(trace_ids(records)),
            "journal_dir": trace_dir,
        }}
        print(f"bench: wrote Chrome-trace JSON to {trace_out} "
              f"(open at https://ui.perfetto.dev)", file=sys.stderr)
    # Worst single interactive interference observation across the fleet
    # (ISSUE 9): the wall-clock stall an interactive stream actually
    # absorbed in one tick — the number the disagg acceptance drill grades
    # strictly. None when no interactive victim was ever co-scheduled.
    i_max = [
        eng._engine.interference_max_by_class.get("interactive")
        for eng in engines
    ]
    i_max = [v for v in i_max if v is not None]
    row = {
        "metric": "fleet decode tokens/sec (%d replica(s) x %d slots, "
                  "router=%s)" % (n_replicas, slots, router),
        **_record_meta(),
        "value": round(tokens / dt, 1),
        "unit": "tokens/sec",
        "vs_baseline": 1.0,
        "vs_baseline_key": "self",
        "platform": platform,
        "generated_tokens": tokens,
        "requests": len(prompts),
        # Serving scheduler block (ISSUE 8): fleet-merged interference
        # quantiles + the measured prefix-cache hit ratio, flat numeric
        # keys so telemetry/perf_compare.py gates serving regressions the
        # same way it gates train rows (the block is hoisted like
        # `roofline`). ISSUE 9 adds the per-class p95 splits (interactive
        # gated) and the worst interactive stall.
        "serving": {
            "prefill_chunk": prefill_chunk,
            "token_budget": token_budget,
            "page_size": page_size,
            "host_tier_mb": host_tier_mb,
            "max_tick_prefill_tokens": max(
                eng._engine.max_tick_prefill_tokens for eng in engines
            ),
            "interactive_interference_max_s": (
                round(max(i_max), 6) if i_max else None
            ),
            **serving_bench_summary(
                [eng._engine.metrics for eng in engines],
                since=serving_base,
            ),
        },
        "gateway": {
            "router": router,
            "fleet_roles": role_list,
            "affinity_ratio": summary.get("ditl_gateway_affinity_ratio"),
            "retries": summary.get("ditl_gateway_retries", 0),
            "hedges": summary.get("ditl_gateway_hedges", 0),
            "routed": {
                k.removeprefix("ditl_gateway_replica_").removesuffix("_routed"): v
                for k, v in summary.items()
                if k.startswith("ditl_gateway_replica_")
                and k.endswith("_routed")
            },
            # Per-role serving sub-blocks (ISSUE 9 satellite): the same
            # timed-region summary, scoped to each role's engines — how a
            # BENCH_r*.json row shows which half of a disaggregated fleet
            # moved.
            "serving_by_role": {
                role: serving_bench_summary(b, since=role_base[role])
                for role, b in bundles_by_role.items()
            },
        },
        **trace_extra,
        **_chaos_result(),
        **_incident_result(_inc0),
    }
    if kv_handoff:
        # KV handoff block (ISSUE 13), schema-stamped like the PR 8
        # serving block; perf_compare hoists it and gates the fallback
        # ratio (shipped prefills failing back to re-prefill burn work).
        attempted = summary.get("ditl_gateway_handoff_attempted", 0)
        fallback = summary.get("ditl_gateway_handoff_fallback", 0)
        row["kv_handoff"] = {
            "schema": 1,
            "attempted": attempted,
            "shipped": summary.get("ditl_gateway_handoff_shipped", 0),
            "declined": summary.get("ditl_gateway_handoff_declined", 0),
            "fallback": fallback,
            "handoff_fallback_ratio": (
                round(fallback / attempted, 4) if attempted else 0.0
            ),
        }
    server.shutdown()
    server.server_close()
    fleet.stop_all(drain=True, timeout=10.0)
    for eng in engines:
        eng.close()
    if gw_journal is not None:
        gw_journal.close()
    return row


def run_trace_replay_bench(trace_path: str, n_replicas: int = 3,
                           slots: int = 2, decode_chunk: int = 2,
                           autoscale: bool = False, speed: float = 1.0,
                           min_replicas: int = 1,
                           slo_ttft_s: float = 2.5,
                           compile_cache_dir: str = "",
                           bulk_backlog: int = 0,
                           _model_overrides: dict | None = None,
                           _autoscale_overrides: dict | None = None) -> dict:
    """Traffic-trace replay bench (ISSUE 12): drive a recorded request
    shape (``gateway --save-trace`` JSONL, or a committed synthetic shape
    under ``tests/fixtures/traces/``) through an in-process gateway fleet
    with PRESERVED inter-arrival times, and grade what the fleet COST:
    the row embeds ``replica_seconds`` (integral of live replicas over the
    timed region) next to the usual serving latency block, plus the
    interactive TTFT-SLO violation rate. With ``autoscale=True`` the
    FleetSupervisor carries an armed Actuator — the on-vs-off pair on the
    same trace is THE autoscaler A/B, and perf_compare gates it: fewer
    replica-seconds at no worse TTFT p95 / SLO violation rate.

    ``speed`` compresses the recorded offsets (2.0 = twice as fast);
    ``min_replicas`` floors ordinary scale-down; ``bulk_backlog`` > 0
    arms the offline bulk lane (ISSUE 19): an N-item job is submitted
    through the real ``POST /v1/bulk/jobs`` endpoint before the timed
    region and soaks spare decode capacity through ``best_effort``
    relays while the interactive trace replays — the row grows a
    ``bulk`` block (lane tokens/sec + the interactive TTFT p95 measured
    WITH the backlog running) that perf_compare gates;
    ``_model_overrides`` / ``_autoscale_overrides`` shrink the model /
    tune the planner for tier-1 acceptance drills (a published row must
    not use them)."""
    import dataclasses
    import threading

    import jax

    from ditl_tpu.config import AutoscaleConfig, GatewayConfig, ModelConfig
    from ditl_tpu.data.tokenizer import ByteTokenizer
    from ditl_tpu.gateway import (
        Actuator, Fleet, FleetSupervisor, GatewayMetrics, InProcessReplica,
        load_trace, make_gateway,
    )
    from ditl_tpu.infer.continuous import ContinuousEngine, ThreadedEngine
    from ditl_tpu.infer.engine import GenerateConfig, Generator
    from ditl_tpu.infer.server import make_server
    from ditl_tpu.models import llama
    from ditl_tpu.runtime.distributed import enable_compile_cache

    enable_compile_cache(compile_cache_dir)
    _inc0 = _incidents_now()
    rows = load_trace(trace_path)
    if not rows:
        raise ValueError(f"no replayable rows in {trace_path}")
    if speed <= 0:
        raise ValueError(f"speed must be > 0, got {speed}")
    platform = jax.devices()[0].platform
    cfg = ModelConfig(
        name="bench-350m", vocab_size=32768, hidden_size=1024,
        intermediate_size=2816, num_layers=24, num_heads=16, num_kv_heads=8,
        head_dim=64, max_seq_len=1024, dtype="bfloat16",
        param_dtype="float32",
    )
    if platform != "tpu":
        cfg = dataclasses.replace(cfg, num_layers=2, hidden_size=256,
                                  intermediate_size=688, vocab_size=4096)
    if _model_overrides:
        cfg = dataclasses.replace(cfg, **_model_overrides)
    default_max_new = max(
        [int(r.get("max_new") or 0) for r in rows] + [8]
    )
    params = llama.init_params(jax.random.key(0), cfg)
    tok = ByteTokenizer()
    shared_gen = Generator(params, cfg, tok)  # tokenize/metadata only
    engines = [
        ThreadedEngine(ContinuousEngine(
            params, cfg, tok, n_slots=slots, decode_chunk=decode_chunk,
            gen=GenerateConfig(max_new_tokens=default_max_new),
            max_queue=len(rows) + 8,
        ))
        for _ in range(n_replicas)
    ]

    def factory(eng):
        # In-process replicas adopt their engine across restarts, so the
        # honest measured cold start is the (tiny) server rebuild — the
        # subprocess path measures the real jax-import+build one.
        return lambda: make_server(shared_gen, port=0, threaded_engine=eng,
                                   default_max_tokens=default_max_new,
                                   cold_start_s=0.05)

    fleet = Fleet([
        InProcessReplica(f"r{i}", factory(eng))
        for i, eng in enumerate(engines)
    ])
    fleet.start_all(wait_healthy_s=30.0)
    gw_metrics = GatewayMetrics()
    supervisor = FleetSupervisor(
        fleet, interval_s=0.05, fail_threshold=3,
        probe_timeout_s=2.0, restart_timeout_s=20.0,
    )
    bulk_manager = None
    bulk_dir = ""
    if bulk_backlog > 0:
        import shutil
        import tempfile

        from ditl_tpu.config import BulkConfig
        from ditl_tpu.gateway.bulk import BulkJobManager

        # One in-flight slot per replica: the lane soaks spare decode
        # slots without queueing deeper than the fleet can absorb, and
        # a mid-run death re-dispatches at most that window.
        bulk_dir = tempfile.mkdtemp(prefix="ditl-bulk-bench-")
        bulk_manager = BulkJobManager(
            bulk_dir,
            BulkConfig(dir=bulk_dir, max_in_flight=max(1, n_replicas)),
            registry=gw_metrics.registry,
        )
    actuator = None
    if autoscale:
        as_kwargs = dict(
            enabled=True, min_replicas=min_replicas,
            up_hysteresis_polls=1, hysteresis_polls=4,
            cooldown_s=1.0, drain_wait_s=2.0,
        )
        as_kwargs.update(_autoscale_overrides or {})
        actuator = Actuator(
            fleet, supervisor, AutoscaleConfig(**as_kwargs),
            metrics=gw_metrics, bulk=bulk_manager,
        )
        supervisor.autoscaler = actuator
    gwcfg = GatewayConfig(router="affinity", affinity_prefix_tokens=4)
    server = make_gateway(fleet, config=gwcfg, metrics=gw_metrics, port=0,
                          actuator=actuator, bulk=bulk_manager)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    port = server.server_address[1]
    try:
        return _run_trace_replay_timed(
            rows, engines, fleet, supervisor, actuator, port,
            n_replicas=n_replicas, slots=slots, autoscale=autoscale,
            speed=speed, min_replicas=min_replicas, slo_ttft_s=slo_ttft_s,
            default_max_new=default_max_new, trace_path=trace_path,
            platform=platform, _inc0=_inc0,
            bulk=bulk_manager, bulk_backlog=bulk_backlog,
        )
    finally:
        # One finally covers the replay too: a failed request (retry
        # deadline, unexpected status) must not leak the gateway server,
        # the supervisor, or the engines into the calling process — the
        # tier-1 A/B drill runs this in-process, where a leaked
        # supervisor thread would keep probing for the rest of the
        # pytest session. The bulk manager stops FIRST so its dispatch
        # threads quit issuing relays before the fleet drains.
        if bulk_manager is not None:
            bulk_manager.close()
        server.shutdown()
        server.server_close()
        fleet.stop_all(drain=True, timeout=10.0)
        for eng in engines:
            eng.close()
        if bulk_manager is not None:
            shutil.rmtree(bulk_dir, ignore_errors=True)


def _run_trace_replay_timed(rows, engines, fleet, supervisor, actuator,
                            port, *, n_replicas, slots, autoscale,
                            speed, min_replicas, slo_ttft_s,
                            default_max_new, trace_path, platform,
                            _inc0, bulk=None, bulk_backlog=0) -> dict:
    """The warmed+timed half of :func:`run_trace_replay_bench`; the
    caller owns (and always tears down) the fleet/server/engines."""
    from concurrent.futures import ThreadPoolExecutor

    from ditl_tpu.gateway import ReplicaSecondsSampler
    from ditl_tpu.telemetry.serving import (
        serving_bench_summary, snapshot_serving, ttft_slo_violation_rate,
    )

    def prompt_for(row) -> str:
        # Tenant digest as the shared token prefix: same-tenant traffic
        # shares an affinity key (and a reusable prompt prefix), the
        # regime the recorded shape came from.
        tenant = str(row.get("tenant") or "anon")
        n = max(4, int(row.get("prompt_tokens") or 8))
        return " ".join(f"{tenant}w{j}" for j in range(n))

    import urllib.error
    import urllib.request

    def one(item):
        idx, row = item
        target = t_start + row["t"] / speed
        delay = target - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        body = {"prompt": prompt_for(row),
                "max_tokens": int(row.get("max_new") or default_max_new)}
        if row.get("slo_class"):
            body["slo_class"] = row["slo_class"]
        deadline = time.monotonic() + 120.0
        while True:
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/v1/completions",
                data=json.dumps(body).encode(),
                headers={"Content-Type": "application/json"}, method="POST",
            )
            try:
                with urllib.request.urlopen(req, timeout=120) as resp:
                    return json.loads(
                        resp.read())["usage"]["completion_tokens"]
            except urllib.error.HTTPError as e:
                # 429 = throttle or scale-to-zero wake promise: honor the
                # Retry-After like a real client (the wake budget says the
                # replica will be up by then). Anything else is a failure.
                e.read()
                if e.code != 429 or time.monotonic() > deadline:
                    raise
                time.sleep(min(5.0, float(e.headers.get("Retry-After", 1))))

    # Warm every PROMPT SHAPE the trace will replay, on every replica (the
    # run_gateway_bench group-length discipline, stricter: the byte
    # tokenizer makes prefill shape = byte length, so warm with the EXACT
    # replay prompts). A shape compiling inside the timed region would
    # charge ~seconds of compile to whichever leg hit it first —
    # corrupting exactly the TTFT comparison the A/B exists for.
    warm_prompts = sorted({prompt_for(r) for r in rows})

    def warm(view):
        for prompt in warm_prompts:
            req = urllib.request.Request(
                f"http://{view.address[0]}:{view.address[1]}"
                "/v1/completions",
                data=json.dumps({"prompt": prompt,
                                 "max_tokens": default_max_new}).encode(),
                headers={"Content-Type": "application/json"},
                method="POST",
            )
            with urllib.request.urlopen(req, timeout=600) as resp:
                resp.read()

    bundles = [eng._engine.metrics for eng in engines]
    sampler = ReplicaSecondsSampler(fleet, interval_s=0.02)
    # The sampler/supervisor threads stop even when a replay request
    # fails; the caller's finally owns the server/fleet/engine teardown.
    try:
        with ThreadPoolExecutor(max_workers=max(8, len(rows))) as pool:
            # Compile every engine OUTSIDE the timed region (direct hits,
            # the run_gateway_bench discipline), then snapshot so the
            # serving block and the replica-seconds integral cover the
            # replay only.
            list(pool.map(warm, fleet.views()))
            serving_base = snapshot_serving(bundles)
            bulk_job_id, bulk_tok0 = "", 0
            if bulk is not None and bulk_backlog > 0:
                # Submit through the REAL endpoint so the row exercises
                # the whole lane (parse -> quota -> journal -> relay).
                # Prompts cycle the already-warmed shapes: a bulk item
                # compiling inside the timed region would charge its
                # compile seconds to the interactive TTFT comparison.
                bulk_prompts = [warm_prompts[i % len(warm_prompts)]
                                for i in range(bulk_backlog)]
                req = urllib.request.Request(
                    f"http://127.0.0.1:{port}/v1/bulk/jobs",
                    data=json.dumps({"prompts": bulk_prompts,
                                     "max_new": default_max_new}).encode(),
                    headers={"Content-Type": "application/json"},
                    method="POST",
                )
                with urllib.request.urlopen(req, timeout=30) as resp:
                    bulk_job_id = json.loads(resp.read())["id"]
            supervisor.start()
            sampler.start()
            if bulk is not None:
                bulk_tok0 = bulk.tokens_total()
            t_start = time.perf_counter()
            tokens = sum(pool.map(one, enumerate(rows)))
            dt = time.perf_counter() - t_start
    finally:
        replica_seconds = sampler.stop()
        supervisor.stop()
    actions: dict[str, int] = {}
    if actuator is not None:
        for entry in actuator.recent():
            key = f"{entry['kind']}_{entry['outcome']}"
            actions[key] = actions.get(key, 0) + 1
    # Summarize the timed region BEFORE draining the bulk tail — the
    # post-replay drain would otherwise leak its (idle-fleet) TTFTs into
    # the serving block the interference comparison reads.
    serving_summary = serving_bench_summary(bundles, since=serving_base)
    bulk_block = None
    if bulk is not None and bulk_backlog > 0:
        bulk_tokens = bulk.tokens_total() - bulk_tok0
        drained = bulk.drain(timeout_s=120.0)
        rec = bulk.status(bulk_job_id) or {}
        # The interference number the lane is graded on: interactive
        # TTFT p95 measured WITH the backlog running. Class-split when
        # the trace carries SLO classes, fleet-wide otherwise.
        ttft = serving_summary.get("interactive_ttft_p95_s")
        if ttft is None:
            ttft = serving_summary.get("ttft_p95_s")
        bulk_block = {
            "backlog": bulk_backlog,
            "bulk_tokens_per_s": (round(bulk_tokens / dt, 1)
                                  if dt > 0 else 0.0),
            "bulk_interactive_ttft_p95_s": ttft,
            "drained": drained,
            "items_completed": int(rec.get("n_done") or 0),
            "items_retried": int(rec.get("n_retried") or 0),
        }
    row = {
        "metric": "trace replay (%d replica(s) x %d slots, autoscale=%s%s)"
                  % (n_replicas, slots, "on" if autoscale else "off",
                     ", bulk=%d" % bulk_backlog if bulk_backlog else ""),
        **_record_meta(),
        "value": round(tokens / dt, 1),
        "unit": "tokens/sec",
        "vs_baseline": 1.0,
        "vs_baseline_key": "self",
        "platform": platform,
        "generated_tokens": tokens,
        "requests": len(rows),
        "trace": {"path": trace_path, "rows": len(rows), "speed": speed,
                  "duration_s": round(dt, 3)},
        "serving": serving_summary,
        # The autoscaler A/B block (hoisted by perf_compare like
        # `serving`): replica_seconds regresses when it RISES, the SLO
        # violation rate when it rises — on-vs-off on the same seeded
        # trace gates "fewer replica-seconds at no worse interactive SLO".
        "autoscale": {
            "enabled": autoscale,
            "min_replicas": min_replicas,
            "replica_seconds": round(replica_seconds, 3),
            "ttft_slo_violation_rate": ttft_slo_violation_rate(
                bundles, slo_ttft_s, since=serving_base),
            "actions": actions,
        },
        **_chaos_result(),
        **_incident_result(_inc0),
    }
    if bulk_block is not None:
        row["bulk"] = bulk_block
    return row


def bench_trace_replay(*args, **kwargs) -> int:
    """CLI wrapper over :func:`run_trace_replay_bench`: one JSON line."""
    print(json.dumps(run_trace_replay_bench(*args, **kwargs)))
    return 0


def bench_gateway(*args, **kwargs) -> int:
    """CLI wrapper over :func:`run_gateway_bench`: one JSON line, like
    every other bench mode."""
    print(json.dumps(run_gateway_bench(*args, **kwargs)))
    return 0


def _percentile(sorted_vals, q: float) -> float:
    """Nearest-rank percentile over an already-sorted list."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, max(0, int(q * len(sorted_vals))))
    return sorted_vals[idx]


class _SelectorSSEStub:
    """Selector-based SSE replica stand-in (ISSUE 17): answers ``GET
    /health`` with the usual JSON and every POST with an SSE first chunk,
    then HOLDS the stream open — no thread per connection on the replica
    either, so a 10k-stream hold doesn't smuggle 10k *stub* threads into
    the row it exists to pin. Implements the InProcessReplica lifecycle
    contract (``serve_forever`` / ``close`` / ``kill`` /
    ``server_address``); ``finish_streams()`` completes every held
    stream (``data: [DONE]`` + close) — the drain drill's "some streams
    finish" lever."""

    _HEALTH = json.dumps({
        "status": "ok", "draining": False, "queue_depth": 0,
        "active_slots": 0, "n_slots": 8,
    }).encode()

    def __init__(self, address=("127.0.0.1", 0)):
        import selectors
        import socket
        import threading

        self._sel = selectors.DefaultSelector()
        self._lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._lsock.bind(address)
        self._lsock.listen(1024)
        self._lsock.setblocking(False)
        self.server_address = self._lsock.getsockname()[:2]
        self._rsock, self._wsock = socket.socketpair()
        self._rsock.setblocking(False)
        self._wsock.setblocking(False)
        self._cmds: list = []  # append/pop(0) are atomic; wake byte signals
        self._bufs: dict = {}  # parsing sockets -> request bytearray
        self._held: list = []  # sockets with an open SSE stream
        self.streams_opened = 0
        self._stopped = threading.Event()
        self._stopped.set()

    def _wake(self, cmd: str) -> None:
        self._cmds.append(cmd)
        try:
            self._wsock.send(b"\x00")
        except OSError:
            pass

    def serve_forever(self, poll_interval: float = 0.5) -> None:
        import selectors

        self._stopped.clear()
        self._sel.register(self._lsock, selectors.EVENT_READ, "accept")
        self._sel.register(self._rsock, selectors.EVENT_READ, "wake")
        try:
            while True:
                for key, _ in self._sel.select(poll_interval):
                    if key.data == "accept":
                        self._accept()
                    elif key.data == "wake":
                        self._drain_wake()
                    else:
                        self._client(key.fileobj)
                while self._cmds:
                    if self._cmds.pop(0) == "finish":
                        self._finish_all()
                    else:  # "stop"
                        return
        finally:
            for sock in [*self._bufs, *self._held]:
                try:
                    sock.close()
                except OSError:
                    pass
            self._bufs.clear()
            self._held.clear()
            for sock in (self._lsock, self._rsock, self._wsock):
                try:
                    sock.close()
                except OSError:
                    pass
            self._sel.close()
            self._stopped.set()

    def _drain_wake(self) -> None:
        try:
            while self._rsock.recv(4096):
                pass
        except OSError:
            pass

    def _accept(self) -> None:
        import selectors
        import socket

        for _ in range(128):
            try:
                sock, _ = self._lsock.accept()
            except OSError:
                return
            sock.setblocking(False)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._bufs[sock] = bytearray()
            try:
                self._sel.register(sock, selectors.EVENT_READ, "client")
            except (KeyError, ValueError, OSError):
                sock.close()
                del self._bufs[sock]

    def _drop(self, sock) -> None:
        try:
            self._sel.unregister(sock)
        except (KeyError, ValueError, OSError):
            pass
        self._bufs.pop(sock, None)
        try:
            self._held.remove(sock)
        except ValueError:
            pass
        try:
            sock.close()
        except OSError:
            pass

    def _client(self, sock) -> None:
        try:
            data = sock.recv(65536)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            self._drop(sock)
            return
        if not data:
            self._drop(sock)
            return
        buf = self._bufs.get(sock)
        if buf is None:
            return  # bytes on a held stream: ignore
        buf += data
        end = buf.find(b"\r\n\r\n")
        if end < 0:
            return
        head = bytes(buf[:end])
        length = 0
        for line in head.split(b"\r\n")[1:]:
            if line[:15].lower() == b"content-length:":
                try:
                    length = int(line[15:])
                except ValueError:
                    length = 0
        if len(buf) < end + 4 + length:
            return  # body still arriving
        self._respond(sock, head)

    def _respond(self, sock, head: bytes) -> None:
        del self._bufs[sock]
        try:
            if head.startswith(b"GET"):
                body = self._HEALTH
                sock.sendall(
                    b"HTTP/1.1 200 OK\r\n"
                    b"Content-Type: application/json\r\n"
                    b"Content-Length: " + str(len(body)).encode() +
                    b"\r\nConnection: close\r\n\r\n" + body)
                self._drop(sock)
                return
            sock.sendall(
                b"HTTP/1.1 200 OK\r\n"
                b"Content-Type: text/event-stream\r\n"
                b"Cache-Control: no-cache\r\n"
                b"Connection: close\r\n\r\n"
                b'data: {"choices": [{"index": 0, "text": "s"}]}\n\n')
        except OSError:
            self._drop(sock)
            return
        self._held.append(sock)
        self.streams_opened += 1

    def _finish_all(self) -> None:
        for sock in list(self._held):
            try:
                sock.sendall(b"data: [DONE]\n\n")
            except OSError:
                pass
            self._drop(sock)

    def finish_streams(self) -> None:
        """Complete every held stream: terminal SSE event, then close
        (SSE is close-delimited — this is a clean upstream EOF)."""
        self._wake("finish")

    def close(self, drain: bool = True, timeout: float = 10.0) -> None:
        self._wake("stop")
        self._stopped.wait(timeout)

    def kill(self) -> None:
        self.close(drain=False)


def gateway_thread_count() -> int:
    """Resident gateway threads right now: every thread the gateway
    owns carries a ``gw-`` name (``gw-loop`` / ``gw-offload`` /
    ``gw-hedge`` / ``gw-fanout``) — the number the 10k-stream hold row
    pins ≤ 16 where thread-per-stream would read ~N."""
    import threading

    return sum(1 for t in threading.enumerate()
               if t.name.startswith("gw-"))


def hold_open_sse_streams(port: int, n: int, *, batch: int = 256,
                          timeout_s: float = 180.0,
                          sample=None) -> tuple[list, int]:
    """Open-loop SSE client (ISSUE 17): open ``n`` streams against the
    gateway and hold them, all from THE CALLING THREAD — one selector,
    no client thread per stream (the whole point is that neither side
    of the hold pays a thread). A stream counts as open once its first
    SSE chunk arrives (headers + ``data:``). Connects ride in waves of
    ``batch`` so the gateway's accept backlog never overflows. Returns
    ``(sockets, opened)`` — the caller owns closing the sockets;
    ``sample`` (optional callable) runs once per loop pass (thread-count
    sampling during the ramp, when the offload pool is busiest)."""
    import selectors
    import socket

    payload = json.dumps({"prompt": "hold", "max_tokens": 4,
                          "stream": True}).encode()
    request = (b"POST /v1/completions HTTP/1.1\r\n"
               b"Host: gw\r\nContent-Type: application/json\r\n"
               b"Content-Length: " + str(len(payload)).encode() +
               b"\r\n\r\n" + payload)
    sel = selectors.DefaultSelector()
    socks: list = []
    states: dict = {}  # sock -> [sent_offset, recv_buf, opened]
    opened = dead = 0
    remaining = n
    inflight = 0
    deadline = time.monotonic() + timeout_s

    def launch():
        nonlocal remaining, inflight
        while remaining and inflight < batch:
            remaining -= 1
            inflight += 1
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            s.setblocking(False)
            try:
                s.connect_ex(("127.0.0.1", port))
                sel.register(s, selectors.EVENT_WRITE, None)
            except OSError:
                settle(s, ok=False)
                continue
            socks.append(s)
            states[s] = [0, bytearray(), False]

    def settle(s, ok: bool):
        nonlocal opened, dead, inflight
        inflight -= 1
        if ok:
            opened += 1
        else:
            dead += 1
        try:
            sel.unregister(s)
        except (KeyError, ValueError, OSError):
            pass

    launch()
    while opened + dead < n and time.monotonic() < deadline:
        events = sel.select(1.0)
        if sample is not None:
            sample()
        for key, ev in events:
            s = key.fileobj
            st = states[s]
            if ev & selectors.EVENT_WRITE:
                try:
                    sent = s.send(request[st[0]:])
                except (BlockingIOError, InterruptedError):
                    continue
                except OSError:
                    settle(s, ok=False)
                    continue
                st[0] += sent
                if st[0] >= len(request):
                    sel.modify(s, selectors.EVENT_READ, None)
                continue
            try:
                data = s.recv(65536)
            except (BlockingIOError, InterruptedError):
                continue
            except OSError:
                settle(s, ok=False)
                continue
            if not data:
                settle(s, ok=False)
                continue
            st[1] += data
            if not st[2] and b"data:" in st[1]:
                st[2] = True
                # Held: no further events needed — the stream just
                # stays open (the stub never sends more).
                settle(s, ok=True)
        launch()
    sel.close()
    return socks, opened


def run_gateway_stream_hold(concurrency: int, n_replicas: int = 2) -> dict:
    """The ``--serve-concurrency N`` axis (ISSUE 17): hold N idle SSE
    streams through an evloop gateway over selector-based SSE stubs and
    record the gateway's max resident thread count — the number that
    reads ~N on thread-per-stream and must stay ≤ loop + offload pool
    (~13) on the event loop.

    Every stream costs 4 fds in this one process (client↔gateway and
    gateway↔stub pairs), so the held count is clamped to the
    RLIMIT_NOFILE budget — LOUDLY, and recorded in the row
    (``requested`` vs ``open_streams``, ``fd_limit``, ``clamped``):
    a clamp is an environment property, never a silent cap."""
    import os
    import resource
    import threading

    from ditl_tpu.config import GatewayConfig
    from ditl_tpu.gateway import (
        Fleet, GatewayMetrics, InProcessReplica, make_gateway,
    )

    soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
    if soft < hard:
        resource.setrlimit(resource.RLIMIT_NOFILE, (hard, hard))
        soft = hard
    fds_open = len(os.listdir("/proc/self/fd")) if os.path.isdir(
        "/proc/self/fd") else 64
    budget = max(16, (soft - fds_open - 256) // 4)
    target = min(concurrency, budget)
    clamped = target < concurrency
    if clamped:
        print(f"bench: stream hold clamped {concurrency} -> {target} "
              f"(RLIMIT_NOFILE {soft}, 4 fds/stream in one process)",
              file=sys.stderr)

    fleet = Fleet([InProcessReplica(f"s{i}", _SelectorSSEStub)
                   for i in range(n_replicas)])
    server = None
    try:
        fleet.start_all()
        for rid in fleet.ids:
            if not fleet.probe(rid, timeout=5.0):
                raise RuntimeError(f"SSE stub {rid} failed its probe")
        gwcfg = GatewayConfig()  # data_plane="evloop" is the default
        server = make_gateway(fleet, config=gwcfg,
                              metrics=GatewayMetrics(), port=0)
    except BaseException:
        if server is not None:
            server.server_close()
        fleet.stop_all(drain=False)
        raise
    threading.Thread(target=server.serve_forever, daemon=True,
                     name="gw-loop").start()
    max_threads = gateway_thread_count()
    socks: list = []
    try:
        def sample():
            nonlocal max_threads
            max_threads = max(max_threads, gateway_thread_count())

        t0 = time.perf_counter()
        socks, opened = hold_open_sse_streams(
            server.server_address[1], target, sample=sample)
        ramp_s = time.perf_counter() - t0
        # Steady-state hold: the loop is idle now — sample again so the
        # row pins the resident count, not just the ramp burst.
        for _ in range(10):
            time.sleep(0.05)
            sample()
        if opened < target:
            raise RuntimeError(
                f"stream hold opened {opened}/{target} streams")
    finally:
        for s in socks:
            try:
                s.close()
            except OSError:
                pass
        server.shutdown()
        server.server_close()
        fleet.stop_all(drain=False)
    return {
        "requested": concurrency,
        "open_streams": opened,
        "clamped": clamped,
        "fd_limit": soft,
        "data_plane": "evloop",
        "ramp_s": round(ramp_s, 3),
        "gateway_max_resident_threads": max_threads,
    }


def run_gateway_overhead_bench(n_replicas: int = 2, requests: int = 240,
                               clients: int = 3, pool_max_idle: int = -1,
                               router: str = "round_robin",
                               usage_metering: bool = False,
                               usage_dir: str | None = None,
                               serve_concurrency: int = 0) -> dict:
    """Gateway data-plane overhead microbench (ISSUE 14): a closed loop
    of keep-alive HTTP clients driving in-process STUB replicas — first
    directly, then through the gateway — so the row isolates the
    gateway's OWN per-request tax (routing, admission, relay, and the
    upstream connect it used to pay per hop) from any device work. The
    stubs do zero compute; this is the one serving number that is honest
    on a CPU-only container.

    ``usage_metering=True`` runs a THIRD closed loop through a second
    gateway over the same stub fleet with the full per-tenant metering
    plane armed (ISSUE 15): tenant admission accounting, the
    credential-safe label digest per request, X-Tenant-Label stamping on
    every relay, per-request routing-ring attribution, and the
    gateway-edge usage LEDGER (one JSONL row per request into
    ``usage_dir``). The row then gains a ``usage_metering`` block
    (``gateway_rps_metered``, ``metering_overhead_ratio``) that
    perf_compare gates — metering overhead is measured, never assumed.

    A profiler-on leg always runs (ISSUE 18): a second evloop gateway
    with the continuous sampling profiler and the loop-lag watchdog
    armed drives the same closed loop, and the row gains a
    ``profiler_overhead`` block whose ``prof_vs_off_rps_ratio``
    perf_compare gates inside the same-box noise floor — the sampler
    stays always-on only while this number says it is free.

    The hoisted ``gateway_overhead`` block embeds requests/sec through
    the gateway, the added latency vs the direct leg (p50/p95), and the
    upstream pool's hit ratio + accepted-connection count;
    ``telemetry/perf_compare.py`` gates the first three with direction
    sense. ``pool_max_idle=0`` is the fresh-connect A/B leg (every
    upstream hop connects fresh — the pre-pool behavior); the default
    (-1) takes GatewayConfig's pooled default. The pooled-vs-fresh pair
    on the same stub fleet is THE A/B this bench exists for.

    Deliberately jax-free: stub replicas, the gateway, and the clients
    are all stdlib — nothing here can be device noise."""
    import http.client
    import socket
    import threading
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from ditl_tpu.config import GatewayConfig
    from ditl_tpu.gateway import (
        Fleet, GatewayMetrics, InProcessReplica, make_gateway,
    )
    from ditl_tpu.utils.http11 import KeepAliveHandlerMixin

    _inc0 = _incidents_now()
    if requests < clients:
        raise ValueError(f"requests ({requests}) must be >= clients "
                         f"({clients})")

    stub_body = json.dumps({
        "object": "text_completion",
        "choices": [{"index": 0, "text": "stub", "finish_reason": "stop"}],
        "usage": {"prompt_tokens": 1, "completion_tokens": 1,
                  "total_tokens": 2},
    }).encode()

    class _StubServer(ThreadingHTTPServer):
        """Keep-alive-capable replica stand-in with the lifecycle hooks
        InProcessReplica drives, counting accepted TCP connections — the
        number the pooled-vs-fresh A/B pins (pooled: ~pool size; fresh:
        ~one per request)."""

        daemon_threads = True
        allow_reuse_address = True

        def __init__(self, *args, **kw):
            self.connections = 0
            super().__init__(*args, **kw)

        def process_request(self, request, client_address):
            self.connections += 1
            super().process_request(request, client_address)

        def close(self, drain=True, timeout=30.0):
            self.shutdown()
            self.server_close()

        def kill(self):
            self.close()

    class _StubHandler(KeepAliveHandlerMixin, BaseHTTPRequestHandler):
        def log_message(self, *args):
            pass

        def _json(self, body: bytes):
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            self._json(json.dumps({
                "status": "ok", "draining": False, "queue_depth": 0,
                "active_slots": 0, "n_slots": 8,
            }).encode())

        def do_POST(self):
            self.rfile.read(int(self.headers.get("Content-Length", 0)))
            self._json(stub_body)

    stubs: list = []

    def factory():
        server = _StubServer(("127.0.0.1", 0), _StubHandler)
        stubs.append(server)
        return server

    fleet = Fleet([InProcessReplica(f"r{i}", factory)
                   for i in range(n_replicas)])
    # One try/finally covers startup too: a stub that fails its probe (or
    # a gateway that fails to build) must not leak already-started stub
    # serve loops into the calling process — the tier-1 A/B drill runs
    # this in-process (the run_trace_replay_bench lesson).
    server = None
    try:
        fleet.start_all()
        for rid in fleet.ids:
            if not fleet.probe(rid, timeout=5.0):
                raise RuntimeError(f"stub replica {rid} failed its probe")
        gwcfg_kwargs = dict(router=router)
        if pool_max_idle >= 0:
            gwcfg_kwargs["pool_max_idle_per_replica"] = pool_max_idle
        gwcfg = GatewayConfig(**gwcfg_kwargs)
        server = make_gateway(fleet, config=gwcfg,
                              metrics=GatewayMetrics(), port=0)
    except BaseException:
        if server is not None:
            server.server_close()
        fleet.stop_all(drain=False)
        raise
    threading.Thread(target=server.serve_forever, daemon=True,
                     name="gw-loop").start()
    gw_port = server.server_address[1]
    payload = json.dumps({"prompt": "overhead probe",
                          "max_tokens": 1}).encode()
    per_client = requests // clients
    total = per_client * clients

    def drive(port: int, latencies: list, bearer: str = "",
              n: int | None = None) -> None:
        # One kept-alive client connection per thread (all legs): the
        # client side is held constant so the pooled-vs-fresh delta is
        # the UPSTREAM hop alone. ``bearer`` (metered leg) exercises the
        # real per-tenant admission/label path per request.
        headers = {"Content-Type": "application/json"}
        if bearer:
            headers["Authorization"] = f"Bearer {bearer}"
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30.0)
        try:
            conn.connect()
            # The client half of the keep-alive Nagle fix (utils/http11):
            # without NODELAY every request on a kept-alive connection
            # stalls ~40 ms behind the peer's delayed ACK.
            conn.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            for _ in range(per_client if n is None else n):
                t0 = time.perf_counter()
                conn.request("POST", "/v1/completions", body=payload,
                             headers=headers)
                resp = conn.getresponse()
                data = resp.read()
                if resp.status != 200:
                    # BEFORE recording the latency: a failed request must
                    # fail the bench, never sneak into the gated
                    # percentiles as a "served" sample.
                    raise RuntimeError(
                        f"overhead bench got {resp.status}: {data[:200]!r}"
                    )
                latencies.append(time.perf_counter() - t0)
        finally:
            conn.close()

    def closed_loop(port: int, bearer_prefix: str = "",
                    n_per_client: int | None = None) -> tuple[float, list]:
        expected = (per_client if n_per_client is None
                    else n_per_client) * clients
        lat_lists = [[] for _ in range(clients)]
        errors: list = []

        def run(i):
            try:
                drive(port, lat_lists[i],
                      bearer=f"{bearer_prefix}-{i}" if bearer_prefix else "",
                      n=n_per_client)
            except BaseException as e:  # re-raised on the caller below
                errors.append(e)

        threads = [
            threading.Thread(target=run, args=(i,), daemon=True)
            for i in range(clients)
        ]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        dt = time.perf_counter() - t0
        if errors:
            # The real failure, not an opaque lost-request count.
            raise errors[0]
        lats = sorted(x for lst in lat_lists for x in lst)
        if len(lats) != expected:
            raise RuntimeError(
                f"overhead bench lost requests: {len(lats)} != {expected}"
            )
        return dt, lats

    try:
        # Warm both legs outside the timed region (thread spawn, route
        # compile — tiny, but the A/B is graded strictly), then snapshot
        # the pool so its hit ratio covers the timed gateway loop only.
        direct_addr = fleet.views()[0].address
        for port in (direct_addr[1], gw_port):
            warm: list = []
            conn = http.client.HTTPConnection("127.0.0.1", port,
                                              timeout=30.0)
            try:
                for _ in range(4):
                    conn.request("POST", "/v1/completions", body=payload,
                                 headers={"Content-Type":
                                          "application/json"})
                    resp = conn.getresponse()
                    warm.append(resp.read())
            finally:
                conn.close()
        direct_dt, direct_lats = closed_loop(direct_addr[1])
        # Back-to-back legacy leg (ISSUE 17): the SAME fleet and the
        # same closed loop through a thread-per-connection gateway, so
        # the evloop-vs-threaded ratio at the legacy concurrency point
        # is measured in the row — a data-plane regression cannot hide
        # behind the new concurrency axis. The threaded leg runs as two
        # HALVES bracketing the evloop leg (A/B/A): this box's
        # throughput drifts over a bench's lifetime, and a sequential
        # A-then-B hands whichever plane runs last a free ~10% — the
        # bracket cancels the drift to first order. The pool/connect
        # snapshots still enclose only the evloop window (both gateways
        # share the fleet's pool).
        server_t = make_gateway(
            fleet,
            config=GatewayConfig(**{**gwcfg_kwargs,
                                    "data_plane": "threaded"}),
            metrics=GatewayMetrics(), port=0)
        threading.Thread(target=server_t.serve_forever, daemon=True,
                         name="gw-threaded").start()
        try:
            t_port = server_t.server_address[1]
            warm_conn = http.client.HTTPConnection("127.0.0.1", t_port,
                                                   timeout=30.0)
            try:
                for _ in range(4):
                    warm_conn.request(
                        "POST", "/v1/completions", body=payload,
                        headers={"Content-Type": "application/json"})
                    warm_conn.getresponse().read()
            finally:
                warm_conn.close()
            n_slices = 4 if per_client >= 4 else 1
            # Every requested request runs: the last slice absorbs the
            # remainder (within a pair both planes still drive the same
            # count, so the per-pair ratio stays fair).
            slice_sizes = [per_client // n_slices] * n_slices
            slice_sizes[-1] += per_client % n_slices
            gw_dt = thr_dt = 0.0
            gw_lats = []
            thr_lats = []
            pair_ratios = []
            pool_delta = {"hits": 0, "misses": 0, "discards": 0}
            connects = 0
            for i, slice_n in enumerate(slice_sizes):
                # Palindromic pair order (TE ET TE ET): both planes'
                # slices share the same mean position in time, so a
                # linear drift contributes identically to each.
                order = ((t_port, gw_port) if i % 2 == 0
                         else (gw_port, t_port))
                pair_dt = {}
                for port in order:
                    if port == gw_port:
                        p0 = fleet.pool.stats()
                        c0 = sum(s.connections for s in stubs)
                        dt, lats = closed_loop(port, n_per_client=slice_n)
                        p1 = fleet.pool.stats()
                        for k in pool_delta:
                            pool_delta[k] += p1[k] - p0[k]
                        connects += sum(
                            s.connections for s in stubs) - c0
                        gw_dt += dt
                        gw_lats.extend(lats)
                    else:
                        dt, lats = closed_loop(port, n_per_client=slice_n)
                        thr_dt += dt
                        thr_lats.extend(lats)
                    pair_dt[port] = dt
                # Same request count both halves of the pair, run
                # back-to-back: the rps ratio is the inverse dt ratio,
                # and drift within one ~0.5 s pair is negligible.
                pair_ratios.append(pair_dt[t_port] / pair_dt[gw_port])
            gw_lats.sort()
            thr_lats.sort()
            gw_total = thr_total = sum(slice_sizes) * clients
            # Median of the paired ratios: pairing cancels drift, the
            # median sheds transient spikes (GC, a neighbor container's
            # burst) — the gated number must measure the data plane, not
            # the box's mood during one unlucky slice.
            ratio_evloop_vs_threaded = statistics.median(pair_ratios)
        finally:
            server_t.shutdown()
            server_t.server_close()
        # Profiler-on A/B leg (ISSUE 18): a second evloop gateway over the
        # same fleet with the continuous sampler AND the loop-lag watchdog
        # armed — the measured price of leaving "what code was running"
        # observability on in production. Gated via prof_vs_off_rps_ratio
        # (profiler-on rps / profiler-off rps, direction +1) inside the
        # same-box noise floor: the sampler is cheap enough to stay on, or
        # this gate says it is not.
        from ditl_tpu.config import TelemetryConfig
        prof_hz = 97.0
        server_p = make_gateway(
            fleet, config=gwcfg, metrics=GatewayMetrics(), port=0,
            telemetry=TelemetryConfig(prof_hz=prof_hz,
                                      loop_stall_threshold_s=0.25),
        )
        threading.Thread(target=server_p.serve_forever, daemon=True,
                         name="gw-prof").start()
        try:
            p_port = server_p.server_address[1]
            warm_conn = http.client.HTTPConnection("127.0.0.1", p_port,
                                                   timeout=30.0)
            try:
                for _ in range(4):
                    warm_conn.request(
                        "POST", "/v1/completions", body=payload,
                        headers={"Content-Type": "application/json"})
                    warm_conn.getresponse().read()
            finally:
                warm_conn.close()
            # Palindromic pairing against the still-live profiler-off
            # gateway (the same estimator the threaded leg uses): both
            # sides share the same mean position in time, so box drift
            # cancels to first order and the median sheds spikes.
            n_slices_p = 4 if per_client >= 4 else 1
            sizes_p = [per_client // n_slices_p] * n_slices_p
            sizes_p[-1] += per_client % n_slices_p
            p_dt = 0.0
            p_lats = []
            p_pair_ratios = []
            for i, slice_n in enumerate(sizes_p):
                order = ((gw_port, p_port) if i % 2 == 0
                         else (p_port, gw_port))
                pair_dt = {}
                for port in order:
                    dt, lats = closed_loop(port, n_per_client=slice_n)
                    pair_dt[port] = dt
                    if port == p_port:
                        p_dt += dt
                        p_lats.extend(lats)
                p_pair_ratios.append(pair_dt[gw_port] / pair_dt[p_port])
            ratio_prof_vs_off = statistics.median(p_pair_ratios)
            p_samples = server_p.profiler.samples
            p_stalls = server_p.watchdog.stalls
        finally:
            server_p.shutdown()
            server_p.server_close()
        metered = None
        if usage_metering:
            # Metered A/B leg (ISSUE 15): same fleet, second gateway with
            # the whole per-tenant metering plane armed — admission
            # accounting + label digests + X-Tenant-Label stamping +
            # routing-ring tenant attribution + the gateway-edge ledger.
            import tempfile

            from ditl_tpu.gateway.admission import TenantAdmission
            from ditl_tpu.telemetry.flight import FlightRecorder
            from ditl_tpu.telemetry.usage import (
                UsageLedger, usage_ledger_path,
            )

            udir = usage_dir or tempfile.mkdtemp(prefix="ditl-usage-bench-")
            ledger = UsageLedger(
                usage_ledger_path(udir, "gateway-bench"),
                source="gateway-bench")
            server2 = make_gateway(
                fleet, config=gwcfg, metrics=GatewayMetrics(), port=0,
                admission=TenantAdmission(),  # no limits: pure accounting
                usage=ledger, flight=FlightRecorder(),
            )
            threading.Thread(target=server2.serve_forever,
                             daemon=True).start()
            try:
                m_port = server2.server_address[1]
                warm_conn = http.client.HTTPConnection(
                    "127.0.0.1", m_port, timeout=30.0)
                try:
                    for _ in range(4):
                        warm_conn.request(
                            "POST", "/v1/completions", body=payload,
                            headers={"Content-Type": "application/json",
                                     "Authorization": "Bearer warm-tenant"})
                        warm_conn.getresponse().read()
                finally:
                    warm_conn.close()
                m_dt, m_lats = closed_loop(m_port,
                                           bearer_prefix="bench-tenant")
            finally:
                server2.shutdown()
                server2.server_close()
                ledger.close()
            metered = (m_dt, m_lats, udir)
    finally:
        server.shutdown()
        server.server_close()
        fleet.stop_all(drain=False)
    stream_hold = None
    if serve_concurrency > 0:
        # Only after the closed-loop gateways are fully torn down: the
        # hold row's resident-thread count must see the hold gateway's
        # threads ALONE. Retired offload workers exit promptly after
        # shutdown(wait=False) — wait for them, bounded.
        deadline = time.monotonic() + 10.0
        while gateway_thread_count() and time.monotonic() < deadline:
            time.sleep(0.05)
        stream_hold = run_gateway_stream_hold(serve_concurrency)
    hits = pool_delta["hits"]
    misses = pool_delta["misses"]
    gw_rps = gw_total / gw_dt
    d_p50, d_p95 = _percentile(direct_lats, 0.50), _percentile(direct_lats,
                                                               0.95)
    g_p50, g_p95 = _percentile(gw_lats, 0.50), _percentile(gw_lats, 0.95)
    pooled = fleet.pool.max_idle_per_replica > 0
    usage_block = {}
    if metered is not None:
        from ditl_tpu.telemetry.usage import load_usage, rollup

        m_dt, m_lats, udir = metered
        m_rps = total / m_dt
        rows = load_usage(udir)
        usage_block = {"usage_metering": {
            "schema": 1,
            "usage_dir": udir,
            "gateway_rps_metered": round(m_rps, 1),
            "metered_p50_s": round(_percentile(m_lats, 0.50), 6),
            "metered_p95_s": round(_percentile(m_lats, 0.95), 6),
            # Fractional rps cost of arming the ledger vs the unmetered
            # gateway leg on the same fleet (negative = noise in the
            # metered leg's favor; gated with direction -1).
            "metering_overhead_ratio": round(1.0 - m_rps / gw_rps, 4),
            "ledger_rows": len(rows),
            "tenants": len(rollup(rows)),
        }}
    p_rps = total / p_dt
    prof_block = {"profiler_overhead": {
        "schema": 1,
        "prof_hz": prof_hz,
        "gateway_rps_profiled": round(p_rps, 1),
        "profiled_p50_s": round(_percentile(p_lats, 0.50), 6),
        "profiled_p95_s": round(_percentile(p_lats, 0.95), 6),
        # Samples actually taken while the leg ran (zero would mean the
        # gate compared a dead sampler) and stalls the armed watchdog
        # convicted (anything non-zero on a clean bench is itself news).
        "prof_samples": int(p_samples),
        "loop_stalls": int(p_stalls),
        "prof_vs_off_rps_ratio": round(ratio_prof_vs_off, 4),
    }}
    return {
        "metric": "gateway data-plane overhead (%d stub replica(s), "
                  "pool=%s)" % (n_replicas, "on" if pooled else "off"),
        **_record_meta(),
        "value": round(gw_rps, 1),
        "unit": "requests/sec",
        "vs_baseline": 1.0,
        "vs_baseline_key": "self",
        # No jax import anywhere on this path — the platform stamp says
        # so instead of lying with a device name.
        "platform": "host",
        "requests": total,
        "gateway_overhead": {
            "schema": 1,
            "pooled": pooled,
            "pool_max_idle": fleet.pool.max_idle_per_replica,
            "clients": clients,
            "router": router,
            "data_plane": gwcfg.data_plane,
            # Legacy thread-per-connection leg on the same fleet + the
            # gated ratio: evloop must hold >= threaded req/s at the
            # legacy concurrency point (direction +1 in perf_compare).
            "threaded": {
                "gateway_rps": round(thr_total / thr_dt, 1),
                "gateway_p50_s": round(_percentile(thr_lats, 0.50), 6),
                "gateway_p95_s": round(_percentile(thr_lats, 0.95), 6),
            },
            "evloop_vs_threaded_rps_ratio": round(
                ratio_evloop_vs_threaded, 4),
            **({"stream_hold": stream_hold,
                "gateway_max_resident_threads":
                    stream_hold["gateway_max_resident_threads"]}
               if stream_hold else {}),
            "gateway_rps": round(gw_rps, 1),
            "direct_rps": round(total / direct_dt, 1),
            "gateway_p50_s": round(g_p50, 6),
            "gateway_p95_s": round(g_p95, 6),
            "direct_p50_s": round(d_p50, 6),
            "direct_p95_s": round(d_p95, 6),
            "gateway_added_p50_s": round(g_p50 - d_p50, 6),
            "gateway_added_p95_s": round(g_p95 - d_p95, 6),
            "pool_hit_ratio": (
                round(hits / (hits + misses), 4) if hits + misses else 0.0
            ),
            "pool": {"hits": hits, "misses": misses,
                     "discards": pool_delta["discards"]},
            "upstream_connects": connects,
        },
        **prof_block,
        **usage_block,
        **_chaos_result(),
        **_incident_result(_inc0),
    }


def bench_gateway_overhead(*args, **kwargs) -> int:
    """CLI wrapper over :func:`run_gateway_overhead_bench`: one JSON
    line."""
    print(json.dumps(run_gateway_overhead_bench(*args, **kwargs)))
    return 0


def run_multi_lora_bench(n_adapters: int = 4, slots: int = 4,
                         decode_chunk: int = 8, prompt_len: int = 0,
                         max_new: int = 0, swaps: int = 6,
                         compile_cache_dir: str = "",
                         _model_overrides: dict | None = None) -> dict:
    """Multi-LoRA serving overhead A/B (ISSUE 16 satellite): the SAME
    model, workload, and engine knobs run twice — once as a plain base
    engine, once with a stacked adapter pool of ``n_adapters`` rows and
    requests spread round-robin across them. The pool rows are all-zeros
    adapters, so leg B's outputs are bitwise the base model's while every
    decode tick still pays the full per-row gather + LoRA matmuls — the
    delta is exactly the price of ARMING the adapter plane, which is
    what ``adapter_gather_overhead_ratio`` records (fraction of base
    tokens/sec lost; perf_compare gates it with direction -1).

    The pool leg then runs a hot-swap drill: an adapter-only checkpoint
    (train/adapter_export layout, crc manifest and all) is repeatedly
    re-published into the live registry (infer/adapters.py) —
    verify -> load-to-spare-row -> flip -> drain-old-row per swap, timed
    end to end from the caller's seat. ``adapter_swap_p95_s`` is the
    second gated number: a regression here means hot publication stopped
    being cheap enough to run against a serving fleet.

    ``_model_overrides`` shrinks the bench model (tier-1 acceptance
    drills only — a published row must not use it)."""
    import dataclasses
    import tempfile

    import jax

    from ditl_tpu.config import ModelConfig
    from ditl_tpu.data.tokenizer import ByteTokenizer
    from ditl_tpu.infer.adapters import AdapterRegistry
    from ditl_tpu.infer.continuous import ContinuousEngine
    from ditl_tpu.infer.engine import GenerateConfig
    from ditl_tpu.models import llama
    from ditl_tpu.models.lora import stack_adapters, zeros_adapter
    from ditl_tpu.runtime.distributed import enable_compile_cache
    from ditl_tpu.train.adapter_export import export_adapter

    if n_adapters < 2:
        # The swap drill re-publishes into a SPARE row while the old one
        # drains — a 1-row pool has no spare (and is not "multi" anyway).
        raise ValueError(f"n_adapters ({n_adapters}) must be >= 2")
    enable_compile_cache(compile_cache_dir)
    _inc0 = _incidents_now()
    platform = jax.devices()[0].platform
    cfg = ModelConfig(
        name="bench-350m", vocab_size=32768, hidden_size=1024,
        intermediate_size=2816, num_layers=24, num_heads=16, num_kv_heads=8,
        head_dim=64, max_seq_len=1024, dtype="bfloat16",
        param_dtype="float32", lora_rank=8,
    )
    max_new = max_new or (128 if platform == "tpu" else 8)
    plen = prompt_len or (64 if platform == "tpu" else 24)
    if platform != "tpu":
        cfg = dataclasses.replace(cfg, num_layers=2, hidden_size=256,
                                  intermediate_size=688, vocab_size=4096,
                                  lora_rank=4)
    if _model_overrides:
        cfg = dataclasses.replace(cfg, **_model_overrides)
    base_cfg = dataclasses.replace(cfg, lora_rank=0)
    params = llama.init_params(jax.random.key(0), base_cfg)
    params_m = llama.num_params(params) / 1e6
    tok = ByteTokenizer()
    import numpy as np

    rng = np.random.default_rng(3)
    n_requests = slots * 2
    prompts = [
        [1] + rng.integers(4, min(4096, cfg.vocab_size),
                           size=plen - 1).tolist()
        for _ in range(n_requests)
    ]

    def timed_leg(eng, adapter_ids):
        def run_once():
            for i, p in enumerate(prompts):
                eng.submit(list(p), max_new_tokens=max_new, seed=i,
                           adapter_id=adapter_ids[i] or None)
            out = eng.run()
            return sum(len(v) for v in out.values())

        run_once()  # compile every program in the path
        times, tokens = [], 0
        for _ in range(5):
            t = time.perf_counter()
            tokens = run_once()
            times.append(time.perf_counter() - t)
        return tokens / statistics.median(times)

    # Leg A: plain base engine — no stacked leaves, no gather anywhere.
    base_eng = ContinuousEngine(
        params, base_cfg, tok, n_slots=slots, decode_chunk=decode_chunk,
        gen=GenerateConfig(max_new_tokens=max_new),
    )
    base_tps = timed_leg(base_eng, [0] * n_requests)

    # Leg B: identical base weights under a stacked pool of n_adapters
    # zeros rows (+ base row 0), requests spread round-robin across the
    # rows — different adapters SHARING decode ticks, the multi-tenant
    # serving regime the per-row gather exists for.
    lparams = {**params, "layers": {**params["layers"], "lora":
               stack_adapters([zeros_adapter(cfg)] * (n_adapters + 1))}}
    pool_eng = ContinuousEngine(
        lparams, cfg, tok, n_slots=slots, decode_chunk=decode_chunk,
        gen=GenerateConfig(max_new_tokens=max_new),
    )
    spread = [1 + i % n_adapters for i in range(n_requests)]
    pool_tps = timed_leg(pool_eng, spread)

    # Hot-swap drill on the (now idle) pool engine: attached AFTER the
    # timed loops so registry billing bookkeeping cannot touch leg B's
    # throughput number.
    registry = AdapterRegistry(pool_eng)
    adir = tempfile.mkdtemp(prefix="ditl-mlora-bench-")
    version = export_adapter(
        adir, "bench-ft", 1, {"layers": {"lora": zeros_adapter(cfg)}}, cfg)
    swap_times = []
    for _ in range(max(1, swaps)):
        # Re-publication to a live name each round after the first:
        # verify -> spare row -> flip -> drain-old — the full publish hop
        # a replica runs, timed from the caller's seat.
        t0 = time.perf_counter()
        registry.load("bench-ft", version)
        swap_times.append(time.perf_counter() - t0)
    swap_times.sort()

    overhead = 1.0 - pool_tps / base_tps
    return {
        "metric": "multi-LoRA serving tokens/sec (%d zero-delta adapter "
                  "rows, rank %d, batch %d, ctx %d+%d)"
                  % (n_adapters, cfg.lora_rank, n_requests, plen, max_new),
        **_record_meta(),
        "value": round(pool_tps, 1),
        "unit": "tokens/sec",
        "vs_baseline": 1.0,
        "vs_baseline_key": "self",
        "params_m": round(params_m, 1),
        "platform": platform,
        "adapters": {
            "schema": 1,
            "n_adapters": n_adapters,
            "lora_rank": cfg.lora_rank,
            "requests": n_requests,
            "base_tokens_per_sec": round(base_tps, 1),
            "pool_tokens_per_sec": round(pool_tps, 1),
            # Fraction of base-engine tokens/sec the armed pool costs
            # (negative = noise in the pool leg's favor; gated -1).
            "adapter_gather_overhead_ratio": round(overhead, 4),
            "swaps": len(swap_times),
            "adapter_swap_p50_s": round(_percentile(swap_times, 0.50), 6),
            "adapter_swap_p95_s": round(_percentile(swap_times, 0.95), 6),
        },
        **_chaos_result(),
        **_incident_result(_inc0),
    }


def bench_multi_lora(*args, **kwargs) -> int:
    """CLI wrapper over :func:`run_multi_lora_bench`: one JSON line."""
    print(json.dumps(run_multi_lora_bench(*args, **kwargs)))
    return 0


def _effective_bwd_impls(cfg, batch: int, seq: int, mesh=None) -> dict[str, str]:
    """Which backward implementation will actually run for this config —
    delegates to the SAME predicates the dispatch uses (ops/mlp.py,
    ops/projection.py: shape tiling + mesh batch-divisibility gates), over
    the model's ACTUAL projection layout (fused vs per-projection qkv).
    The Pallas kernels fall back to the einsum spelling where those gates
    fail, and a round-over-round ``vs_baseline`` must never silently
    attribute a delta to a kernel that was never executed. A projection
    set that only partially tiles reports "mixed"."""
    from ditl_tpu.ops import mlp, projection

    d, hd = cfg.hidden_size, cfg.head_dim
    mlp_eff = mlp.effective_bwd_impl(
        cfg.mlp_bwd_impl, batch, seq, d, cfg.intermediate_size,
        (cfg.mlp_bwd_block_n, cfg.mlp_bwd_block_f, cfg.mlp_bwd_block_d),
        mesh,
    )
    if cfg.fused_qkv:
        proj_shapes = [(d, (cfg.num_heads + 2 * cfg.num_kv_heads) * hd)]
    else:
        proj_shapes = [(d, cfg.num_heads * hd), (d, cfg.num_kv_heads * hd)]
    proj_shapes.append((cfg.num_heads * hd, d))  # wo
    blocks = (cfg.proj_bwd_block_n, cfg.proj_bwd_block_d)
    effs = {
        projection.effective_bwd_impl(
            cfg.proj_bwd_impl, batch, seq, d_in, f, blocks, mesh
        )
        for d_in, f in proj_shapes
    }
    proj_eff = effs.pop() if len(effs) == 1 else "mixed"
    return {"mlp": mlp_eff, "proj": proj_eff}


def run_train_bench(model_name: str = "350m",
                    overrides: list[str] | None = None,
                    batch_override: int = 0, seq_override: int = 0,
                    compile_cache_dir: str = "") -> dict:
    """One fine-tune bench measurement; returns the result record (the
    JSON row ``main`` prints). Extracted so ``--sweep`` can run it once per
    grid cell and record each row into the versioned sweep JSON."""
    import dataclasses

    import jax
    import numpy as np

    from ditl_tpu.config import MeshConfig, TrainConfig
    from ditl_tpu.data.loader import make_global_batch
    from ditl_tpu.models import llama
    from ditl_tpu.runtime.distributed import enable_compile_cache
    from ditl_tpu.runtime.mesh import build_mesh
    from ditl_tpu.train.state import create_train_state
    from ditl_tpu.train.step import make_multi_step

    from ditl_tpu.telemetry import (
        GoodputTracker, MemoryWatcher, StepAnatomy, compiled_cost, roofline,
    )
    from ditl_tpu.telemetry.perf import peak_hbm_bw

    # Goodput accounting for the bench itself (ISSUE 3 satellite): the same
    # bucket convention as the trainer, so BENCH_r*.json rows say where the
    # bench's wall clock went (compile vs data staging vs timed steps).
    tracker = GoodputTracker()
    tracker.start()
    if enable_compile_cache(compile_cache_dir):
        print(f"bench: persistent compile cache at {compile_cache_dir}",
              file=sys.stderr)
    n_chips = len(jax.devices())
    platform = jax.devices()[0].platform
    print(f"bench: {n_chips} {platform} device(s)", file=sys.stderr)

    cfg, batch, seq, optimizer = _model_cfg(model_name, platform)
    if overrides:
        # Same dotted-override machinery as the launcher/server: sweep a
        # config knob without editing the pinned bench config.
        from ditl_tpu.config import Config, parse_overrides

        cfg = parse_overrides(
            Config(model=cfg), [f"model.{o}" for o in overrides]
        ).model
        print(f"bench: overrides {overrides}", file=sys.stderr)
    if batch_override:
        batch = batch_override
    if seq_override:
        seq = seq_override
        cfg = dataclasses.replace(cfg, max_seq_len=max(cfg.max_seq_len, seq))
    tcfg = TrainConfig(total_steps=1000, warmup_steps=10, optimizer=optimizer)
    mesh = build_mesh(MeshConfig())
    _inc0 = _incidents_now()

    chunk = 20 if platform == "tpu" else 3
    n_windows = 6 if platform == "tpu" else 2
    rng = np.random.default_rng(0)
    # One stacked (chunk, B, S) window per timed iteration — every step of
    # every window sees distinct, learnable data (see _bigram_batches).
    all_tokens = _bigram_batches(rng, chunk * (n_windows + 1), batch, seq,
                                 cfg.vocab_size)
    ones = np.ones((chunk, batch, seq), np.float32)
    segs = np.ones((chunk, batch, seq), np.int32)
    pos = np.tile(np.arange(seq, dtype=np.int32), (chunk, batch, 1))

    def window(i):
        toks = all_tokens[i * chunk:(i + 1) * chunk]
        return {
            "input_ids": toks,
            "loss_mask": ones,
            "labels": np.zeros((chunk, batch), np.int32),
            "segment_ids": segs,
            "positions": pos,
        }

    example = {k: v[0] for k, v in window(0).items()}
    gb = make_global_batch(mesh, example)

    # The whole window of `chunk` optimizer steps is ONE compiled program
    # (lax.scan over stacked batches, train/step.make_multi_step) — the device
    # runs autonomously with zero host dispatch between steps; the same
    # mechanism the trainer exposes as `train.steps_per_call`.
    # Explicit lower().compile() (instead of tracing on first call) so the
    # SAME executable the timed loop runs also answers cost_analysis() —
    # XLA's own flops/bytes for the roofline report (ISSUE 7).
    t0 = time.perf_counter()
    state = create_train_state(jax.random.key(0), cfg, tcfg)
    params_m = llama.num_params(state.params) / 1e6
    multi = make_multi_step(cfg, tcfg, mesh, gb, chunk)
    gb0 = make_global_batch(mesh, window(0))
    multi_exe = multi.lower(state, gb0).compile()
    cost = compiled_cost(multi_exe, n_steps=chunk)
    state, metrics = multi_exe(state, gb0)
    loss_start = float(metrics["loss"][0])
    float(metrics["loss"][-1])  # full host sync (block_until_ready alone does
    # not guarantee completion through remote-device transports)
    tracker.add("compile", time.perf_counter() - t0)
    print(f"bench: compile+first window {time.perf_counter() - t0:.1f}s "
          f"({params_m:.1f}M params)", file=sys.stderr)

    # Pre-stage every window on device before timing: distinct data per step
    # stays honest, while the host->device copy is excluded — the trainer's
    # prefetch pipeline (data/loader.py) overlaps it with compute in real runs.
    with tracker.span("data_wait"):
        staged = [make_global_batch(mesh, window(i))
                  for i in range(1, n_windows + 1)]
        jax.block_until_ready(staged)
    # Step-time anatomy over the timed windows (telemetry/perf.py): data is
    # pre-staged (data_wait excluded by design), so the wall decomposes into
    # host_dispatch (the async call returning) + device_compute (the host
    # blocked on the window's results) — conservation-exact by measurement.
    anatomy = StepAnatomy()
    memwatch = MemoryWatcher()
    times = []
    for stacked in staged:
        t = time.perf_counter()
        state, metrics = multi_exe(state, stacked)
        t_disp = time.perf_counter()
        float(metrics["loss"][-1])  # sync
        t_end = time.perf_counter()
        dt_w = t_end - t
        anatomy.add("host_dispatch", t_disp - t)
        anatomy.add("device_compute", t_end - t_disp)
        anatomy.add_wall(dt_w, chunk)
        tracker.add_step(dt_w, chunk)
        times.append(dt_w / chunk)
    memwatch.sample()  # post-run high-watermark (no-op on statless backends)
    p50 = statistics.median(times)
    final_loss = float(metrics["loss"][-1])
    tokens_per_step = batch * seq
    tps_chip = tokens_per_step / p50 / n_chips
    print(f"bench: step_time_p50={p50 * 1e3:.1f}ms "
          f"loss {loss_start:.4f} -> {final_loss:.4f}", file=sys.stderr)
    if not (final_loss < loss_start and np.isfinite(final_loss)):
        print("bench: WARNING loss did not fall — training regression?",
              file=sys.stderr)

    anchors = {"1b3": ("R02_1B3_BASELINE_TPS", R02_1B3_BASELINE_TPS),
               "350m": ("R01_350M_BASELINE_TPS", R01_350M_BASELINE_TPS)}
    swept = bool(overrides or batch_override or seq_override)
    # vs_baseline names the EXACT anchor it divides by (ISSUE 7 satellite):
    # a swept run measures a different config (no anchor), a CPU smoke has
    # nothing real to compare against (self), and a pinned TPU run names
    # the bench constant — no more implicit pairing.
    anchor_key, anchor_tps = anchors[model_name]
    if swept:
        vs_baseline, vs_key = None, None
    elif platform == "tpu":
        vs_baseline, vs_key = round(tps_chip / anchor_tps, 4), \
            f"bench.{anchor_key}"
    else:
        vs_baseline, vs_key = 1.0, "self"
    result = {
        "metric": "fine-tune tokens/sec/chip (Llama-style %dM, bf16, seq %d)"
                  % (round(params_m), seq),
        **_record_meta(),
        "value": round(tps_chip, 1),
        "unit": "tokens/sec/chip",
        # A swept run measures a DIFFERENT config/workload than the pinned
        # anchor — comparing would misattribute progress, so swept runs
        # carry their knobs in the JSON and no vs_baseline.
        "vs_baseline": vs_baseline,
        "vs_baseline_key": vs_key,
        "step_time_p50_ms": round(p50 * 1e3, 2),
        "n_chips": n_chips,
        "platform": platform,
        "params_m": round(params_m, 1),
        "loss_start": round(loss_start, 4),
        "final_loss": round(final_loss, 4),
        # The backward implementations that ACTUALLY ran (pallas falls back
        # to the einsum spelling on untileable shapes) — keeps
        # round-over-round vs_baseline attributable (ISSUE 2 satellite).
        "bwd_impl": _effective_bwd_impls(cfg, batch, seq, mesh),
        # Phase attribution (ISSUE 3 satellite): where the bench's own wall
        # clock went — conservation-checked buckets, same convention as the
        # trainer's goodput report.
        "goodput": tracker.report(),
        # Step-time anatomy over the timed windows (ISSUE 7): dispatch vs
        # device-blocked decomposition of the p50 the headline divides by.
        "step_anatomy": anatomy.report(),
        **_chaos_result(),
        **_incident_result(_inc0),
    }
    mem = memwatch.report()
    if mem:
        result["memory"] = mem
    if swept:
        result["swept"] = {
            "overrides": list(overrides or []),
            "batch": batch, "seq": seq,
        }
    peak = _peak_flops(jax.devices()[0])
    if peak:
        train_flops_per_token = 3 * _model_flops_per_token(cfg, seq)
        result["mfu"] = round(tps_chip * train_flops_per_token / peak, 4)
        if cost is not None:
            # Roofline from XLA's own cost model (ISSUE 7): cost-counted
            # flops INCLUDE remat recompute, so mfu_cost - mfu is the
            # measured recompute tax; arithmetic intensity + the bandwidth
            # ceiling say which wall the remaining gap sits against.
            result["roofline"] = roofline(
                cost["flops_per_step"], cost.get("bytes_per_step"), p50,
                peak * n_chips,
                (peak_hbm_bw(jax.devices()[0].device_kind) or 0) * n_chips
                or None,
            )
            result["roofline"]["mfu_analytic"] = result["mfu"]
    elif cost is not None:
        # No known peak (CPU smoke): record the raw cost-model numbers so
        # the record format is exercised everywhere the bench runs.
        result["cost"] = {
            k: v for k, v in cost.items() if v is not None
        }
    return result


def main(model_name: str = "350m", overrides: list[str] | None = None,
         batch_override: int = 0, seq_override: int = 0,
         compile_cache_dir: str = "") -> int:
    result = run_train_bench(
        model_name, overrides=overrides, batch_override=batch_override,
        seq_override=seq_override, compile_cache_dir=compile_cache_dir,
    )
    print(json.dumps(result))
    return 0


def _parse_sweep_spec(spec: str) -> list[dict[str, str]]:
    """``"flash_block_q=512,1024;remat=dots,dots_inputs"`` -> the list of
    grid cells (cross-product), each a {field: value} dict. Fields are
    ModelConfig knobs (the ``--override`` namespace) plus the special
    ``batch`` / ``seq`` axes."""
    import itertools

    axes: list[tuple[str, list[str]]] = []
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise SystemExit(
                f"--sweep axis must be field=v1,v2,... got {part!r}"
            )
        key, values = part.split("=", 1)
        vals = [v.strip() for v in values.split(",") if v.strip()]
        if not vals:
            raise SystemExit(f"--sweep axis {key!r} has no values")
        axes.append((key.strip(), vals))
    if not axes:
        raise SystemExit("--sweep spec is empty")
    cells = []
    for combo in itertools.product(*(vals for _, vals in axes)):
        cells.append({k: v for (k, _), v in zip(axes, combo)})
    return cells


def run_sweep(model_name: str, spec: str, out_path: str,
              overrides: list[str] | None = None,
              batch_override: int = 0, seq_override: int = 0,
              compile_cache_dir: str = "") -> int:
    """``bench.py --sweep`` (ISSUE 7 tentpole leg 3): run a dotted-override
    grid, one resumable record per cell, into the versioned sweep JSON at
    ``out_path``. Cells already present in an existing record (same schema)
    are skipped, so a sweep killed at cell k resumes at cell k — on a TPU
    where each cell costs a fresh ~85 s compile, that is the difference
    between a usable overnight grid and a babysat one. Diff two sweeps with
    ``python -m ditl_tpu.telemetry.perf_compare``."""
    import jax

    from ditl_tpu.telemetry.perf import (
        cell_key, load_sweep_record, new_sweep_record, record_sweep_cell,
    )

    cells = _parse_sweep_spec(spec)
    _inc0 = _incidents_now()
    platform = jax.devices()[0].platform
    meta = {"model": model_name, "platform": platform,
            "base_overrides": list(overrides or []),
            "batch": batch_override, "seq": seq_override}
    record = load_sweep_record(out_path)
    if record is not None:
        # Resume only a record measured under the SAME base configuration:
        # cell keys name only the swept knobs, so resuming a 350m record
        # from a 1b3 invocation would silently reuse the other model's
        # numbers — and feed perf_compare wrong-config baselines.
        got = record.get("meta", {})
        mismatch = {k: (got.get(k), v) for k, v in meta.items()
                    if got.get(k) != v}
        if mismatch:
            raise SystemExit(
                f"--sweep-out {out_path} was recorded under a different "
                f"base config ({mismatch}); point --sweep-out elsewhere "
                "or delete the stale record"
            )
    else:
        record = new_sweep_record(f"train-{model_name}", meta=meta)
    completed = skipped = failed = 0
    for cell in cells:
        key = cell_key(cell)
        prior = record["cells"].get(key)
        if prior is not None and "error" not in prior:
            skipped += 1
            print(f"bench: sweep cell [{key}] already recorded — skipping",
                  file=sys.stderr)
            continue
        if prior is not None:
            # An errored cell is retried on resume: the failure may have
            # been transient (host pressure, a preempted chip). A
            # persistent failure just re-records its error — and still
            # fails the run's exit code.
            print(f"bench: sweep cell [{key}] previously FAILED — retrying",
                  file=sys.stderr)
        cell_overrides = list(overrides or [])
        cell_batch, cell_seq = batch_override, seq_override
        for k, v in cell.items():
            if k == "batch":
                cell_batch = int(v)
            elif k == "seq":
                cell_seq = int(v)
            else:
                cell_overrides.append(f"{k}={v}")
        print(f"bench: sweep cell [{key}]", file=sys.stderr)
        try:
            result = run_train_bench(
                model_name, overrides=cell_overrides,
                batch_override=cell_batch, seq_override=cell_seq,
                compile_cache_dir=compile_cache_dir,
            )
        except Exception as e:  # noqa: BLE001 - an OOM cell must not kill
            # the rest of the grid; the failure IS the cell's result.
            result = {"error": f"{type(e).__name__}: {str(e)[:500]}"}
            failed += 1
            print(f"bench: sweep cell [{key}] FAILED {result['error']}",
                  file=sys.stderr)
        else:
            completed += 1
        result["cell"] = dict(cell)
        record = record_sweep_cell(out_path, record, key, result)
    print(json.dumps({
        "metric": f"train sweep ({model_name}, {len(cells)} cell(s))",
        **_record_meta(),
        "value": completed,
        "unit": "cells",
        "vs_baseline": None,
        "vs_baseline_key": None,
        "platform": platform,
        "cells": len(cells),
        "completed": completed,
        "skipped": skipped,
        "failed": failed,
        "out": out_path,
        **_chaos_result(),
        **_incident_result(_inc0),
    }))
    return 0 if failed == 0 else 1


if __name__ == "__main__":
    import argparse

    parser = argparse.ArgumentParser(prog="bench.py")
    parser.add_argument("--infer", action="store_true",
                        help="decode/serving benchmark instead of the "
                        "fine-tune one")
    parser.add_argument("--model", choices=("350m", "1b3"), default="1b3",
                        help="fine-tune bench model size (default: the "
                        "1.27B north-star proxy, 56%% MFU on v5e; the 350M "
                        "r1 continuity config stays available)")
    parser.add_argument("--engine", choices=("lockstep", "continuous"),
                        default="lockstep",
                        help="serving engine for --infer")
    parser.add_argument("--cache", choices=("contiguous", "paged"),
                        default="contiguous",
                        help="KV layout for --infer --engine continuous")
    parser.add_argument("--quantize", choices=("int8",), default=None,
                        help="weight-only quantization (only with --infer)")
    parser.add_argument("--kv-quant", choices=("int8",), default=None,
                        help="int8 KV-cache quantization (only with --infer)")
    parser.add_argument("--speculative", action="store_true",
                        help="speculative decode ticks (--infer --engine "
                        "continuous; A/B against the same command without "
                        "this flag)")
    parser.add_argument("--infer-workload",
                        choices=("random", "repetitive", "bigram"),
                        default="random",
                        help="'repetitive' briefly fine-tunes on a repeated "
                        "pattern and prompts with it — the regime where "
                        "prompt-lookup speculation pays")
    parser.add_argument("--slots", type=int, default=8,
                        help="batch size / continuous-engine slots (--infer)")
    parser.add_argument("--decode-chunk", type=int, default=16,
                        help="decode steps per tick (--infer continuous)")
    parser.add_argument("--page-size", type=int, default=256,
                        help="tokens per KV page (--infer --cache paged)")
    parser.add_argument("--moe", action="store_true",
                        help="MoE bench model (8 experts, top-2) for --infer "
                        "— the Mixtral-style serving path")
    parser.add_argument("--prompt-len", type=int, default=0,
                        help="prompt tokens per request (--infer; 0 = "
                        "workload default — raise for long-context rows, "
                        "e.g. 2048 to reproduce the int8-KV context sweep)")
    parser.add_argument("--max-new", type=int, default=0,
                        help="generated tokens per request (--infer; 0 = "
                        "workload default)")
    parser.add_argument("--temperature", type=float, default=0.0,
                        help="sampling temperature for --infer continuous "
                        "(0 = greedy; >0 with --speculative measures the "
                        "rejection-sampling path)")
    parser.add_argument("--guided", default="",
                        help="grammar-constrained decoding (--infer --engine "
                        "continuous): 'json' = the json_object grammar, "
                        "anything else = a regex; \"(.|\\n)*\" measures the "
                        "FSM machinery's overhead against the same command "
                        "without --guided")
    parser.add_argument("--admission", choices=("reserve", "optimistic"),
                        default="reserve",
                        help="paged admission policy (optimistic: admit past "
                        "worst-case reservation, preempt on exhaustion)")
    parser.add_argument("--pages", type=int, default=0,
                        help="paged pool size override (0 = contiguous-"
                        "equivalent capacity) — shrink to exercise "
                        "optimistic admission under pressure")
    parser.add_argument("--pipeline", action="store_true",
                        help="double-buffered decode ticks on the continuous "
                        "engine (dispatch tick N+1 before fetching tick N)")
    parser.add_argument("--spec-draft", action="store_true",
                        help="model-based speculation (--infer --engine "
                        "continuous --speculative): a ~10x-smaller draft "
                        "model drafts (fine-tuned alongside the target on "
                        "the repetitive workload) instead of prompt lookup")
    parser.add_argument("--serve-replicas", type=int, default=0,
                        help="fleet serving bench (--infer): N in-process "
                        "replicas behind the gateway (ditl_tpu/gateway/); "
                        "records fleet throughput, affinity hit-rate, and "
                        "retry counts in the bench JSON")
    parser.add_argument("--serve-router", default="affinity",
                        choices=("round_robin", "least_outstanding",
                                 "affinity"),
                        help="gateway routing policy for --serve-replicas "
                        "(A/B round_robin vs affinity for the fleet-level "
                        "prefix-cache claim)")
    parser.add_argument("--override", action="append", default=[],
                        metavar="FIELD=VALUE",
                        help="ModelConfig override for the TRAIN bench "
                        "(repeatable), e.g. flash_block_q=2048 — sweep a "
                        "knob without editing the pinned config")
    parser.add_argument("--sweep", default="", metavar="GRID",
                        help="train-bench grid sweep (ISSUE 7): semicolon-"
                        "separated axes of ModelConfig knobs (plus the "
                        "special batch/seq axes), cross-producted, e.g. "
                        "'flash_block_q=512,1024;remat=dots,dots_inputs'. "
                        "One resumable record per cell lands in --sweep-out; "
                        "diff two sweeps with python -m "
                        "ditl_tpu.telemetry.perf_compare")
    parser.add_argument("--sweep-out", default="sweep.json", metavar="PATH",
                        help="versioned sweep-record JSON for --sweep "
                        "(existing cells at the same schema are skipped — "
                        "a killed sweep resumes where it died)")
    parser.add_argument("--batch", type=int, default=0,
                        help="train-bench batch override (0 = config default)")
    parser.add_argument("--seq", type=int, default=0,
                        help="train-bench seq-len override (0 = config default)")
    parser.add_argument("--compile-cache-dir",
                        default="~/.cache/ditl_tpu/xla-cache",
                        help="persistent XLA compilation cache directory "
                        "(on by default — a warm second run skips the "
                        "~85 s compile+first-window; pass '' to disable; "
                        "see docs/troubleshooting.md §20 for staleness)")
    parser.add_argument("--chaos", default="", metavar="SPEC",
                        help="arm the fault plane (ditl_tpu/chaos/) with a "
                        "rule spec, e.g. 'engine.tick:delay@p=0.05,"
                        "delay=0.01' — measure perf UNDER fault; injected-"
                        "fault counts land in the bench JSON so the row "
                        "stays attributable")
    parser.add_argument("--chaos-seed", type=int, default=0,
                        help="fault-plane seed (--chaos): the same seed "
                        "replays the identical fault sequence")
    parser.add_argument("--trace-out", default="", metavar="PATH",
                        help="with --serve-replicas: arm end-to-end request "
                        "tracing (ISSUE 6) across the gateway and every "
                        "replica, and write the merged Chrome-trace/"
                        "Perfetto JSON here (open at ui.perfetto.dev)")
    parser.add_argument("--serve-prefill-chunk", type=int, default=-1,
                        help="with --serve-replicas: chunked-prefill size "
                        "per replica (-1 = pinned page-size-aligned "
                        "default, ON; 0 = whole-prompt prefill — the "
                        "unchunked A/B leg whose interference p95 the "
                        "budgeted default is gated against)")
    parser.add_argument("--serve-token-budget", type=int, default=-1,
                        help="with --serve-replicas: per-tick token budget "
                        "per replica engine (-1 = slots x decode-chunk + "
                        "prefill-chunk, ON; 0 = unbudgeted scheduler)")
    parser.add_argument("--serve-roles", default="", metavar="ROLES",
                        help="with --serve-replicas: heterogeneous fleet "
                        "roles, comma-separated per replica (ISSUE 9), e.g. "
                        "'prefill_heavy,decode_heavy,decode_heavy'; shorter "
                        "specs pad with hybrid, '' = homogeneous. Engine "
                        "knobs derive from the role (gateway/roles.py)")
    parser.add_argument("--serve-mixed-trace", action="store_true",
                        help="with --serve-replicas: add one long batch-"
                        "class prompt per replica alongside the interactive "
                        "short streams — the disagg-vs-homogeneous A/B "
                        "workload; the row gains per-class TTFT/interference "
                        "p95s (interactive pair perf_compare-gated)")
    parser.add_argument(
        "--serve-host-tier-mb", type=float, default=0.0,
        help="arm each replica engine's host-RAM prefix-cache tier "
        "(ISSUE 13) at this capacity; run the same seeded trace with 0 "
        "for the off leg of the tier A/B (perf_compare gates the serving "
        "block's hit ratio + swap_in_p95_s)",
    )
    parser.add_argument(
        "--serve-kv-handoff", action="store_true",
        help="arm prefill->decode KV handoff (ISSUE 13): replicas serve "
        "the /internal KV endpoints and the gateway ships eligible "
        "prefills per its transfer-cost model; the row gains a "
        "schema-stamped kv_handoff block (fallback ratio gated)",
    )
    parser.add_argument("--serve-gateway-overhead", action="store_true",
                        help="gateway data-plane overhead microbench "
                        "(ISSUE 14): closed-loop keep-alive clients vs "
                        "in-process STUB replicas, direct and through the "
                        "gateway — device-noise-free by construction (no "
                        "jax anywhere on the path). The row embeds a "
                        "hoisted gateway_overhead block (requests/sec, "
                        "added-latency p50/p95, pool hit ratio) that "
                        "perf_compare gates; run once with "
                        "--serve-pool-idle 0 for the fresh-connect A/B "
                        "leg")
    parser.add_argument("--serve-usage-metering", action="store_true",
                        help="with --serve-gateway-overhead: run a third "
                        "closed loop through a metering-armed gateway "
                        "(tenant admission + label digests + "
                        "X-Tenant-Label + the gateway-edge usage ledger, "
                        "ISSUE 15); the row gains a usage_metering block "
                        "(gateway_rps_metered / metering_overhead_ratio) "
                        "that perf_compare gates")
    parser.add_argument("--serve-multi-lora", type=int, default=0,
                        metavar="N",
                        help="multi-LoRA serving A/B (--infer, ISSUE 16): "
                        "the same engine/workload run base-only and then "
                        "with a stacked pool of N zero-delta adapter rows "
                        "(zeros rows still pay the per-row gather), plus a "
                        "hot re-publication swap drill through the adapter "
                        "registry; the row embeds a hoisted adapters block "
                        "(adapter_gather_overhead_ratio / adapter_swap_"
                        "p95_s) that perf_compare gates")
    parser.add_argument("--serve-pool-idle", type=int, default=-1,
                        help="with --serve-gateway-overhead: override "
                        "gateway.pool_max_idle_per_replica (0 = pooling "
                        "off, every upstream hop connects fresh — the "
                        "A/B baseline leg; -1 = the config default)")
    parser.add_argument("--serve-overhead-requests", type=int, default=240,
                        help="with --serve-gateway-overhead: total "
                        "closed-loop requests per leg")
    parser.add_argument("--serve-concurrency", type=int, default=0,
                        metavar="N",
                        help="with --serve-gateway-overhead: hold N idle "
                        "SSE streams through the evloop gateway from an "
                        "open-loop selector client (no thread per stream "
                        "on either side, ISSUE 17) and record the "
                        "gateway's max resident thread count in the row; "
                        "the held count is clamped to the RLIMIT_NOFILE "
                        "budget (4 fds/stream in-process) and the clamp "
                        "is recorded, never silent")
    parser.add_argument("--serve-trace-replay", default="", metavar="PATH",
                        help="with --infer --serve-replicas: replay a "
                        "recorded traffic trace (gateway --save-trace "
                        "JSONL, or tests/fixtures/traces/*.jsonl) through "
                        "the fleet with preserved inter-arrival times "
                        "(ISSUE 12); the row embeds replica_seconds + the "
                        "TTFT-SLO violation rate — the autoscaler A/B "
                        "surface perf_compare gates")
    parser.add_argument("--serve-autoscale", action="store_true",
                        help="with --serve-trace-replay: arm the autoscale "
                        "actuator (gateway/autoscale.py) on the replay "
                        "fleet — the ON leg of the on-vs-off A/B")
    parser.add_argument("--serve-min-replicas", type=int, default=1,
                        help="with --serve-autoscale: ordinary scale-down "
                        "floor (autoscale.min_replicas)")
    parser.add_argument("--trace-speed", type=float, default=1.0,
                        help="with --serve-trace-replay: compress the "
                        "recorded inter-arrival offsets by this factor "
                        "(2.0 = replay twice as fast)")
    parser.add_argument("--serve-bulk-backlog", type=int, default=0,
                        metavar="N",
                        help="with --serve-trace-replay: submit an N-item "
                        "offline bulk job (POST /v1/bulk/jobs) before the "
                        "timed replay and soak it through the best_effort "
                        "lane while the interactive trace runs (ISSUE 19); "
                        "the row grows a `bulk` block — lane tokens/sec "
                        "plus the interactive TTFT p95 measured WITH the "
                        "backlog running — that perf_compare gates")
    args = parser.parse_args()
    if args.chaos:
        from ditl_tpu.chaos import FaultPlane, arm

        arm(FaultPlane(seed=args.chaos_seed, rules=args.chaos))
        print(f"bench: chaos armed ({args.chaos!r}, seed {args.chaos_seed})",
              file=sys.stderr)
    if args.serve_gateway_overhead:
        # Host-only (stub replicas, no jax import): dispatched before any
        # device-flag validation on purpose.
        sys.exit(bench_gateway_overhead(
            n_replicas=args.serve_replicas or 2,
            requests=args.serve_overhead_requests,
            pool_max_idle=args.serve_pool_idle,
            usage_metering=args.serve_usage_metering,
            serve_concurrency=args.serve_concurrency,
        ))
    infer_only = (args.quantize or args.kv_quant or args.speculative
                  or args.engine != "lockstep" or args.cache != "contiguous"
                  or args.infer_workload != "random" or args.moe
                  or args.prompt_len or args.max_new or args.guided
                  or args.spec_draft or args.serve_replicas
                  or args.serve_trace_replay or args.serve_multi_lora)
    if infer_only and not args.infer:
        parser.error("serving flags require --infer")
    if args.infer and (args.override or args.batch or args.seq):
        parser.error("--override/--batch/--seq sweep the TRAIN bench only; "
                     "the serving bench has its own knobs (--slots, "
                     "--decode-chunk, --prompt-len, --max-new, ...)")
    if args.sweep and args.infer:
        parser.error("--sweep is a TRAIN-bench grid (the serving bench has "
                     "its own knobs)")
    if args.spec_draft and (not args.speculative
                            or args.engine != "continuous"):
        # Validate HERE, not after bench_infer's expensive fine-tune has
        # already burned minutes of chip time.
        parser.error("--spec-draft needs --speculative --engine continuous")
    if args.trace_out and not args.serve_replicas:
        parser.error("--trace-out requires --infer --serve-replicas (the "
                     "fleet serving bench is the traced path)")
    if args.serve_trace_replay and not (args.infer and args.serve_replicas):
        parser.error("--serve-trace-replay requires --infer "
                     "--serve-replicas N (the fleet it replays against)")
    if args.serve_bulk_backlog and not args.serve_trace_replay:
        parser.error("--serve-bulk-backlog requires --serve-trace-replay "
                     "(the interactive load the lane must not burn)")
    if args.infer and args.serve_multi_lora:
        sys.exit(bench_multi_lora(
            n_adapters=args.serve_multi_lora, slots=args.slots,
            decode_chunk=args.decode_chunk, prompt_len=args.prompt_len,
            max_new=args.max_new,
            compile_cache_dir=args.compile_cache_dir,
        ))
    if args.infer and args.serve_trace_replay:
        sys.exit(bench_trace_replay(
            args.serve_trace_replay, n_replicas=args.serve_replicas,
            slots=args.slots, decode_chunk=args.decode_chunk,
            autoscale=args.serve_autoscale, speed=args.trace_speed,
            min_replicas=args.serve_min_replicas,
            compile_cache_dir=args.compile_cache_dir,
            bulk_backlog=args.serve_bulk_backlog,
        ))
    if args.infer and args.serve_replicas:
        sys.exit(bench_gateway(
            args.serve_replicas, slots=args.slots,
            decode_chunk=args.decode_chunk, prompt_len=args.prompt_len,
            max_new=args.max_new, router=args.serve_router,
            compile_cache_dir=args.compile_cache_dir,
            trace_out=args.trace_out,
            prefill_chunk=args.serve_prefill_chunk,
            token_budget=args.serve_token_budget,
            roles=args.serve_roles,
            mixed_trace=args.serve_mixed_trace,
            host_tier_mb=args.serve_host_tier_mb,
            kv_handoff=args.serve_kv_handoff,
        ))
    if args.infer:
        sys.exit(bench_infer(
            engine=args.engine, cache=args.cache,
            quantize=args.quantize == "int8",
            kv_quant=args.kv_quant == "int8",
            speculative=args.speculative, workload=args.infer_workload,
            slots=args.slots, decode_chunk=args.decode_chunk,
            page_size=args.page_size, moe=args.moe,
            prompt_len=args.prompt_len, max_new=args.max_new,
            temperature=args.temperature, guided=args.guided,
            spec_draft=args.spec_draft, pipeline=args.pipeline,
            admission=args.admission, pages=args.pages,
            compile_cache_dir=args.compile_cache_dir,
        ))
    if args.sweep:
        sys.exit(run_sweep(
            args.model, args.sweep, args.sweep_out,
            overrides=args.override, batch_override=args.batch,
            seq_override=args.seq,
            compile_cache_dir=args.compile_cache_dir,
        ))
    sys.exit(main(args.model, overrides=args.override,
                  batch_override=args.batch, seq_override=args.seq,
                  compile_cache_dir=args.compile_cache_dir))
