"""Batching + host-local -> global device arrays (L3).

The reference's loader is ``DataLoader(batch_size=4, sampler=DistributedSampler)``
feeding a serial per-example loop (ref ``src/distributed_inference.py:59,64-69``)
— the anti-pattern SURVEY.md §7 calls out as 'hard part (c)'. The TPU-native
pipeline instead:

1. shards the dataset per *process* with ``ShardedSampler`` (each host only
   tokenizes its own shard),
2. tokenizes/pads (or packs) into fixed ``(per_host_batch, seq_len)`` int32
   arrays — static shapes so XLA compiles once,
3. assembles a *global* jax.Array sharded over the mesh's batch axes with
   ``jax.make_array_from_process_local_data`` (every host holds only its
   addressable shards),
4. prefetches ahead of the device step (double buffering) so the TPU never
   waits on host tokenization.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Iterator

import numpy as np

from ditl_tpu.chaos import maybe_inject
from ditl_tpu.config import DataConfig
from ditl_tpu.data.dataset import TextDataset
from ditl_tpu.data.sampler import ShardedSampler
from ditl_tpu.data.tokenizer import Tokenizer
from ditl_tpu.runtime.mesh import batch_axes
from ditl_tpu.utils.logging import get_logger

logger = get_logger(__name__)

__all__ = ["DataStallError", "make_global_batch", "DataPipeline"]


class DataStallError(RuntimeError):
    """The training loop waited longer than ``data.data_wait_timeout_s``
    for the prefetch producer to yield a batch. Distinguishes a wedged
    data pipeline (hub stall, hung tokenizer, injected ``hang``) from a
    wedged device program: the exception names the pipeline, carries the
    producer's liveness, and fails the step loop diagnosably instead of
    letting it hang forever (where the only external signal would be a
    heartbeat stall attributing the death to the wrong subsystem)."""


def tokenize_example(
    tok: Tokenizer, text: str, seq_len: int
) -> tuple[np.ndarray, np.ndarray]:
    """[bos] + ids + [eos], truncated/padded to ``seq_len``; mask covers real
    tokens only."""
    ids = [tok.bos_id] + tok.encode(text)[: seq_len - 2] + [tok.eos_id]
    mask = np.zeros(seq_len, dtype=np.float32)
    mask[: len(ids)] = 1.0
    out = np.full(seq_len, tok.pad_id, dtype=np.int32)
    out[: len(ids)] = ids
    return out, mask


def make_global_batch(mesh, host_batch: dict[str, np.ndarray]) -> dict:
    """Form globally-sharded jax.Arrays from per-host numpy batches.

    The leading (batch) dim is sharded over the mesh's ``data``/``fsdp`` axes;
    remaining dims are replicated. This is the TPU analog of 'each rank holds
    its DataLoader batch' — except the result is one logical global array XLA
    can partition against."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    out = {}
    for key, arr in host_batch.items():
        spec = P(batch_axes(), *([None] * (arr.ndim - 1)))
        sharding = NamedSharding(mesh, spec)
        out[key] = jax.make_array_from_process_local_data(sharding, arr)
    return out


class DataPipeline:
    """End-to-end host data pipeline: shard -> tokenize -> batch -> global
    arrays, with epoch reseeding and background prefetch."""

    def __init__(
        self,
        dataset: TextDataset,
        tokenizer: Tokenizer,
        config: DataConfig,
        mesh,
    ):
        import jax

        self.dataset = dataset
        self.tokenizer = tokenizer
        self.config = config
        self.mesh = mesh
        self.process_count = jax.process_count()
        self.process_index = jax.process_index()
        if config.batch_size % self.process_count:
            raise ValueError(
                f"global batch_size {config.batch_size} must divide evenly over "
                f"{self.process_count} processes"
            )
        self.host_batch_size = config.batch_size // self.process_count
        # Batch dim must also divide over the mesh's batch axes for sharding.
        from ditl_tpu.runtime.mesh import data_parallel_size

        dp = data_parallel_size(mesh)
        if config.batch_size % dp:
            raise ValueError(
                f"global batch_size {config.batch_size} must divide evenly over "
                f"data-parallel size {dp} (mesh {dict(mesh.shape)})"
            )
        self.sampler = ShardedSampler(
            dataset_size=len(dataset),
            num_replicas=self.process_count,
            rank=self.process_index,
            shuffle=config.shuffle,
            seed=config.seed,
            drop_last=config.drop_last,
        )
        self._doc_len_cache: dict[int, int] = {}

    @property
    def steps_per_epoch(self) -> int:
        return len(self.sampler) // self.host_batch_size

    def _host_batches(
        self, epoch: int, start_step: int = 0
    ) -> Iterator[dict[str, np.ndarray]]:
        """Per-host numpy batches for one epoch (identical step count on every
        host, by ShardedSampler's equal-split guarantee). ``start_step`` skips
        the first N batches (checkpoint resume) without tokenizing them in the
        padded path; the packed path must still tokenize to keep the stream
        aligned, but skips batch assembly/upload."""
        self.sampler.set_epoch(epoch)
        indices = self.sampler.local_indices()
        seq_len = self.config.seq_len
        if self.config.pack_sequences:
            yield from self._packed_batches(indices, start_step)
            return
        n_full = len(indices) // self.host_batch_size
        for b in range(start_step, n_full):
            chunk = indices[b * self.host_batch_size : (b + 1) * self.host_batch_size]
            ids = np.empty((len(chunk), seq_len), dtype=np.int32)
            mask = np.empty((len(chunk), seq_len), dtype=np.float32)
            labels = np.empty((len(chunk),), dtype=np.int32)
            from ditl_tpu.data.tokenizer import ByteTokenizer
            from ditl_tpu.native import dataprep

            is_byte = isinstance(self.tokenizer, ByteTokenizer)
            for i, idx in enumerate(chunk):
                item = self.dataset[int(idx)]
                if is_byte:  # native C++ tokenize+pad (csrc/dataprep.cpp)
                    tok = self.tokenizer
                    ids[i], mask[i] = dataprep.tokenize_padded(
                        item["text"], seq_len, bos=tok.bos_id, eos=tok.eos_id,
                        pad=tok.pad_id, byte_offset=tok.byte_offset,
                    )
                else:
                    ids[i], mask[i] = tokenize_example(
                        self.tokenizer, item["text"], seq_len
                    )
                labels[i] = item["label"]
            # Segment ids isolate real tokens (1) from padding (0) in attention.
            yield {
                "input_ids": ids,
                "loss_mask": mask,
                "labels": labels,
                "segment_ids": mask.astype(np.int32),
                "positions": np.broadcast_to(
                    np.arange(seq_len, dtype=np.int32), ids.shape
                ).copy(),
            }

    def _packed_batches(
        self, indices: np.ndarray, start_step: int = 0
    ) -> Iterator[dict[str, np.ndarray]]:
        """Sequence packing: concatenate [bos]doc[eos] streams and slice fixed
        rows — no pad waste, fully dense MXU work. Deterministic given the
        epoch's index order.

        SPMD safety: hosts' shards can tokenize to different lengths, so the
        raw per-host row counts can differ — every host therefore computes the
        *global minimum* batch count from all shards (cheap: token counts only,
        no cross-host communication, since every host knows the full
        permutation) and truncates to it, keeping step counts identical.
        """
        tok, seq_len = self.tokenizer, self.config.seq_len
        stream = self._pack_stream(indices)
        rows_total = len(stream) // seq_len
        n_batches = rows_total // self.host_batch_size
        if self.process_count > 1:
            n_batches = min(n_batches, self._global_min_batches())
        arr = stream[: rows_total * seq_len].reshape(rows_total, seq_len)
        # Per-row document segments (1-based) and positions restarting at each
        # bos — native C++ when available, numpy otherwise (same semantics).
        from ditl_tpu.native import dataprep

        segments, positions = dataprep.segments_positions(arr, bos=tok.bos_id)
        for b in range(start_step, n_batches):
            sl = slice(b * self.host_batch_size, (b + 1) * self.host_batch_size)
            yield {
                "input_ids": arr[sl],
                "loss_mask": np.ones_like(arr[sl], dtype=np.float32),
                "labels": np.zeros((arr[sl].shape[0],), dtype=np.int32),
                "segment_ids": segments[sl],
                "positions": positions[sl],
            }

    def _pack_stream(self, indices: np.ndarray) -> np.ndarray:
        """Tokenized [bos]doc[eos] stream for this shard. The byte tokenizer
        goes through the native C++ path (csrc/dataprep.cpp) — the host-side
        hot loop, SURVEY.md §7 hard part (c); other tokenizers (HF: their own
        native code) take the generic path."""
        from ditl_tpu.data.tokenizer import ByteTokenizer
        from ditl_tpu.native import dataprep

        tok = self.tokenizer
        texts = [self.dataset[int(idx)]["text"] for idx in indices]
        if isinstance(tok, ByteTokenizer):
            return dataprep.pack_stream(
                texts, bos=tok.bos_id, eos=tok.eos_id, byte_offset=tok.byte_offset
            )
        stream: list[int] = []
        for text in texts:
            stream.extend([tok.bos_id] + tok.encode(text) + [tok.eos_id])
        return np.asarray(stream, dtype=np.int32)

    def _doc_token_count(self, idx: int) -> int:
        """Tokenized length of one document incl. bos/eos. Cached: document
        lengths are epoch-invariant (only the permutation reshuffles), so the
        global batch-count scan must not re-tokenize the dataset every epoch."""
        cached = self._doc_len_cache.get(idx)
        if cached is None:
            cached = len(self.tokenizer.encode(self.dataset[idx]["text"])) + 2
            self._doc_len_cache[idx] = cached
        return cached

    def _global_min_batches(self) -> int:
        """Minimum packed batch count over all hosts' shards. Every host can
        compute every shard's token count locally (the permutation is shared),
        so this needs no collective."""
        seq_len = self.config.seq_len
        perm = self.sampler.global_permutation()
        counts = []
        for rank in range(self.process_count):
            shard = perm[rank :: self.process_count]
            tokens = sum(self._doc_token_count(int(i)) for i in shard)
            counts.append((tokens // seq_len) // self.host_batch_size)
        return min(counts)

    def _chaos_batches(
        self, epoch: int, start_step: int
    ) -> Iterator[dict[str, np.ndarray]]:
        """Host batches with the chaos seam applied (runs on the PREFETCH
        PRODUCER thread, so injected errors/hangs exercise the real
        cross-thread propagation path): ``error`` raises InjectedFault into
        the consumer, ``hang`` wedges the producer (the data-wait timeout's
        drill), ``corrupt`` zeroes the batch's tokens (garbage data, valid
        shapes — the silent-corruption class)."""
        for i, hb in enumerate(self._host_batches(epoch, start_step)):
            fault = maybe_inject("data.batch", request=start_step + i)
            if fault is not None and fault.action == "corrupt":
                hb = dict(hb)
                hb["input_ids"] = np.zeros_like(hb["input_ids"])
            yield hb

    def epoch(self, epoch: int, start_step: int = 0) -> Iterator[dict]:
        """Globally-sharded batches for one epoch, with prefetch."""
        yield from _prefetch(
            (
                make_global_batch(self.mesh, hb)
                for hb in self._chaos_batches(epoch, start_step)
            ),
            self.config.prefetch,
            timeout_s=self.config.data_wait_timeout_s,
        )

    def __iter__(self) -> Iterator[dict]:
        """Infinite stream across epochs (epoch-seeded reshuffle each pass)."""
        epoch = 0
        while True:
            yield from self.epoch(epoch)
            epoch += 1


def _prefetch(it: Iterator, depth: int, timeout_s: float = 0.0) -> Iterator:
    """Background-thread prefetch of up to ``depth`` items (device transfer is
    async in JAX, so buffering the host side is enough for double buffering).

    Producer exceptions (tokenizer bugs, injected faults) propagate to the
    consumer — the iterator never ends silently because the producer died.
    ``timeout_s > 0`` additionally bounds how long the consumer may block
    waiting for ONE item: past it, a :class:`DataStallError` names the
    pipeline as the wedged subsystem (a producer that is alive-but-hung —
    e.g. a stalled hub read — produces no exception to propagate, and
    without the bound the step loop would hang forever). No prefetch
    thread (``depth <= 0``) means no cross-thread seam to time out;
    the producer runs inline and its exceptions are the consumer's.

    Abandoning the returned generator (partial consumption + ``close()`` /
    garbage collection) stops the worker thread — without that, every
    partially-read epoch (validation loops!) would leak a blocked thread
    pinning ``depth`` device batches."""
    if depth <= 0:
        yield from it
        return
    queue: collections.deque = collections.deque()
    lock = threading.Condition()
    done = object()
    failed = object()
    stop = False

    def worker():
        try:
            for item in it:
                with lock:
                    while len(queue) >= depth and not stop:
                        lock.wait()
                    if stop:
                        return
                    queue.append(item)
                    lock.notify_all()
        except BaseException as e:  # surface producer errors to the consumer
            with lock:
                queue.append((failed, e))
                lock.notify_all()
            return
        with lock:
            queue.append(done)
            lock.notify_all()

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    try:
        while True:
            with lock:
                t_wait0 = time.monotonic()
                while not queue:
                    if timeout_s > 0:
                        remaining = timeout_s - (time.monotonic() - t_wait0)
                        if remaining <= 0:
                            raise DataStallError(
                                f"data pipeline produced no batch for "
                                f"{timeout_s:.1f}s (producer thread "
                                f"{'alive' if t.is_alive() else 'dead'}, "
                                f"prefetch depth {depth}); the data side is "
                                "wedged — see data.data_wait_timeout_s"
                            )
                        lock.wait(timeout=remaining)
                    else:
                        lock.wait()
                item = queue.popleft()
                lock.notify_all()
            if item is done:
                return
            if isinstance(item, tuple) and len(item) == 2 and item[0] is failed:
                raise item[1]
            yield item
    finally:
        with lock:
            stop = True
            lock.notify_all()
