"""Tokenizers (L3).

The reference has no tokenizer — raw review text goes to the remote API and
the 'device op' encodes characters as ``float(ord(c))`` (ref
``src/utils.py:25-28``). A real on-TPU fine-tune needs token ids, so:

- ``ByteTokenizer``: dependency-free UTF-8 byte-level tokenizer (vocab 256 +
  specials) — the default for tests/benchmarks; deterministic and hub-free.
- ``get_tokenizer``: resolves ``DataConfig.tokenizer`` to either the byte
  tokenizer or a HF ``AutoTokenizer`` (for Llama-3.1 runs with the real vocab).

Both expose the same tiny surface: ``vocab_size``, ``encode``, ``decode``,
``pad_id``, ``bos_id``, ``eos_id``.
"""

from __future__ import annotations

from typing import Protocol, Sequence

__all__ = ["Tokenizer", "ByteTokenizer", "HFTokenizer", "check_vocab",
           "get_tokenizer"]


class Tokenizer(Protocol):
    vocab_size: int
    pad_id: int
    bos_id: int
    eos_id: int

    def encode(self, text: str) -> list[int]: ...

    def decode(self, ids: Sequence[int]) -> str: ...


class ByteTokenizer:
    """UTF-8 bytes shifted by the number of special tokens."""

    def __init__(self):
        self.pad_id = 0
        self.bos_id = 1
        self.eos_id = 2
        self.byte_offset = 3  # id of byte b is b + byte_offset (public:
        # the native packer, loader, and tests key off it)
        self.vocab_size = 256 + self.byte_offset

    def encode(self, text: str) -> list[int]:
        return [b + self.byte_offset for b in text.encode("utf-8")]

    def decode(self, ids: Sequence[int]) -> str:
        # Skip specials and out-of-vocab ids (a model head can be wider than
        # the tokenizer — e.g. vocab padded up for MXU tiling).
        data = bytes(
            i - self.byte_offset
            for i in ids
            if self.byte_offset <= i < self.byte_offset + 256
        )
        return data.decode("utf-8", errors="replace")


class HFTokenizer:
    """Thin adapter over ``transformers.AutoTokenizer``."""

    def __init__(self, name: str):
        from transformers import AutoTokenizer

        self._tok = AutoTokenizer.from_pretrained(name)
        self.vocab_size = len(self._tok)
        # `is not None`, not `or`: token id 0 is a legitimate special token.
        self.bos_id = self._tok.bos_token_id if self._tok.bos_token_id is not None else 1
        self.eos_id = self._tok.eos_token_id if self._tok.eos_token_id is not None else 2
        self.pad_id = (
            self._tok.pad_token_id if self._tok.pad_token_id is not None else self.eos_id
        )

    def encode(self, text: str) -> list[int]:
        return self._tok.encode(text, add_special_tokens=False)

    def decode(self, ids: Sequence[int]) -> str:
        # Drop ids outside the tokenizer's table: a model head can be wider
        # than the tokenizer (vocab padded for MXU tiling, or Llama-3.1's
        # reserved rows), and an undertrained model can emit those ids —
        # HF decode would raise/garble instead of skipping.
        return self._tok.decode([i for i in ids if 0 <= i < self.vocab_size])


def check_vocab(tokenizer: Tokenizer, model_vocab: int, where: str) -> None:
    """Padded-vocab seam validation (one rule everywhere): a tokenizer
    WIDER than the model head means ids the model cannot embed — hard
    error; a model head wider than the tokenizer is legitimate (padding /
    reserved rows) — the decode paths skip those ids and grammar tables
    mask them, so it only logs."""
    tv = tokenizer.vocab_size
    if tv > model_vocab:
        raise ValueError(
            f"{where}: tokenizer vocab {tv} exceeds the model's "
            f"{model_vocab} — prompts could contain ids the embedding "
            f"table does not have"
        )
    if tv < model_vocab:
        from ditl_tpu.utils.logging import get_logger

        get_logger(__name__).info(
            "%s: model head (%d) wider than tokenizer (%d): padded/"
            "reserved rows; out-of-table ids are skipped on decode and "
            "masked in grammar tables", where, model_vocab, tv,
        )


def get_tokenizer(name: str = "byte") -> Tokenizer:
    if name == "byte":
        return ByteTokenizer()
    return HFTokenizer(name)
