"""Rank/world-size-aware index sharding with epoch-seeded shuffling.

Faithful reimplementation of the semantics the reference gets from
``torch.utils.data.DistributedSampler(custom_dataset, num_replicas=world_size,
rank=rank)`` + ``sampler.set_epoch(epoch)`` (ref
``src/distributed_inference.py:58,63``), without torch:

- **Equal split**: every replica yields exactly ``ceil(N / num_replicas)``
  indices (``floor`` with ``drop_last``), so SPMD step counts agree across
  hosts — a hard requirement on TPU where a straggler with one extra batch
  deadlocks every collective.
- **Padding**: when ``N % num_replicas != 0`` the index list is extended by
  repeating leading indices (torch's documented behavior); ``drop_last``
  truncates instead.
- **Interleaved assignment**: replica ``r`` takes ``indices[r::num_replicas]``.
- **Epoch-seeded shuffle**: permutation seeded by ``seed + epoch`` so every
  replica computes the same global permutation each epoch without
  communication, and order is reproducible across world sizes.
"""

from __future__ import annotations

import numpy as np

__all__ = ["ShardedSampler"]


class ShardedSampler:
    def __init__(
        self,
        dataset_size: int,
        num_replicas: int,
        rank: int,
        shuffle: bool = True,
        seed: int = 0,
        drop_last: bool = False,
    ):
        if not 0 <= rank < num_replicas:
            raise ValueError(f"rank {rank} out of range for {num_replicas} replicas")
        if dataset_size <= 0:
            raise ValueError("dataset_size must be positive")
        self.dataset_size = dataset_size
        self.num_replicas = num_replicas
        self.rank = rank
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.epoch = 0
        if drop_last and dataset_size % num_replicas:
            self.num_samples = dataset_size // num_replicas
        else:
            self.num_samples = -(-dataset_size // num_replicas)  # ceil
        self.total_size = self.num_samples * self.num_replicas

    def set_epoch(self, epoch: int) -> None:
        """Reseed the shuffle for a new epoch (ref ``:63``)."""
        self.epoch = epoch

    def global_permutation(self) -> np.ndarray:
        """The full (padded/truncated) index order for this epoch — identical
        on every replica by construction."""
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self.epoch)
            indices = rng.permutation(self.dataset_size)
        else:
            indices = np.arange(self.dataset_size)
        if not self.drop_last and self.total_size > len(indices):
            pad = self.total_size - len(indices)
            # Repeat from the front; tile in case num_replicas > dataset_size.
            reps = -(-pad // len(indices))
            indices = np.concatenate([indices, np.tile(indices, reps)[:pad]])
        return indices[: self.total_size]

    def local_indices(self) -> np.ndarray:
        """This replica's shard: every ``num_replicas``-th index."""
        return self.global_permutation()[self.rank :: self.num_replicas]

    def __iter__(self):
        return iter(self.local_indices().tolist())

    def __len__(self) -> int:
        return self.num_samples
