from ditl_tpu.data.dataset import TextDataset, load_text_dataset, synthetic_dataset  # noqa: F401
from ditl_tpu.data.sampler import ShardedSampler  # noqa: F401
from ditl_tpu.data.tokenizer import ByteTokenizer, get_tokenizer  # noqa: F401
from ditl_tpu.data.loader import DataPipeline, make_global_batch  # noqa: F401
