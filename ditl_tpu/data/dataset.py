"""Datasets (L3).

``TextDataset`` is the parity surface for the reference's ``CustomDataset`` —
a map-style dataset over parallel ``texts``/``labels`` lists whose items are
``{"text": ..., "label": ...}`` dicts (ref ``src/distributed_inference.py:23-32``).

``load_text_dataset`` covers the ingestion call
``load_dataset("imdb", split="train[:1%]")`` (ref ``:56-57``) and degrades to a
deterministic synthetic corpus when the HF hub is unreachable or
``DataConfig.synthetic`` is set, so tests and airgapped TPU VMs stay hermetic.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from ditl_tpu.config import DataConfig
from ditl_tpu.utils.logging import get_logger

logger = get_logger(__name__)

__all__ = ["TextDataset", "load_text_dataset", "synthetic_dataset"]

_WORDS = (
    "the a this that movie film plot acting director scene story truly utterly "
    "remarkably painfully good bad great terrible brilliant dull vivid flat "
    "masterpiece disaster delight bore triumph mess loved hated enjoyed endured "
    "recommend avoid rewatch forget".split()
)


class TextDataset:
    """Map-style dataset over parallel text/label sequences."""

    def __init__(self, texts: Sequence[str], labels: Sequence[int]):
        if len(texts) != len(labels):
            raise ValueError(
                f"texts ({len(texts)}) and labels ({len(labels)}) must be parallel"
            )
        self.texts = list(texts)
        self.labels = list(labels)

    def __len__(self) -> int:
        return len(self.texts)

    def __getitem__(self, idx: int) -> dict:
        return {"text": self.texts[idx], "label": self.labels[idx]}


def synthetic_dataset(n_examples: int = 256, seed: int = 0) -> TextDataset:
    """Deterministic IMDB-shaped sentiment corpus (text + binary label)."""
    rng = np.random.default_rng(seed)
    texts, labels = [], []
    for _ in range(n_examples):
        label = int(rng.integers(0, 2))
        n_words = int(rng.integers(16, 96))
        words = rng.choice(_WORDS, size=n_words).tolist()
        sentiment = "I loved it." if label else "I hated it."
        texts.append(" ".join(words) + " " + sentiment)
        labels.append(label)
    return TextDataset(texts, labels)


def load_text_dataset(config: DataConfig) -> TextDataset:
    """HF-hub ingestion with a hermetic fallback."""
    if config.synthetic:
        return synthetic_dataset(config.synthetic_examples, config.seed)
    try:
        from datasets import load_dataset

        ds = load_dataset(config.dataset_name, split=config.dataset_split)
        return TextDataset(ds[config.text_column], ds[config.label_column])
    except Exception as e:  # hub unreachable / dataset missing
        logger.warning(
            "load_dataset(%r, %r) failed (%s); using synthetic corpus",
            config.dataset_name,
            config.dataset_split,
            e,
        )
        return synthetic_dataset(config.synthetic_examples, config.seed)
