"""Process-0-gated logging (L0).

Parity surface: ``setup_logging()`` (ref ``src/utils.py:5-10``) configured
INFO-level timestamped logging, and the driver gated per-example output on
``rank == 0`` (ref ``src/distributed_inference.py:71-76``). Here the gating is
built into the logger itself so every module gets it for free: non-zero
processes log only WARNING and above unless ``all_processes=True``.
"""

from __future__ import annotations

import logging
import sys

_FORMAT = "%(asctime)s - %(levelname)s - [p%(process_index)s] %(name)s - %(message)s"
_handler: logging.Handler | None = None


class _ProcessIndexFilter(logging.Filter):
    """Injects the JAX process index into every record (lazily — jax may not be
    initialized when logging is configured)."""

    def filter(self, record: logging.LogRecord) -> bool:
        record.process_index = _process_index()
        return True


def _process_index() -> int:
    try:
        import jax

        return jax.process_index()
    except Exception:
        return 0


def setup_logging(level: str = "INFO", all_processes: bool = False) -> None:
    """Configure root logging. On processes != 0, raise the threshold to
    WARNING (the reference's ``if rank == 0`` gate, made structural).

    Re-entrant and embedding-safe: we track OUR OWN handler and replace only
    it on reconfiguration. The old behavior cleared root handlers only when
    we had already configured once, so under pytest (which installs its own
    capture handler first) or any embedding app, the first setup_logging
    added a second root handler and every record was emitted twice — and a
    re-setup would wipe the HOST's handlers (ISSUE 3 satellite)."""
    global _handler
    effective = level.upper()
    if not all_processes and _process_index() != 0:
        effective = "WARNING"
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(logging.Formatter(_FORMAT))
    handler.addFilter(_ProcessIndexFilter())
    root = logging.getLogger()
    if _handler is not None and _handler in root.handlers:
        root.removeHandler(_handler)
    root.addHandler(handler)
    root.setLevel(effective)
    _handler = handler


def get_logger(name: str) -> logging.Logger:
    return logging.getLogger(name)
