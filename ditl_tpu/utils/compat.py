"""Pinned-toolchain version shims.

The repo targets current jax APIs (``jax.shard_map``, pallas
``CompilerParams``, orbax metadata wrappers); the baked image can pin an
older toolchain where those live under their pre-promotion names. Each shim
prefers the modern spelling and falls back, so the code reads current and
still runs on the pinned versions. Keep these thin: one public name per
drifted API, no behavior of our own.
"""

from __future__ import annotations

__all__ = ["axis_size", "shard_map", "tpu_compiler_params"]


def axis_size(axis_name) -> int:
    """Static size of a named mesh axis inside a shard_map body:
    ``jax.lax.axis_size`` (modern) or the ambient axis env (older jax)."""
    import jax

    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    from jax._src import core

    return core.axis_frame(axis_name)


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True, **kw):
    """``jax.shard_map`` (modern) or ``jax.experimental.shard_map.shard_map``
    (older jax, where ``check_vma`` was still called ``check_rep``)."""
    import jax

    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma, **kw,
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma, **kw,
    )


def tpu_compiler_params(**kw):
    """``pltpu.CompilerParams`` (modern) / ``pltpu.TPUCompilerParams``
    (older jax) with identical field names."""
    import jax.experimental.pallas.tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
    return cls(**kw)
