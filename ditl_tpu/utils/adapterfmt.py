"""Adapter-only checkpoint format (ISSUE 16): the on-disk contract between
the trainer (writer, train/adapter_export.py), the serving registry
(loader, infer/adapters.py), and the gateway publisher (verifier,
gateway/publish.py).

One directory per published adapter version:

    <dir>/adapter.npz        flat ``<target>.a`` / ``<target>.b`` arrays,
                             each (L, d, r) / (L, r, f) — the SAME leaf
                             shapes ``models/lora.init_lora_params`` emits
    <dir>/adapter_meta.json  name/step/geometry (rank, alpha, targets,
                             hidden/layer dims, dtype) — verified against
                             the serving model BEFORE any bytes reach HBM
    <dir>/ditl_manifest.json the PR 5 checkpoint manifest ({"step": N,
                             "files": {rel: {size, crc32}}}), written
                             LAST via tmp+rename: its presence commits
                             the version, its absence (or any size/crc
                             mismatch) marks it torn

and an atomic ``LATEST`` pointer file next to the version dirs so a
publisher polling ``<root>/<name>/LATEST`` never reads a half-written
step directory.

Deliberately stdlib+numpy only (no jax anywhere): the gateway publisher
verifies checkpoints from inside a jax-free zone (the import-layering
analysis rule), and the loader wants to crc the EXACT bytes it will ship
to the device, which means hashing host buffers, not traced arrays.
"""

from __future__ import annotations

import io
import json
import os
import zlib

__all__ = [
    "ADAPTER_FILE",
    "LATEST_NAME",
    "MANIFEST_NAME",
    "META_NAME",
    "file_crc32",
    "read_meta",
    "resolve_latest",
    "verify_and_read",
    "verify_dir",
    "write_adapter_dir",
    "write_latest",
]

# Mirrors train/checkpoint.MANIFEST_NAME (that module imports orbax/jax at
# module level; this one must stay importable from the jax-free zones).
MANIFEST_NAME = "ditl_manifest.json"
META_NAME = "adapter_meta.json"
ADAPTER_FILE = "adapter.npz"
LATEST_NAME = "LATEST"


def file_crc32(path: str) -> int:
    crc = 0
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            crc = zlib.crc32(chunk, crc)
    return crc


def _atomic_write(path: str, data: bytes) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def write_adapter_dir(directory: str, *, name: str, step: int,
                      arrays: dict, meta: dict) -> str:
    """Commit one adapter version: npz + meta, then the manifest LAST
    (tmp+rename) — a crash at any point leaves either a complete verified
    version or one :func:`verify_dir` rejects. ``arrays`` maps flat
    ``target.leaf`` keys to numpy arrays; ``meta`` carries the geometry
    (merged over name/step here)."""
    import numpy as np

    os.makedirs(directory, exist_ok=True)
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    _atomic_write(os.path.join(directory, ADAPTER_FILE), buf.getvalue())
    meta_bytes = json.dumps(
        {**meta, "name": name, "step": int(step)},
        indent=2, sort_keys=True,
    ).encode()
    _atomic_write(os.path.join(directory, META_NAME), meta_bytes)
    files = {
        rel: {
            "size": os.path.getsize(os.path.join(directory, rel)),
            "crc32": file_crc32(os.path.join(directory, rel)),
        }
        for rel in (ADAPTER_FILE, META_NAME)
    }
    _atomic_write(
        os.path.join(directory, MANIFEST_NAME),
        json.dumps({"step": int(step), "files": files},
                   indent=2, sort_keys=True).encode(),
    )
    return directory


def write_latest(root: str, version_dir: str) -> None:
    """Atomically point ``<root>/LATEST`` at ``version_dir`` (stored
    relative when possible so the tree can be moved/mounted elsewhere)."""
    rel = os.path.relpath(version_dir, root)
    target = version_dir if rel.startswith("..") else rel
    _atomic_write(os.path.join(root, LATEST_NAME),
                  (target + "\n").encode())


def resolve_latest(path: str) -> str:
    """Follow a ``LATEST`` pointer if ``path`` carries one; otherwise
    ``path`` itself is the version dir."""
    latest = os.path.join(path, LATEST_NAME)
    if os.path.isfile(latest):
        with open(latest) as f:
            target = f.read().strip()
        if target:
            return target if os.path.isabs(target) \
                else os.path.join(path, target)
    return path


def read_meta(directory: str) -> dict:
    with open(os.path.join(directory, META_NAME)) as f:
        meta = json.load(f)
    if not isinstance(meta, dict):
        raise ValueError(f"adapter meta is not an object: {directory}")
    return meta


def verify_dir(directory: str) -> tuple[str, str]:
    """``("verified", "")`` when the manifest exists and every listed file
    matches its recorded size AND crc32; otherwise ``("corrupt", why)``
    (missing manifest counts as corrupt: an adapter version is only
    committed once its manifest lands — the PR 5 torn-save rule)."""
    manifest_path = os.path.join(directory, MANIFEST_NAME)
    if not os.path.isfile(manifest_path):
        return "corrupt", f"no {MANIFEST_NAME} (torn or foreign dir)"
    try:
        with open(manifest_path) as f:
            manifest = json.load(f)
        files = manifest["files"]
    except (OSError, ValueError, KeyError, TypeError) as e:
        return "corrupt", f"unreadable manifest: {e}"
    for rel, want in sorted(files.items()):
        path = os.path.join(directory, rel)
        if not os.path.isfile(path):
            return "corrupt", f"missing {rel}"
        size = os.path.getsize(path)
        if size != want.get("size"):
            return "corrupt", (
                f"{rel}: size {size} != manifest {want.get('size')}")
        crc = file_crc32(path)
        if crc != want.get("crc32"):
            return "corrupt", (
                f"{rel}: crc32 {crc:#010x} != manifest "
                f"{int(want.get('crc32', 0)):#010x}")
    return "verified", ""


def verify_and_read(directory: str, *, flip_byte: bool = False) -> dict:
    """Manifest-verify ``directory`` and return its npz arrays as a dict —
    crc'd over the EXACT bytes that will be decoded, read once. Raises
    ``ValueError`` on any mismatch (the caller maps that to a clean load
    refusal; corrupt bytes must never reach the device). ``flip_byte``
    is the chaos ``adapter.load:corrupt`` hook: one bit of the adapter
    payload flips AFTER the disk read, exactly the torn-transfer the crc
    exists to catch."""
    import numpy as np

    status, why = verify_dir(directory)
    if status != "verified":
        raise ValueError(f"adapter checkpoint {directory}: {why}")
    with open(os.path.join(directory, MANIFEST_NAME)) as f:
        manifest = json.load(f)
    want = manifest["files"][ADAPTER_FILE]
    with open(os.path.join(directory, ADAPTER_FILE), "rb") as f:
        raw = f.read()
    if flip_byte and raw:
        mid = len(raw) // 2
        raw = raw[:mid] + bytes([raw[mid] ^ 0x40]) + raw[mid + 1:]
    if len(raw) != want["size"] or zlib.crc32(raw) != want["crc32"]:
        raise ValueError(
            f"adapter checkpoint {directory}: {ADAPTER_FILE} bytes do not "
            f"match the manifest crc (torn write or corrupt transfer)")
    with np.load(io.BytesIO(raw)) as z:
        return {k: z[k] for k in z.files}
