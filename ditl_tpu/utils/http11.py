"""HTTP/1.1 keep-alive plumbing shared by the replica server and the
gateway (ISSUE 14).

The stdlib ``BaseHTTPRequestHandler`` defaults to HTTP/1.0, which closes
the client connection after every response — the keep-alive the gateway's
per-request-id comments always assumed never actually happened, and every
upstream hop paid a fresh TCP connect. :class:`KeepAliveHandlerMixin`
flips a handler to real HTTP/1.1 (every non-streaming response in this
tree already sends ``Content-Length``; SSE responses opt out with an
explicit ``Connection: close``) and cooperates with the server's drain
lifecycle:

- While a connection is *parked* — the handler thread blocked in
  ``readline`` waiting for the next request on a kept-alive socket — the
  mixin reports it to the server (``note_parked``), so ``drain()`` can
  sever exactly the idle connections without touching in-flight requests.
  Without this, a draining replica wedges: its ``close(drain=True)``
  completes but the peer's pooled sockets keep handler threads parked
  forever, and a request relayed onto one post-drain would be served by a
  replica the fleet believes is gone.
- Once the server is draining, every response closes its connection
  (``close_connection``) so no NEW parked connections accumulate.

stdlib-only on purpose: the gateway package (provably jax-free on import)
and the jax-laden replica server both use it.
"""

from __future__ import annotations

__all__ = ["KeepAliveHandlerMixin"]


class KeepAliveHandlerMixin:
    """Mix into a ``BaseHTTPRequestHandler`` subclass (FIRST in the MRO)
    to serve real HTTP/1.1 keep-alive. Handlers must send
    ``Content-Length`` on every response or an explicit
    ``Connection: close`` (SSE) — the stdlib honors the latter via
    ``send_header``."""

    protocol_version = "HTTP/1.1"
    # Keep-alive makes Nagle's algorithm a per-request tax: the stdlib
    # writes response headers and body as separate small segments, and on
    # a kept-alive connection the second segment sits behind the peer's
    # delayed ACK (~40 ms on Linux) because the connection never closes to
    # flush it. socketserver honors this flag with TCP_NODELAY at setup.
    disable_nagle_algorithm = True
    # Idle cap: a kept-alive connection whose peer goes silent would
    # otherwise pin a handler thread and an FD FOREVER (HTTP/1.0 closed
    # per response; the gateway's public listener has no drain/sever
    # path). socketserver applies this as the socket timeout and the
    # stdlib's handle_one_request treats the timeout as close-on-idle.
    # Comfortably above the upstream pool's default max_age_s (30 s) so
    # the pool rotates connections on its own terms, not the server's.
    timeout = 120.0

    def handle_one_request(self):
        # The blocked-on-readline window IS the parked state: mark it for
        # the server's drain sweep, and clear it the moment a request line
        # parses (parse_request below) so an in-flight request is never
        # severed as "idle". Servers without parked tracking (stubs, the
        # gateway's own listener) simply don't expose note_parked.
        note = getattr(self.server, "note_parked", None)
        if note is not None:
            note(self.connection, True)
        try:
            super().handle_one_request()
        finally:
            if note is not None:
                note(self.connection, False)
            if getattr(self.server, "draining", False):
                # No new parked connections once draining: the response
                # that just went out is this connection's last.
                self.close_connection = True

    def parse_request(self):
        note = getattr(self.server, "note_parked", None)
        if note is not None:
            note(self.connection, False)
        return super().parse_request()
