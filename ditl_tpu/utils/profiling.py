"""Tracing / profiling (SURVEY.md §5: absent in the reference, whose
monitoring story is 'check console output' + nvidia-smi, ref
``docs/setup_guide.md:68-71``).

Two mechanisms, both process-0-gated and off by default:

- ``jax.profiler.start_server(port)`` (runtime/distributed.py, config
  ``runtime.profiler_port``) — live capture from TensorBoard/XProf.
- ``StepProfiler`` (here) — programmatic capture of a step window
  [``profile_start_step``, ``profile_start_step + profile_num_steps``) to
  ``profile_dir``, viewable in TensorBoard. Each step inside the window is
  wrapped in a ``StepTraceAnnotation`` so XProf's step view lines up with
  train steps. Capturing a *window* (not the whole run) keeps trace files
  bounded and skips the untypical compile step.
"""

from __future__ import annotations

import jax

from ditl_tpu.utils.logging import get_logger

logger = get_logger(__name__)

__all__ = ["StepProfiler", "annotate_step"]


def annotate_step(step: int):
    """Context manager naming this step in the trace timeline."""
    return jax.profiler.StepTraceAnnotation("train_step", step_num=step)


class StepProfiler:
    """Captures steps [start, start+num) to ``directory`` on process 0.

    Usage (trainer loop):
        prof.maybe_start(global_step)
        with prof.annotate(global_step):
            state, metrics = train_step(state, batch)
        prof.maybe_stop(global_step)

    ``tracer`` (telemetry/tracing.py, ISSUE 6 satellite): when armed, the
    capture window is recorded as a ``profiler.capture`` span in the
    training journal — the xprof window shows up ON the merged timeline
    (with its step range and output dir) instead of existing only as a
    goodput bucket.
    """

    def __init__(self, directory: str, start_step: int, num_steps: int = 3,
                 tracer=None):
        self.directory = directory
        self.start_step = start_step
        self.num_steps = num_steps
        self._active = False
        self._done = False
        self._stop_after = start_step + num_steps - 1
        self._enabled = bool(directory) and num_steps > 0 and jax.process_index() == 0
        self._tracer = tracer
        self._span_t0 = 0.0
        self._window_start = 0

    def maybe_start(self, step: int) -> None:
        # >= not ==: a resumed run whose restored step is already past
        # start_step still gets its window (shifted to the resume point).
        if self._enabled and not self._active and not self._done and step >= self.start_step:
            import time as _time

            jax.profiler.start_trace(self.directory)
            self._active = True
            self._stop_after = step + self.num_steps - 1
            self._span_t0 = _time.time()
            self._window_start = step
            logger.info(
                "profiler: tracing steps %d..%d to %s",
                step, self._stop_after, self.directory,
            )

    def _trace_bytes(self) -> int:
        """Total bytes of trace artifacts under ``directory`` — the size of
        what this capture wrote to disk (xplane.pb + json sidecars)."""
        import os

        total = 0
        try:
            for root, _dirs, files in os.walk(self.directory):
                for name in files:
                    try:
                        total += os.path.getsize(os.path.join(root, name))
                    except OSError:
                        continue
        except OSError:
            pass
        return total

    def _record_span(self, last_step: int, partial: bool) -> None:
        """ISSUE 7 satellite: the span carries the capture's measured wall
        (``capture_s`` — start_trace through the trace write; the goodput
        ``profiler`` bucket the trainer tracks covers the same interval, so
        the overhead is attributable instead of vanishing into ``other``)
        and the on-disk trace size (``trace_bytes``)."""
        if self._tracer is None or not getattr(self._tracer, "armed", False):
            return
        import time as _time

        self._tracer.start_span(
            "profiler.capture", t0=self._span_t0,
            start_step=self._window_start, last_step=last_step,
            directory=self.directory, partial=partial,
            capture_s=round(_time.time() - self._span_t0, 6),
            trace_bytes=self._trace_bytes(),
        ).end()

    def annotate(self, step: int):
        if self._active:
            return annotate_step(step)
        import contextlib

        return contextlib.nullcontext()

    def maybe_stop(self, step: int) -> None:
        """``step`` is the LAST completed step since ``maybe_start`` — with
        step windows (train.steps_per_call > 1) the caller passes the window's
        last step, so the trace covers whole windows (rounding the configured
        step count up to a window boundary, never running a full extra
        window)."""
        if self._active and step >= self._stop_after:
            # Block until device work from the traced steps has finished so
            # the trace actually contains the device timeline.
            jax.effects_barrier()
            jax.profiler.stop_trace()
            self._active = False
            self._done = True
            self._record_span(step, partial=False)
            logger.info("profiler: trace written to %s", self.directory)

    def close(self) -> None:
        """Mirror ``maybe_stop`` for a trainer exiting mid-window (epoch end,
        exception, total_steps inside the window): effects_barrier first so
        the trace still contains the device timeline of the steps that DID
        run, and mark ``_done`` so a reused profiler cannot restart a second
        window after its trace was finalized (ISSUE 3 satellite)."""
        if self._active:
            jax.effects_barrier()
            jax.profiler.stop_trace()
            self._active = False
            self._done = True
            self._record_span(self._stop_after, partial=True)
            logger.info("profiler: trace (partial window) written to %s",
                        self.directory)
