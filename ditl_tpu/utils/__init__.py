from ditl_tpu.utils.logging import get_logger, setup_logging  # noqa: F401
