"""OpenAI-compatible HTTP server over the local TPU model (L4/L6).

The reference points LiteLLM at an external OpenAI-compatible endpoint
(``CONFIG['API_BASE']``, ref ``src/distributed_inference.py:53-54``) — the
serving side is someone else's. This module supplies it: a ``/v1/chat/
completions`` + ``/v1/completions`` server backed by the KV-cache Generator,
so the framework's own L4 client (client/llm.py) — or litellm, or the openai
SDK — can evaluate against a model running on *this* TPU.

Threading model: stdlib ``ThreadingHTTPServer`` accepts concurrently. Two
engines (``--engine``):

- ``lockstep`` (default): a lock serializes device work; each request runs
  the batch Generator exclusively.
- ``continuous``: requests from all connections share slot-based decode
  ticks (infer/continuous.py) — concurrent requests batch on the device
  automatically, and a long generation no longer blocks short ones.

CLI (any host of a pod; serving is process-0-gated):

    python -m ditl_tpu.infer.server --preset tiny-llama --port 8300
"""

from __future__ import annotations

import argparse
import collections
import dataclasses
import json
import socket
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ditl_tpu.chaos import InjectedFault, maybe_inject
from ditl_tpu.config import ModelConfig
from ditl_tpu.data.tokenizer import Tokenizer
from ditl_tpu.infer.continuous import (
    BadRequestError,
    DeadlineExceededError,
    QueueFullError,
)
from ditl_tpu.infer.engine import GenerateConfig, Generator
from ditl_tpu.telemetry.serving import ServingMetrics
from ditl_tpu.telemetry.slo import BurnRateMonitor, serving_slo
from ditl_tpu.telemetry.usage import sanitize_label, tenant_label
from ditl_tpu.telemetry.tracing import (
    NULL_TRACER,
    Tracer,
    parse_traceparent,
    resolve_request_id,
)
from ditl_tpu.utils.http11 import KeepAliveHandlerMixin
from ditl_tpu.utils.logging import get_logger

logger = get_logger(__name__)

__all__ = ["DrainableHTTPServer", "serve", "make_server"]


class DrainableHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer with a graceful-drain lifecycle — the primitive
    the gateway's rolling restart (ditl_tpu/gateway/) builds on:

    - ``drain()`` flips ``/health`` to ``{"status": "draining"}`` and makes
      new completion/embedding work answer 503; in-flight requests finish.
    - ``close(drain=True)`` drains, waits for in-flight work to complete
      (bounded), then stops the serve loop and closes the socket — the
      ``SIGTERM`` disposition ``serve()`` installs.
    - ``kill()`` is the abrupt path (the in-process stand-in for kill -9):
      stop accepting, close the listening socket, and sever every open
      client connection mid-flight — clients observe connection reset /
      refused exactly as they would for a SIGKILLed process, which is what
      the gateway's retry-on-replica-death drills exercise.

    In-flight accounting covers the *completion-shaped* POST work (the
    device-occupying routes); metadata GETs are never blocked by a drain so
    health polling keeps working while draining.
    """

    def __init__(self, *args, **kwargs):
        self.draining = False
        self._inflight = 0
        self._idle = threading.Condition()
        self._conns: set = set()
        # Keep-alive connections currently parked between requests (the
        # handler thread blocked waiting for the next request line) —
        # maintained by KeepAliveHandlerMixin via note_parked. drain()
        # severs exactly these: without it a draining replica wedges on
        # the gateway pool's idle sockets (ISSUE 14).
        self._parked: set = set()  # guarded-by: _conn_lock
        self._conn_lock = threading.Lock()
        # (timestamp, completed-counter) samples for the backlog-aware
        # Retry-After derivation (_Handler._retry_after_s).
        self._rate_samples: collections.deque = collections.deque(maxlen=64)
        super().__init__(*args, **kwargs)

    # -- connection tracking (for kill() and drain()) ------------------------

    def process_request(self, request, client_address):
        with self._conn_lock:
            self._conns.add(request)
        super().process_request(request, client_address)

    def shutdown_request(self, request):
        with self._conn_lock:
            self._conns.discard(request)
            self._parked.discard(request)
        super().shutdown_request(request)

    def note_parked(self, request, parked: bool) -> None:
        """KeepAliveHandlerMixin callback: ``request``'s handler thread is
        (or stopped being) blocked between keep-alive requests."""
        with self._conn_lock:
            if parked:
                self._parked.add(request)
            else:
                self._parked.discard(request)

    def sever_parked(self) -> None:
        """Close every idle kept-alive connection. In-flight requests are
        untouched (a connection mid-request is not parked)."""
        with self._conn_lock:
            parked = list(self._parked)
        for s in parked:
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass

    def handle_error(self, request, client_address):
        import sys

        # Severed connections (client gone, or kill() cut the socket) are
        # expected during drills — log, don't stack-trace to stderr.
        exc = sys.exc_info()[1]
        if isinstance(exc, (ConnectionError, BrokenPipeError, OSError)):
            logger.debug("connection error from %s: %s", client_address, exc)
            return
        super().handle_error(request, client_address)

    # -- in-flight accounting ----------------------------------------------

    def _enter_request(self) -> int:
        """Register one in-flight completion; returns the new count (the
        lockstep admission cap compares it against ``max_pending``)."""
        with self._idle:
            self._inflight += 1
            return self._inflight

    def _exit_request(self) -> None:
        with self._idle:
            self._inflight -= 1
            self._idle.notify_all()

    @property
    def inflight(self) -> int:
        return self._inflight

    # -- lifecycle ----------------------------------------------------------

    def drain(self) -> None:
        """Stop accepting new work (503) while in-flight requests finish;
        /health reports ``draining`` so a router stops sending traffic.
        Idle kept-alive connections are severed — parked peers (the
        gateway's connection pool, lingering pollers) would otherwise pin
        handler threads through the drain and could relay one more
        request onto a replica the fleet believes is gone. New
        connections are still accepted (metadata routes keep working);
        they just stop being kept alive while draining."""
        self.draining = True
        self.sever_parked()

    def wait_idle(self, timeout: float | None = None) -> bool:
        """Block until no completion work is in flight. Returns False on
        timeout (callers may proceed to a hard stop)."""
        with self._idle:
            return self._idle.wait_for(
                lambda: self._inflight == 0, timeout=timeout
            )

    def close(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Graceful shutdown: drain, wait for in-flight work (bounded by
        ``timeout``), stop the serve loop, close the socket. Must be called
        from a thread other than the one running ``serve_forever``."""
        if drain:
            self.drain()
            if not self.wait_idle(timeout):
                logger.warning(
                    "drain timed out after %.1fs with %d request(s) in "
                    "flight; closing anyway", timeout, self._inflight,
                )
        self.shutdown()
        self.server_close()

    def kill(self) -> None:
        """Abrupt death: close the listening socket and sever every open
        client connection. From the network's perspective this is
        indistinguishable from the process being SIGKILLed — new connects
        are refused, in-flight requests see a reset."""
        self.shutdown()
        self.server_close()
        with self._conn_lock:
            conns = list(self._conns)
        for s in conns:
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass


def _stop_list(stop) -> list[str]:
    """Normalize OpenAI's `stop` (str | list | None) to <= 4 sequences.
    Raises ValueError on non-string entries (callers answer 400)."""
    if not stop:
        return []
    if isinstance(stop, str):
        stop = [stop]
    if not isinstance(stop, list) or any(not isinstance(s, str) for s in stop):
        raise ValueError("stop must be a string or an array of strings")
    return [s for s in stop if s][:4]


def _apply_stop(text: str, stops: list[str]) -> tuple[str, bool]:
    """Truncate at the earliest stop sequence (excluded, per OpenAI)."""
    cut = None
    for s in stops:
        i = text.find(s)
        if i >= 0 and (cut is None or i < cut):
            cut = i
    return (text, False) if cut is None else (text[:cut], True)


class _StopTracker:
    """Streaming stop handling: emits increments, holding back any trailing
    text that could be the start of a stop sequence spanning a chunk
    boundary."""

    def __init__(self, stops: list[str]):
        self.stops = stops
        self.acc = ""
        self.sent = 0
        self.hit = False

    def push(self, piece: str) -> str:
        """Add decoded text; return what is safe to emit now."""
        if self.hit:
            return ""
        self.acc += piece
        cut, self.hit = _apply_stop(self.acc, self.stops)
        if self.hit:
            out = cut[self.sent:]
            self.sent = len(cut)
            return out
        hold = 0
        for s in self.stops:
            for k in range(1, len(s)):
                if self.acc.endswith(s[:k]):
                    hold = max(hold, k)
        safe = len(self.acc) - hold
        out = self.acc[self.sent: safe] if safe > self.sent else ""
        self.sent = max(self.sent, safe)
        return out

    def flush(self) -> str:
        """End of stream: release any held-back stop-prefix text."""
        if self.hit:
            return ""
        out = self.acc[self.sent:]
        self.sent = len(self.acc)
        return out


def _chat_prompt(messages: list[dict], tokenizer=None) -> str:
    """Render chat messages to a prompt string. HF tokenizers that carry a
    chat template (Llama-3.1 etc.) use it — real special-token turns, the
    same rendering the model was trained with; the byte/debug tokenizer
    falls back to plain-text role turns."""
    inner = getattr(tokenizer, "_tok", None)
    if inner is not None and getattr(inner, "chat_template", None):
        try:
            return inner.apply_chat_template(
                messages, tokenize=False, add_generation_prompt=True
            )
        except Exception:
            logger.exception("chat template failed; using plain-text turns")
    parts = [f"{m.get('role', 'user')}: {m.get('content', '')}" for m in messages]
    return "\n".join(parts) + "\nassistant:"


class _Handler(KeepAliveHandlerMixin, BaseHTTPRequestHandler):
    generator: Generator = None  # injected by make_server
    threaded_engine = None  # ContinuousEngine driver; None => lockstep path
    spec_generator = None  # speculative path for greedy lock-step requests
    model_name: str = "ditl-tpu"
    device_lock: threading.Lock = None
    default_max_tokens: int = 64
    # Admission cap for the handler-thread-per-request paths: completions
    # beyond this many in flight answer 429 instead of piling up on the
    # device lock (None = unbounded, the historical behavior). The
    # continuous engine has its own queue cap (--max-queue); this one is
    # the LOCKSTEP overload control.
    max_pending: int = None
    adapter_names: dict = {}  # multi-LoRA: request "model" name -> adapter id
    grammar_cache = None  # guided decoding: spec-key -> CompiledGrammar LRU
    grammar_lock: threading.Lock = None
    embed_cache = None  # /v1/embeddings: (batch, plen) -> jitted program LRU
    # Telemetry bundle (telemetry/serving.py): the continuous engine's own
    # when one is serving (it records queue-wait/TTFT/TPOT on its scheduler
    # ticks), else a server-owned bundle the lock-step path records into.
    serving_metrics: ServingMetrics = None
    # Request tracing (ISSUE 6, telemetry/tracing.py): unarmed by default;
    # make_server derives it from the engine's tracer so one knob arms the
    # replica end-to-end (server span -> engine lifecycle spans).
    tracer: Tracer = NULL_TRACER
    # SLO burn-rate monitor (telemetry/slo.py), rendered at /slo and as
    # gauges on /metrics.
    slo: BurnRateMonitor = None
    # Disaggregated-fleet role tag (ISSUE 9): echoed on /health so the
    # gateway's role-aware routing reads the replica's OWN claim.
    role: str = "hybrid"
    # Incident manager (ISSUE 10, telemetry/incident.py): arms the
    # /incidents listing endpoint; None => 404 (unarmed is distinguishable
    # from "no incidents").
    incidents = None
    # KV handoff (ISSUE 13): arms the /internal/prefill + /internal/
    # kv_handoff endpoints (paged continuous engines only) and the
    # kv_handoff flag on /health the gateway's orchestration keys on.
    kv_handoff_enabled: bool = False
    # Per-tenant usage metering (ISSUE 15, telemetry/usage.py): ``usage``
    # (UsageMeter) serves /usage and the ditl_usage_* families; the
    # continuous engine feeds it on its own terminal paths, the LOCKSTEP
    # paths feed it here (the engine never sees those requests).
    # ``usage_ledger`` (UsageLedger) is the lockstep paths' ledger sink
    # (the continuous engine writes its own rows). Both unarmed by
    # default — /usage then 404s (absent != zero usage).
    usage = None
    usage_ledger = None
    # Adapter plane (ISSUE 16, infer/adapters.py): the LIVE registry —
    # /v1/adapters lifecycle endpoints, live /v1/models, name->row
    # resolution under the registry lock (an evicted name 404s with a
    # reason, never a silent fall-through to base — the launch-frozen
    # adapter_names dict this replaces could not say "gone"), and the
    # owner-billing flush on /usage. None => legacy static routing.
    adapter_registry = None

    def log_message(self, *args):  # route through our logger, not stderr
        logger.debug("http: " + args[0], *args[1:])

    def _request_id(self) -> str:
        """Stable per-request id: the client's sanitized ``X-Request-Id``
        or a generated one — echoed on EVERY response (success, 429, 504,
        SSE) so client-side logs join to traces (ISSUE 6 satellite). Reset
        per request in do_GET/do_POST: one handler instance serves many
        requests on a keep-alive connection."""
        rid = getattr(self, "_rid", None)
        if rid is None:
            rid = resolve_request_id(self.headers.get("X-Request-Id"))
            self._rid = rid
        return rid

    def _send_json(self, status: int, payload: dict) -> None:
        body = json.dumps(payload).encode("utf-8")
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("X-Request-Id", self._request_id())
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _load_snapshot(self) -> dict:
        """The load signal routers consume (gateway/router.py
        least-outstanding): queue depth + active slots, from the engine's
        stats when a continuous engine serves, else from the server's own
        in-flight accounting (the device lock serializes, so the lockstep
        server is a 1-slot engine with ``inflight - 1`` waiting)."""
        eng = self._engine_for_stats()
        if eng is not None:
            st = eng.stats()
            out = {
                "queue_depth": int(st.get("queue_depth", 0)),
                "active_slots": int(st.get("slots_busy", 0)),
                "n_slots": int(st.get("n_slots", 1)),
            }
            # Prefix-cache accounting rides the health payload (ISSUE 8):
            # the gateway's Fleet folds each poll into its ReplicaView, so
            # per-replica hit ratios aggregate on the gateway /metrics
            # without an extra scrape fan-out.
            pc = st.get("prefix_cache")
            if isinstance(pc, dict):
                out["cache_hit_tokens"] = int(pc.get("hit_tokens", 0))
                out["cache_miss_tokens"] = int(pc.get("miss_tokens", 0))
            # KV-handoff cost-model inputs (ISSUE 13): the gateway's
            # transfer-vs-re-prefill decision reads these from ordinary
            # health polls. Measured values only — absent until the engine
            # has prefilled/imported something (absent != 0; the model
            # falls back to its configured floors).
            for key in ("prefill_tok_per_s", "kv_bytes_per_token"):
                if key in st:
                    out[key] = st[key]
            kvt = st.get("kv_transfer")
            if isinstance(kvt, dict) and "put_mbps" in kvt:
                out["kv_put_mbps"] = kvt["put_mbps"]
            if self.kv_handoff_enabled and st.get("cache_mode") == "paged":
                out["kv_handoff"] = True
            return out
        inflight = int(getattr(self.server, "inflight", 0))
        return {
            "queue_depth": max(0, inflight - 1),
            "active_slots": min(1, inflight),
            "n_slots": 1,
        }

    def _sample_service_rate(self) -> None:
        """Append a (now, completed) sample for the Retry-After derivation;
        called after every completion-shaped request (cheap host reads)."""
        samples = getattr(self.server, "_rate_samples", None)
        if samples is not None and self.serving_metrics is not None:
            samples.append((time.time(), self.serving_metrics.completed.value))

    def _retry_after_s(self) -> int:
        """Backlog-aware Retry-After: how long until the CURRENT backlog
        (queued + active requests) clears at the recently measured service
        rate — the shared telemetry.serving.backlog_retry_after derivation
        (clamped [1, 30] s, stale samples aged out), replacing the old
        hardcoded 1 s that synchronized the whole herd's retries onto the
        same instant."""
        from ditl_tpu.telemetry.serving import backlog_retry_after

        self._sample_service_rate()
        load = self._load_snapshot()
        backlog = load["queue_depth"] + load["active_slots"]
        samples = getattr(self.server, "_rate_samples", None)
        return backlog_retry_after(samples or (), backlog)

    def _send_429(self, message: str) -> None:
        """OpenAI rate-limit shape: clients back off and retry, spaced by
        the backlog-aware Retry-After (was a hardcoded 1 s, which
        synchronized the whole herd's retries onto the same instant)."""
        body = json.dumps({"error": {
            "message": message, "type": "rate_limit_error",
        }}).encode()
        self.send_response(429)
        self.send_header("Content-Type", "application/json")
        self.send_header("X-Request-Id", self._request_id())
        self.send_header("Retry-After", str(self._retry_after_s()))
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _tenant_label(self) -> str:
        """This request's credential-safe tenant label (ISSUE 15). The
        gateway's ``X-Tenant-Label`` relay header wins — it carries the
        admission-layer identity (configured public name or sha digest;
        the gateway NEVER forwards the raw bearer spelling of a tenant it
        admitted). Direct clients fall back to their own Authorization
        bearer, reduced through ``tenant_label`` (digest — the raw key
        must never reach the ledger, /usage, or /metrics). TRUST MODEL:
        the header is honored from whoever can reach this port — the same
        private-network trust the replica's unauthenticated /metrics,
        /stats, and /internal endpoints already assume. On a replica
        exposed beyond the gateway, a client that learns another tenant's
        label can mis-attribute its OWN traffic onto that bill (billing
        pollution, not privilege: admission/quota enforcement stays at
        the gateway) — serve replicas behind the gateway, as everything
        since ISSUE 4 assumes (docs/design.md)."""
        hdr = self.headers.get("X-Tenant-Label")
        if hdr:
            return sanitize_label(hdr)
        auth = self.headers.get("Authorization", "")
        if auth.lower().startswith("bearer "):
            bearer = auth[7:].strip()
            if bearer:
                return tenant_label(bearer)
        return "anonymous"

    def _note_usage_lockstep(self, tenant: str, n_prompt: int, n_gen: int,
                             t0: float, outcome: str = "200",
                             slo_class: str | None = None) -> None:
        """Terminal usage row for a request the LOCKSTEP path served (the
        continuous engine ledgers its own). The device lock serializes
        whole requests, so the request wall doubles as the device-time
        estimate — exclusive occupancy, not a share."""
        if self.usage is None and self.usage_ledger is None:
            return
        dt = round(time.time() - t0, 6)
        row = {
            "tenant": sanitize_label(tenant), "outcome": outcome,
            "slo_class": slo_class or "interactive",
            "prompt_tokens": int(n_prompt), "generated_tokens": int(n_gen),
            "device_time_est_s": dt, "e2e_s": dt,
        }
        try:
            if self.usage is not None:
                self.usage.note_terminal(row)
            if self.usage_ledger is not None:
                self.usage_ledger.record(**row)
        except Exception:  # noqa: BLE001 - metering must not crash serving
            logger.exception("lockstep usage metering failed (row dropped)")

    def _gate_slo_class(self, slo_class, from_header) -> tuple:
        """This serving path cannot honor a scheduling class (lockstep,
        pod FIFO staging, adapter/logprobs fallbacks): drop a header-
        derived hint (the gateway stamps every relay best-effort), 400 an
        explicit non-default payload value (reject-don't-drop — the PR 5
        deadline split). Returns (ok, slo_class)."""
        if slo_class in (None, "interactive"):
            return True, slo_class
        if from_header:
            return True, None
        self._send_json(400, {"error": {"message":
            "slo_class requires the continuous-engine serving path (no "
            "lockstep or pod engine, adapter fallback, or logprobs beyond "
            "--logprobs-k)"}})
        return False, None

    # -- adapter plane (ISSUE 16, infer/adapters.py) -------------------------

    def _resolve_adapter(self, payload: dict):
        """This request's adapter ids (``[row]``, or None = base).

        The gateway's ``X-Adapter-Name`` pin (tenant->adapter pinning,
        gateway/admission ``per_tenant``) wins over the payload's model
        field — the X-SLO-Class precedence. With the registry armed the
        name resolves against LIVE state and an unknown/evicted name
        raises :class:`AdapterNotFound` (404 with a reason — never a
        silent fall-through to base); without it the legacy launch-frozen
        ``adapter_names`` dict routes and unknown names keep serving base
        (OpenAI compat: the model field stays advisory on adapters-less
        servers). Stamps ``self._adapter_fp`` (``adapter:<name>@g<gen>``)
        for the response's ``system_fingerprint`` — a client diffing two
        responses can SEE the publication boundary."""
        self._adapter_fp = None
        pin = self.headers.get("X-Adapter-Name")
        name = str(pin or payload.get("model") or "")
        reg = self.adapter_registry
        if reg is None:
            aid = self.adapter_names.get(name)
            return [aid] if aid is not None else None
        if not name or name == self.model_name:
            return None
        row, generation = reg.resolve(name)  # raises AdapterNotFound
        self._adapter_fp = f"adapter:{name}@g{generation}"
        return [row]

    def _adapter_admin(self, payload: dict, op: str) -> None:
        """POST /v1/adapters/{load,evict,publish}: the hot-lifecycle
        endpoints. Every refusal maps an :class:`AdapterError` status
        (404 unknown/evicted, 409 pool-full/busy, 422 failed
        verification) — reject-don't-drop, with the reason in the body."""
        from ditl_tpu.infer.adapters import AdapterError

        reg = self.adapter_registry
        if reg is None:
            self._send_json(404, {"error": {"message":
                "adapter plane not armed on this replica (serve a "
                "multi-LoRA continuous engine: --adapter and/or "
                "--adapter-pool)"}})
            return
        name = str(payload.get("name") or "")
        if not name:
            self._send_json(400, {"error": {"message":
                f"adapter {op} wants a non-empty 'name'"}})
            return
        if name == self.model_name:
            self._send_json(400, {"error": {"message":
                f"{name!r} is the base model name; an adapter cannot "
                f"shadow it"}})
            return
        try:
            if op == "evict":
                out = reg.evict(name)
            else:
                directory = str(payload.get("dir")
                                or payload.get("directory") or "")
                if not directory:
                    self._send_json(400, {"error": {"message":
                        f"adapter {op} wants 'dir' (a manifest-carrying "
                        f"adapter checkpoint directory or its parent "
                        f"with a LATEST pointer)"}})
                    return
                # The OWNER the row bills to: an explicit payload owner
                # (the gateway's publication fan-out forwards the
                # publisher's label) else the caller's own tenant label.
                owner = str(payload.get("owner") or "") or self._tenant_label()
                fn = reg.publish if op == "publish" else reg.load
                out = fn(name, directory, owner=owner)
            self._send_json(200, out)
        except AdapterError as e:
            self._send_json(e.status, {"error": {"message": str(e)}})
        except Exception as e:  # noqa: BLE001 - admin errors become JSON
            logger.exception("adapter %s %r failed", op, name)
            self._send_json(500, {"error": {"message": str(e)}})

    def do_GET(self):
        self._rid = None  # fresh id per request on keep-alive connections
        if self.path in ("/health", "/v1/health"):
            draining = bool(getattr(self.server, "draining", False))
            payload = {
                "status": "draining" if draining else "ok",
                "model": self.model_name,
                "draining": draining,
                # Disaggregated-fleet role (ISSUE 9): the gateway's Fleet
                # prefers this over the handle's configured role so a
                # relaunch with different args cannot route under a stale
                # tag.
                "role": self.role,
            }
            # Measured cold start (ISSUE 12): time-to-first-ready stamped
            # by serve() (process start -> port bound, compile cache
            # included). The gateway's autoscale planner derives its
            # scale-to-zero wake budget from this MEASURED value, never a
            # constant; absent on embedded servers that never stamped one.
            cold = getattr(self.server, "cold_start_s", None)
            if isinstance(cold, (int, float)):
                payload["cold_start_s"] = round(float(cold), 3)
            payload.update(self._load_snapshot())
            # Latency snapshot for the gateway's per-role TTFT/TPOT
            # aggregation (ISSUE 9): lifetime histogram p95s, present only
            # once something has been served (absent != zero).
            m = self.serving_metrics
            if m is not None:
                for key, hist in (("ttft_p95_s", m.ttft),
                                  ("tpot_p95_s", m.decode_token)):
                    q = hist.quantile(0.95) if hist.count else None
                    if q is not None:
                        payload[key] = round(q, 6)
            self._send_json(200, payload)
        elif self.path in ("/v1/stats", "/stats"):
            stats = {"model": self.model_name, "engine": "lockstep",
                     "draining": bool(getattr(self.server, "draining", False)),
                     "inflight": int(getattr(self.server, "inflight", 0))}
            stats.update(self._load_snapshot())
            eng = self._engine_for_stats()
            if eng is not None:
                stats.update(eng.stats())
            spec = self.spec_generator
            if spec is not None:
                stats["speculative"] = True
                acc = getattr(spec, "acceptance_ema", None)
                inner = getattr(spec, "spec", spec)
                if acc is None:
                    acc = getattr(inner, "last_acceptance", None)
                if acc is not None:
                    stats["speculative_acceptance"] = round(acc, 3)
            self._send_json(200, stats)
        elif self.path in ("/v1/models", "/models"):
            # With the adapter plane armed, the list is the REGISTRY's
            # live state (one locked snapshot) — a hot-loaded adapter
            # appears, an evicted one disappears; the launch-frozen
            # adapter_names dict routes only on adapters-less servers.
            if self.adapter_registry is not None:
                names = sorted(self.adapter_registry.names())
            else:
                names = list(self.adapter_names)
            models = [{"id": self.model_name, "object": "model"}] + [
                {"id": name, "object": "model", "parent": self.model_name}
                for name in names
            ]
            self._send_json(200, {"object": "list", "data": models})
        elif self.path in ("/v1/adapters", "/adapters"):
            # Adapter-plane listing (ISSUE 16): pool occupancy + every
            # live binding (name/row/generation/step/owner) + evicted
            # tombstones. 404 when unarmed — distinguishable from an
            # armed, empty pool.
            if self.adapter_registry is None:
                self._send_json(404, {"error": {"message":
                    "adapter plane not armed on this replica (serve a "
                    "multi-LoRA continuous engine: --adapter and/or "
                    "--adapter-pool)"}})
            else:
                self._send_json(200, self.adapter_registry.list())
        elif self.path == "/metrics":
            self._metrics()
        elif self.path in ("/slo", "/v1/slo"):
            # SLO burn-rate evaluation (telemetry/slo.py): the scrape IS
            # the sampling cadence — each hit appends one cumulative
            # snapshot and grades the windows against it.
            if self.slo is None:
                self._send_json(404, {"error": {"message":
                    "no SLO monitor configured"}})
            else:
                self._send_json(200, self.slo.report())
        elif self.path in ("/usage", "/v1/usage"):
            # Per-tenant usage rollups (ISSUE 15): the meter's live
            # in-memory view — what the gateway's /usage fan-out
            # aggregates fleet-wide. 404 when metering is unarmed so an
            # aggregator can tell "no usage" from "not metering".
            if self.adapter_registry is not None and self.usage is not None:
                # Flush accrued adapter owner bills (HBM residency +
                # gather attribution, ISSUE 16) so the rollup below
                # carries them; the same rows land in the ledger sink.
                for row in self.adapter_registry.flush_billing():
                    self.usage.note_terminal(row)
            if self.usage is None:
                self._send_json(404, {"error": {"message":
                    "usage metering is not armed on this replica"}})
            else:
                self._send_json(200, {
                    "requests": self.usage.total_requests,
                    "tenants": self.usage.snapshot(),
                })
        elif self.path in ("/incidents", "/v1/incidents"):
            # Incident bundles (ISSUE 10): list this replica's assembled
            # bundle manifests. Torn/tmp dirs are skipped by the reader,
            # never an error; 404 when the incident plane is unarmed so a
            # fleet aggregator can tell "no incidents" from "not watching".
            if self.incidents is None:
                self._send_json(404, {"error": {"message":
                    "no incident manager configured"}})
            else:
                from ditl_tpu.telemetry.incident import list_bundles

                bundles = list_bundles(self.incidents.directory)
                self._send_json(200, {
                    "count": len(bundles),
                    "suppressed": self.incidents.suppressed_total,
                    "incidents": bundles,
                })
        elif self.path.partition("?")[0].rstrip("/") in ("/profile",
                                                         "/v1/profile"):
            self._profile(self.path.partition("?")[2])
        else:
            self._send_json(404, {"error": {"message": f"no route {self.path}"}})

    def _profile(self, query: str) -> None:
        """On-demand wall-clock profile (ISSUE 18): sample every thread
        for ``?seconds=N`` (clamped) and return flamegraph-ready
        collapsed stacks as text/plain — "what code is this replica
        running right now" without attaching a debugger. Stdlib sampler,
        no lock on the sample path: safe under live decode."""
        from ditl_tpu.telemetry.prof import profile_for

        seconds = 2.0
        for part in query.split("&"):
            if part.startswith("seconds="):
                try:
                    seconds = float(part.split("=", 1)[1])
                except ValueError:
                    self._send_json(400, {"error": {
                        "message": "seconds must be a number"}})
                    return
        seconds = min(max(seconds, 0.1), 60.0)
        body = profile_for(seconds).encode()
        self.send_response(200)
        self.send_header("Content-Type", "text/plain")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _metrics(self) -> None:
        """Prometheus text exposition (no device sync), two sections:

        1. The telemetry registry (telemetry/serving.py): REAL cumulative
           series — latency histograms (queue-wait, TTFT, per-token decode,
           e2e) as ``_bucket``/``_sum``/``_count`` triples and monotonic
           ``_total`` counters (admissions, 429s, preemptions, degrade
           windows, grammar-masked tokens, speculative accept/reject).
        2. The /v1/stats snapshot flattened to ``ditl_serving_<path>``
           gauges (slot occupancy, queue depth, page pool, acceptance EMA)
           — point-in-time state, kept as gauges on purpose."""
        stats: dict = {}
        eng = self._engine_for_stats()
        if eng is not None:
            stats.update(eng.stats())
        spec = self.spec_generator
        if spec is not None:
            # Lock-step speculative serving (no continuous engine): surface
            # the same acceptance /v1/stats reports.
            stats["lockstep_speculative"] = True
            acc = getattr(spec, "acceptance_ema", None)
            if acc is None:
                acc = getattr(getattr(spec, "spec", spec),
                              "last_acceptance", None)
            if acc is not None:
                stats["lockstep_speculative_acceptance"] = round(acc, 3)

        lines: list[str] = []
        reserved: set[str] = set()
        if self.slo is not None:
            # Refresh the ditl_slo_* burn-rate gauges (they live in the
            # serving registry) so /metrics carries the same numbers /slo
            # renders; the scrape doubles as the monitor's sample tick.
            self.slo.report()
        if self.serving_metrics is not None:
            lines.extend(self.serving_metrics.render().splitlines())
            # A flattened stats gauge must not shadow a registry metric
            # (e.g. the lifetime "preemptions" count, now a real _total
            # counter) — exposing both a `x` gauge and an `x_total` counter
            # for the same fact invites dashboards built on the wrong one.
            reserved = set(self.serving_metrics.registry._metrics)

        from ditl_tpu.telemetry.serving import flattened_stats_lines

        lines.extend(flattened_stats_lines(stats, reserved))
        # HBM accounting (telemetry/memwatch.py, ISSUE 7): per-device
        # allocator gauges (bytes in use, high-watermark, limit) sampled at
        # scrape time — absent (not zero) on backends without memory stats.
        from ditl_tpu.telemetry.memwatch import memory_metrics_lines

        lines.extend(memory_metrics_lines())
        lines.append("# TYPE ditl_serving_up gauge")
        lines.append("ditl_serving_up 1")
        body = ("\n".join(lines) + "\n").encode()
        self.send_response(200)
        self.send_header("Content-Type", "text/plain; version=0.0.4")
        self.send_header("X-Request-Id", self._request_id())
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _engine_for_stats(self):
        """The serving driver, if any (both drivers expose ``stats()``)."""
        return self.threaded_engine

    def do_POST(self):
        self._rid = None  # fresh id per request on keep-alive connections
        self._adapter_fp = None  # set by _resolve_adapter per request
        try:
            length = int(self.headers.get("Content-Length", 0))
            raw = self.rfile.read(length)
        except (ValueError, OSError) as e:
            # The request body was never (fully) consumed: leftover bytes
            # would be parsed as the NEXT request line on this kept-alive
            # connection (desync) — close it after the error response.
            self.close_connection = True
            self._send_json(400, {"error": {"message": f"bad request: {e}"}})
            return
        path = self.path.rstrip("/")
        if path.endswith("/internal/kv_handoff"):
            # Binary paged-KV blob (infer/kv_transfer.py) — never decoded
            # as JSON; its own header/crc framing rejects torn payloads.
            self._kv_handoff(raw or b"")
            return
        try:
            payload = json.loads(raw or b"{}")
        except (ValueError, json.JSONDecodeError) as e:
            self._send_json(400, {"error": {"message": f"bad request: {e}"}})
            return
        if path.endswith("/internal/prefill"):
            self._internal_prefill(payload)
        elif path.endswith(("/adapters/load", "/adapters/evict",
                            "/adapters/publish")):
            self._adapter_admin(payload, path.rsplit("/", 1)[1])
        elif path.endswith(("/chat/completions", "/completions", "/embeddings")):
            self._device_work(payload, path)
        elif path.endswith("/tokenize"):
            tok = self.generator.tokenizer
            text = payload.get("prompt")
            if not isinstance(text, str):
                self._send_json(400, {"error": {"message":
                    "tokenize wants a string 'prompt'"}})
                return
            ids = tok.encode(text)
            if payload.get("add_special_tokens", True):
                ids = [tok.bos_id] + ids
            self._send_json(200, {"tokens": ids, "count": len(ids),
                                  "max_model_len": self.generator.cfg.max_seq_len
                                  if hasattr(self.generator, "cfg") else None})
        elif path.endswith("/detokenize"):
            tok = self.generator.tokenizer
            ids = payload.get("tokens")
            if not isinstance(ids, list) or any(
                not isinstance(i, int) for i in ids
            ):
                self._send_json(400, {"error": {"message":
                    "detokenize wants an integer array 'tokens'"}})
                return
            self._send_json(200, {"prompt": tok.decode(ids)})
        else:
            self._send_json(404, {"error": {"message": f"no route {self.path}"}})

    # -- prefill->decode KV handoff (ISSUE 13) -------------------------------

    def _kv_gate(self):
        """Common gate for the /internal KV endpoints: 404 unless handoff
        is armed on a paged continuous engine (unarmed is distinguishable
        from broken); 503 while draining (the rolling-restart protocol —
        the gateway falls back to plain relay)."""
        eng = self.threaded_engine
        if (not self.kv_handoff_enabled or eng is None
                or getattr(eng, "_engine", None) is None
                or eng._engine.cache_mode != "paged"):
            self._send_json(404, {"error": {"message":
                "KV handoff not armed on this replica "
                "(--kv-handoff with a paged continuous engine)"}})
            return None
        if getattr(self.server, "draining", False):
            self._send_json(503, {"error": {"message":
                "server is draining; retry on another replica",
                "type": "unavailable_error"}})
            return None
        return eng

    def _internal_prefill(self, payload: dict) -> None:
        """Prefill-export half of the handoff: tokenize exactly like
        /v1/completions does (the shipped pages must match the relayed
        request's block keys bit-for-bit), prefill whatever isn't cached,
        and answer the serialized page blob. Runs on the engine driver
        thread via ThreadedEngine.call — handler threads never touch
        device state mid-tick."""
        eng = self._kv_gate()
        if eng is None:
            return
        prompt = payload.get("prompt")
        if not isinstance(prompt, str) or not prompt:
            self._send_json(400, {"error": {"message":
                "internal/prefill wants a non-empty string 'prompt'"}})
            return
        from ditl_tpu.infer.continuous import BadRequestError

        tok = self.generator.tokenizer
        ids = [tok.bos_id] + tok.encode(prompt)
        try:
            blob, shipped = eng.call(lambda: eng._engine.export_kv(ids))
        except BadRequestError as e:
            self._send_json(400, {"error": {"message": str(e)}})
            return
        except MemoryError as e:
            self._send_json(503, {"error": {"message": str(e)}})
            return
        except RuntimeError as e:
            self._send_json(500, {"error": {"message": str(e)}})
            return
        self.send_response(200)
        self.send_header("Content-Type", "application/octet-stream")
        self.send_header("X-Request-Id", self._request_id())
        self.send_header("X-KV-Tokens", str(shipped))
        self.send_header("Content-Length", str(len(blob)))
        self.end_headers()
        self.wfile.write(blob)

    def _kv_handoff(self, raw: bytes) -> None:
        """Import half of the handoff: install + publish a shipped
        prefill's pages so the relayed request's admission prefix-matches
        them. Torn/crc-failing/mismatched blobs answer 400 (counted on
        ``kv_handoff_rejected``) — reject-don't-install; the gateway's
        fallback relay re-prefills."""
        eng = self._kv_gate()
        if eng is None:
            return
        from ditl_tpu.infer.continuous import BadRequestError
        from ditl_tpu.infer.kv_transfer import KVTransferError

        try:
            res = eng.call(lambda: eng._engine.import_kv(raw))
        except (KVTransferError, BadRequestError, ValueError) as e:
            if self.serving_metrics is not None:
                self.serving_metrics.kv_handoff_rejected.inc()
            self._send_json(400, {"error": {"message": str(e)}})
            return
        except RuntimeError as e:
            self._send_json(500, {"error": {"message": str(e)}})
            return
        self._send_json(200, res)

    def _device_work(self, payload: dict, path: str) -> None:
        """Admission wrapper for the device-occupying POST routes
        (completions / chat completions / embeddings): reject while
        draining (503 — the rolling-restart protocol; a router retries on a
        peer replica), count in-flight work (the drain wait and the
        lockstep load signal), and apply the lockstep overload cap
        (``max_pending``) with a real 429 instead of an unbounded pile-up
        on the device lock."""
        srv = self.server
        if getattr(srv, "draining", False):
            self._send_json(503, {"error": {
                "message": "server is draining; retry on another replica",
                "type": "unavailable_error",
            }})
            return
        # Chaos seam: `error` answers a clean 500 (the gateway's retry
        # fodder), `delay`/`hang` make this replica slow-not-dead (hedging
        # and health-poll drills), `kill` is a real replica death.
        try:
            maybe_inject("server.request")
        except InjectedFault as e:
            self._send_json(500, {"error": {"message": str(e)}})
            return
        tracked = hasattr(srv, "_enter_request")
        n = srv._enter_request() if tracked else 0
        try:
            if self.max_pending is not None and n > self.max_pending:
                if self.serving_metrics is not None:
                    self.serving_metrics.queue_full.inc()
                self._send_429(
                    f"server at capacity ({self.max_pending} requests in "
                    "flight)"
                )
                return
            if path.endswith("/chat/completions"):
                self._complete(payload, chat=True)
            elif path.endswith("/completions"):
                self._complete(payload, chat=False)
            else:
                try:
                    self._embeddings(payload)
                except Exception as e:
                    logger.exception("embeddings failed")
                    self._send_json(500, {"error": {"message": str(e)}})
        finally:
            if tracked:
                srv._exit_request()
            self._sample_service_rate()

    def _observe_lockstep(self, t0: float, n_gen: int) -> None:
        """Telemetry for requests the LOCK-STEP path served (the continuous
        engine records its own on scheduler ticks): end-to-end latency plus
        the request/completion/token counters. Queue-wait/TTFT/TPOT have no
        lock-step analog — the device lock serializes whole requests."""
        m = self.serving_metrics
        if m is None:
            return
        dt = time.time() - t0
        m.requests.inc()
        m.completed.inc()
        m.tokens_generated.inc(n_gen)
        m.e2e.observe(dt)

    def _lockstep_generate(self, prompt_ids, gen, adapter_ids) -> list:
        """One lock-step generation, speculatively when eligible: greedy,
        no adapter, and the spec program's k+1 KV slack fits (ValueError
        falls back to the plain Generator). Outputs are token-identical by
        the speculation exactness contract. Used by both the streaming and
        non-streaming paths."""
        if (
            self.spec_generator is not None
            and gen.temperature == 0.0
            and adapter_ids is None
        ):
            try:
                with self.device_lock:
                    return self.spec_generator.generate_tokens(
                        [prompt_ids], gen.max_new_tokens
                    )[0]
            except ValueError:
                pass
        with self.device_lock:
            return self.generator.generate_tokens(
                [prompt_ids], gen, adapter_ids
            )[0]

    def _send_sse(self, events) -> None:
        """Stream pre-serialized JSON events as Server-Sent Events.

        A client that vanishes mid-stream (broken pipe / reset on write)
        CANCELS the in-flight generation deterministically: closing the
        events generator unwinds its ``finally`` chain into
        ``ThreadedEngine.stream_one``'s cancel, freeing the slot instead of
        decoding the abandoned token budget — and the eviction is counted
        (``client_disconnects``) so vanishing clients are visible on
        /metrics, not just a GC side effect."""
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("X-Request-Id", self._request_id())
        self.send_header("Cache-Control", "no-cache")
        # SSE opts out of HTTP/1.1 keep-alive by design: the stream has
        # no Content-Length, so close-delimited framing is the only
        # correct end-of-body signal — send_header("Connection", "close")
        # also flips the stdlib's close_connection for us (ISSUE 14).
        self.send_header("Connection", "close")
        self.end_headers()
        try:
            for event in events:
                self.wfile.write(f"data: {json.dumps(event)}\n\n".encode())
                self.wfile.flush()
            self.wfile.write(b"data: [DONE]\n\n")
            self.wfile.flush()
        except OSError:  # BrokenPipeError/ConnectionError are subclasses
            if self.serving_metrics is not None:
                self.serving_metrics.client_disconnects.inc()
            logger.info(
                "client disconnected mid-stream; cancelling in-flight "
                "generation"
            )
        finally:
            events.close()

    def _multi_complete(
        self, payload: dict, prompt: str, gen, *, chat: bool, n: int,
        best_of: int, adapter_ids=None, stops=None, grammar=None,
        slo_class=None, slo_from_header=False, trace=None, tenant=None,
    ) -> None:
        """OpenAI ``n``/``best_of``: generate ``best_of`` candidates (the
        continuous engine batches them into shared decode ticks; the
        lock-step path replicates the prompt into one batch) and return the
        top ``n`` ranked by mean token logprob (OpenAI's best_of rule).
        Ranking needs per-token logprobs: the continuous engine must be
        armed (``--logprobs-k``) when ``best_of > n``; the lock-step
        generator computes them natively."""
        t0 = time.time()
        rank = best_of > n
        eng = self.threaded_engine
        use_cont = eng is not None and (
            adapter_ids is None or getattr(eng, "multi_lora", False)
        ) and (not rank or getattr(eng, "logprobs_k", 0) > 0)
        if use_cont:
            tok = eng.tokenizer
            prompt_ids = [tok.bos_id] + tok.encode(prompt)
            reqs = eng.generate_many(
                prompt_ids, best_of,
                max_new_tokens=gen.max_new_tokens,
                temperature=gen.temperature, top_p=gen.top_p,
                seed=gen.seed,
                adapter_id=adapter_ids[0] if adapter_ids else None,
                grammar=grammar,
                slo_class=slo_class,
                logprobs=0 if rank else None,
                trace=trace,
                tenant=tenant,
            )
            cands = [(r.tokens, r.lp_token) for r in reqs]
        else:
            # Lock-step batch fallback: no class-ordered scheduler here —
            # drop/400 a non-default class (reject-don't-drop).
            ok, slo_class = self._gate_slo_class(slo_class, slo_from_header)
            if not ok:
                return
            if grammar is not None:
                # Name the ACTUAL missing piece: a guided request can land
                # here despite a guided-armed continuous engine when
                # best_of ranking needs logprobs the engine wasn't built
                # with.
                msg = (
                    "best_of ranking with guided decoding requires the "
                    "continuous engine armed with --logprobs-k >= 1"
                    if eng is not None and rank
                    and getattr(eng, "logprobs_k", 0) == 0
                    else "guided decoding requires the continuous engine"
                )
                self._send_json(400, {"error": {"message": msg}})
                return
            if rank and not hasattr(
                self.generator, "generate_tokens_with_logprobs"
            ):
                self._send_json(400, {"error": {"message":
                    "best_of ranking is not supported with --pod serving"}})
                return
            tok = self.generator.tokenizer
            prompt_ids = [tok.bos_id] + tok.encode(prompt)
            batch = [list(prompt_ids) for _ in range(best_of)]
            if rank:
                lp_gen = dataclasses.replace(gen, logprobs=1)
                with self.device_lock:
                    outs, lps = self.generator.generate_tokens_with_logprobs(
                        batch, lp_gen, adapter_ids * best_of if adapter_ids else None
                    )
                cands = [
                    (outs[i], lps[i]["token_logprobs"]) for i in range(best_of)
                ]
            else:
                with self.device_lock:
                    outs = self.generator.generate_tokens(
                        batch, gen, adapter_ids * best_of if adapter_ids else None
                    )
                cands = [(o, None) for o in outs]
        if rank:
            def score(c):
                toks, lp = c
                return (sum(lp[: len(toks)]) / max(1, len(toks))) if lp else 0.0

            cands.sort(key=score, reverse=True)
        # Bill the tokens actually GENERATED — all best_of candidates, not
        # just the n returned (OpenAI best_of billing); and count ids, not
        # a re-encode: decode->encode is not idempotent for every tokenizer
        # (byte tokenizers strip non-printables), so re-encoding
        # under-counts (ADVICE r3, r4).
        total_out = sum(len(out) for out, _ in cands)
        cands = cands[:n]
        choices = []
        for i, (out, _) in enumerate(cands):
            text, hit_stop = _apply_stop(tok.decode(out), stops or [])
            finish = (
                "stop" if hit_stop or len(out) < gen.max_new_tokens
                else "length"
            )
            choices.append(
                {"index": i, "message": {"role": "assistant", "content": text},
                 "finish_reason": finish}
                if chat
                else {"index": i, "text": text, "finish_reason": finish}
            )
        n_prompt = len(prompt_ids)
        if not use_cont:
            # Before the response write — see _complete's lockstep note.
            self._observe_lockstep(t0, total_out)
            # Usage billing is DEVICE accounting, per candidate: the
            # lockstep batch genuinely prefills all best_of prompt copies
            # (no prefix cache on this path), matching the continuous
            # engine's one-row-per-candidate rows. The API response's
            # OpenAI `usage` field still reports the prompt once.
            self._note_usage_lockstep(tenant or "anonymous",
                                      n_prompt * best_of,
                                      total_out, t0, slo_class=slo_class)
        self._send_json(200, {
            "id": f"cmpl-{uuid.uuid4().hex[:24]}",
            "object": "chat.completion" if chat else "text_completion",
            "created": int(t0),
            "model": payload.get("model") or self.model_name,
            # Which adapter GENERATION served (adapter plane, ISSUE 16):
            # a publication's flip is visible as this value changing.
            **({"system_fingerprint": self._adapter_fp}
               if getattr(self, "_adapter_fp", None) else {}),
            "choices": choices,
            "usage": {
                "prompt_tokens": n_prompt,
                "completion_tokens": total_out,
                "total_tokens": n_prompt + total_out,
            },
        })

    def _embeddings(self, payload: dict) -> None:
        """OpenAI ``/v1/embeddings``: mean-pooled, L2-normalized final
        hidden states (the standard causal-LM embedding recipe). One jitted
        program per (batch, length) bucket, LRU-bounded like every other
        client-shaped compile cache; runs under the device lock (embedding
        batches are one forward — lock-step is the right shape)."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        from ditl_tpu.infer.engine import _next_pow2, lru_program
        from ditl_tpu.models import llama

        if not hasattr(self.generator, "cfg"):
            # --pod wraps the generator in PodGenerator (tokenizer-only
            # surface): a direct forward here would run device work outside
            # the pod broadcast protocol and hang the other processes.
            self._send_json(400, {"error": {"message":
                "embeddings are not supported with --pod serving"}})
            return
        raw = payload.get("input")
        if isinstance(raw, str):
            inputs = [raw]
        elif isinstance(raw, list) and raw and all(
            isinstance(x, int) for x in raw
        ):
            inputs = [raw]  # one pre-tokenized prompt
        elif isinstance(raw, list):
            inputs = raw
        else:
            self._send_json(400, {"error": {"message":
                "input must be a string, array of strings, or token array"}})
            return
        if not inputs or len(inputs) > 64:
            self._send_json(400, {"error": {"message":
                "input must contain 1..64 entries"}})
            return
        gen = self.generator
        tok = gen.tokenizer
        token_lists = []
        for item in inputs:
            if isinstance(item, str):
                ids = [tok.bos_id] + tok.encode(item)
            elif isinstance(item, list) and all(isinstance(x, int) for x in item):
                ids = item or [tok.bos_id]
            else:
                self._send_json(400, {"error": {"message":
                    "each input must be a string or a token-id array"}})
                return
            if len(ids) > gen.cfg.max_seq_len:
                ids = ids[: gen.cfg.max_seq_len]
            token_lists.append(ids)
        batch = _next_pow2(len(token_lists), floor=1)
        plen = _next_pow2(max(len(t) for t in token_lists))
        ids = np.full((batch, plen), tok.pad_id, np.int32)
        lengths = np.ones((batch,), np.int32)
        for i, t in enumerate(token_lists):
            ids[i, : len(t)] = t
            lengths[i] = len(t)
        cfg, mesh, rules = gen.cfg, gen.mesh, gen.rules

        def build():
            def run(params, ids, lengths):
                q_pos = jnp.arange(plen, dtype=jnp.int32)
                seg = (q_pos[None, :] < lengths[:, None]).astype(jnp.int32)
                hidden = llama.forward(
                    params, ids, cfg,
                    positions=jnp.broadcast_to(q_pos, (batch, plen)),
                    segment_ids=seg, mesh=mesh, rules=rules,
                    return_hidden=True,
                )
                mask = seg.astype(jnp.float32)[..., None]
                pooled = (hidden.astype(jnp.float32) * mask).sum(1) / mask.sum(1)
                norm = jnp.linalg.norm(pooled, axis=-1, keepdims=True)
                return pooled / jnp.maximum(norm, 1e-9)

            return jax.jit(run)

        with self.device_lock:
            program = lru_program(
                self.embed_cache, (batch, plen), build, bound=16
            )
            vecs = np.asarray(
                jax.device_get(program(gen.params, ids, lengths))
            )
        self._send_json(200, {
            "object": "list",
            "model": payload.get("model") or self.model_name,
            "data": [
                {"object": "embedding", "index": i,
                 "embedding": vecs[i].tolist()}
                for i in range(len(token_lists))
            ],
            "usage": {
                "prompt_tokens": int(sum(len(t) for t in token_lists)),
                "total_tokens": int(sum(len(t) for t in token_lists)),
            },
        })

    def _resolve_grammar(self, payload: dict):
        """Parse the request's guided-decoding spec (``guided_regex``,
        ``guided_json``, or OpenAI ``response_format`` json_object /
        json_schema) into a CompiledGrammar, LRU-cached by spec — grammar
        compilation is host work (regex -> DFA -> token table) that repeat
        clients shouldn't pay twice; the engine additionally dedups
        registration by table content. Returns None when the request is
        unconstrained; raises ValueError (caller answers 400) on a bad spec
        or a server not armed for guided decoding."""
        rf = payload.get("response_format")
        rf = rf if isinstance(rf, dict) else {}
        specs = [
            payload.get("guided_regex") is not None,
            payload.get("guided_json") is not None,
            rf.get("type") in ("json_object", "json_schema"),
        ]
        if not any(specs):
            return None
        if sum(specs) > 1:
            raise ValueError(
                "at most one of guided_regex, guided_json, response_format "
                "may constrain a request"
            )
        eng = self.threaded_engine
        if eng is None or not getattr(eng, "guided", False):
            raise ValueError(
                "guided decoding requires --engine continuous with "
                "--fsm-capacity > 0"
            )
        tok = eng.tokenizer
        from ditl_tpu.infer import grammar as G

        if payload.get("guided_regex") is not None:
            pattern = payload["guided_regex"]
            if not isinstance(pattern, str):
                raise ValueError("guided_regex must be a string")
            key, build = ("regex", pattern), (
                lambda: G.compile_regex(pattern, tok)
            )
        elif payload.get("guided_json") is not None:
            schema = payload["guided_json"]
            if isinstance(schema, str):
                schema = json.loads(schema)
            if not isinstance(schema, dict):
                raise ValueError("guided_json must be a JSON-schema object")
            key = ("schema", json.dumps(schema, sort_keys=True))
            build = lambda: G.compile_json_schema(schema, tok)  # noqa: E731
        elif rf.get("type") == "json_schema":
            schema = (rf.get("json_schema") or {}).get("schema")
            if not isinstance(schema, dict):
                raise ValueError(
                    "response_format.json_schema.schema must be an object"
                )
            key = ("schema", json.dumps(schema, sort_keys=True))
            build = lambda: G.compile_json_schema(schema, tok)  # noqa: E731
        else:  # json_object
            key, build = ("json_object",), (lambda: G.compile_json(tok))
        with self.grammar_lock:
            if key in self.grammar_cache:
                self.grammar_cache.move_to_end(key)
                return self.grammar_cache[key]
        g = build()  # compile OUTSIDE the lock: can cost ~seconds
        with self.grammar_lock:
            self.grammar_cache[key] = g
            while len(self.grammar_cache) > 64:
                self.grammar_cache.popitem(last=False)
        return g

    def _stream_complete(
        self, payload: dict, prompt: str, gen, *, chat: bool, adapter_ids=None,
        stops=None, lp_n=None, grammar=None, deadline_s=None, slo_class=None,
        trace=None, tenant=None,
    ) -> None:
        """OpenAI streaming: real incremental chunks from the continuous
        engine; the lockstep engine generates fully, then emits one chunk.
        ``lp_n`` (continuous engine only, validated by the caller): attach
        per-chunk logprobs with ``lp_n`` alternatives."""
        cmpl_id = f"cmpl-{uuid.uuid4().hex[:24]}"
        t_stream0 = time.time()
        created = int(t_stream0)
        model = payload.get("model") or self.model_name
        kind = "chat.completion.chunk" if chat else "text_completion"

        def event(text, finish=None, role=None, logprobs=None):
            if chat:
                delta = {}
                if role is not None:
                    delta["role"] = role
                    delta["content"] = ""
                elif text:
                    delta = {"content": text}
                choice = {"index": 0, "delta": delta, "finish_reason": finish}
            else:
                choice = {"index": 0, "text": text, "finish_reason": finish}
            if logprobs is not None:
                choice["logprobs"] = logprobs
            out = {"id": cmpl_id, "object": kind, "created": created,
                   "model": model, "choices": [choice]}
            if getattr(self, "_adapter_fp", None):
                out["system_fingerprint"] = self._adapter_fp
            return out

        # Submit eagerly, BEFORE the SSE headers go out: stream_one reserves
        # the queue slot here, so QueueFullError still becomes an HTTP 429
        # instead of a silently truncated stream (ADVICE r2).
        stream_iter = None
        if self.threaded_engine is not None and (
            adapter_ids is None
            or getattr(self.threaded_engine, "multi_lora", False)
        ):
            etok = self.threaded_engine.tokenizer
            if lp_n is not None:
                stream_iter = self.threaded_engine.stream_one_with_logprobs(
                    [etok.bos_id] + etok.encode(prompt), lp_n,
                    max_new_tokens=gen.max_new_tokens,
                    temperature=gen.temperature,
                    top_p=gen.top_p,
                    seed=gen.seed,
                    grammar=grammar,
                    deadline_s=deadline_s,
                    slo_class=slo_class,
                    trace=trace,
                    tenant=tenant,
                )
            else:
                stream_iter = self.threaded_engine.stream_one(
                    [etok.bos_id] + etok.encode(prompt),
                    max_new_tokens=gen.max_new_tokens,
                    temperature=gen.temperature,
                    top_p=gen.top_p,
                    seed=gen.seed,
                    adapter_id=adapter_ids[0] if adapter_ids else None,
                    grammar=grammar,
                    deadline_s=deadline_s,
                    slo_class=slo_class,
                    trace=trace,
                    tenant=tenant,
                )

        def events():
            if chat:
                yield event("", role="assistant")  # role-announcement chunk
            tracker = _StopTracker(stops or [])
            n_gen = 0
            if stream_iter is not None and lp_n is not None:
                # Streaming logprobs (stops excluded by the caller): each
                # chunk carries its tokens' stats; text offsets advance
                # through the decoded stream.
                tok = self.threaded_engine.tokenizer
                pos = len(prompt)
                for toks, lp in stream_iter:
                    n_gen += len(toks)
                    tok_strs = [tok.decode([t]) for t in toks]
                    if chat:
                        lpj = {"content": [
                            {"token": s,
                             "logprob": lp["token_logprobs"][i],
                             "top_logprobs": [
                                 {"token": tok.decode([tid]), "logprob": tlp}
                                 for tid, tlp in zip(lp["top_ids"][i],
                                                     lp["top_logprobs"][i])
                             ]}
                            for i, s in enumerate(tok_strs)
                        ]}
                    else:
                        offsets = []
                        for s in tok_strs:
                            offsets.append(pos)
                            pos += len(s)
                        lpj = {
                            "tokens": tok_strs,
                            "token_logprobs": lp["token_logprobs"],
                            "top_logprobs": [
                                {tok.decode([tid]): tlp
                                 for tid, tlp in zip(lp["top_ids"][i],
                                                     lp["top_logprobs"][i])}
                                for i in range(len(tok_strs))
                            ],
                            "text_offset": offsets,
                        }
                    yield event("".join(tok_strs), logprobs=lpj)
            elif stream_iter is not None:
                tok = self.threaded_engine.tokenizer
                for chunk in stream_iter:
                    n_gen += len(chunk)
                    text = tracker.push(tok.decode(chunk))
                    if text:
                        yield event(text)
                    if tracker.hit:
                        break  # stream_one cancels the abandoned request
                tail = tracker.flush()
                if tail:
                    yield event(tail)
            else:
                # The lock-step stream generates fully before emitting, so
                # greedy streamed requests benefit from speculation the same
                # way non-streaming ones do.
                tok = self.generator.tokenizer
                prompt_ids = [tok.bos_id] + tok.encode(prompt)
                out = self._lockstep_generate(prompt_ids, gen, adapter_ids)
                n_gen = len(out)
                self._observe_lockstep(t_stream0, n_gen)
                self._note_usage_lockstep(tenant or "anonymous",
                                          len(prompt_ids), n_gen, t_stream0,
                                          slo_class=slo_class)
                text, hit = _apply_stop(tok.decode(out), tracker.stops)
                if hit:
                    # Fold into the tracker so the finish computation reports
                    # "stop" even when the completion also used its full
                    # token budget.
                    tracker.hit = True
                if text:
                    yield event(text)
            finish = (
                "stop"
                if tracker.hit or n_gen < gen.max_new_tokens
                else "length"
            )
            yield event("", finish=finish)

        self._send_sse(events())

    def _complete(self, payload: dict, *, chat: bool) -> None:
        # Request tracing (ISSUE 6): continue the client's/gateway's trace
        # (W3C traceparent) or root a fresh one; the engine's lifecycle
        # spans chain under this span via submit(trace=...), so the merged
        # timeline nests gateway -> server -> engine across processes. The
        # span also covers the stream-write leg (SSE chunks relay inside
        # _stream_complete before this method returns).
        span = self.tracer.start_span(
            "server.request",
            parent=parse_traceparent(self.headers.get("traceparent")),
            request_id=self._request_id(),
            route="chat" if chat else "completions",
        )
        try:
            if chat:
                messages = payload.get("messages") or []
                prompt = _chat_prompt(messages, self.generator.tokenizer)
            else:
                prompt = payload.get("prompt") or ""
                if isinstance(prompt, list):
                    prompt = prompt[0] if prompt else ""
            # Usage attribution (ISSUE 15): the credential-safe tenant
            # label every engine/ledger path below bills to.
            tenant = self._tenant_label()
            # Fresh seed per request unless the client pins one — otherwise
            # every temperature>0 request would replay jax.random.key(0).
            seed = payload.get("seed")
            if seed is None:
                import random as _random

                seed = _random.getrandbits(31)
            gen = GenerateConfig(
                max_new_tokens=int(
                    payload.get("max_tokens") or self.default_max_tokens
                ),
                temperature=float(payload.get("temperature") or 0.0),
                top_p=float(payload.get("top_p") or 1.0),
                seed=int(seed),
            )
            # Per-request deadline (ISSUE 5): the client's `deadline_s`
            # payload field, or the `X-Request-Deadline-S` header the
            # gateway stamps with the remaining fleet budget. Enforced by
            # the continuous engine (queue/slot eviction); an
            # already-expired arrival answers 504 before any device work
            # on either engine.
            deadline_s = payload.get("deadline_s")
            from_header = False
            if deadline_s is None:
                deadline_s = self.headers.get("X-Request-Deadline-S")
                from_header = deadline_s is not None
            if deadline_s is not None:
                try:
                    deadline_s = float(deadline_s)
                except (TypeError, ValueError):
                    self._send_json(400, {"error": {"message":
                        "deadline_s must be a number (seconds)"}})
                    return
                if deadline_s != deadline_s:  # NaN: poisons deadline sweeps
                    self._send_json(400, {"error": {"message":
                        "deadline_s must be a number (seconds)"}})
                    return
                if deadline_s <= 0:
                    if self.serving_metrics is not None:
                        self.serving_metrics.deadline_expired.inc()
                    self._send_json(504, {"error": {
                        "message": "deadline expired before any work began",
                        "type": "timeout_error",
                    }})
                    return
            # SLO class (ISSUE 8): scheduling priority for the continuous
            # engine's class-ordered admission/preemption. The X-SLO-Class
            # HEADER wins over the payload field — the gateway stamps it
            # when per-tenant admission pins a tenant to a class, and the
            # pin must override whatever the tenant's payload claims. On
            # paths whose scheduler cannot honor classes (lockstep, the pod
            # driver's replicated FIFO staging) an explicit non-default
            # payload value is rejected (reject-don't-drop) while a
            # header-derived hint is dropped, so gateway-routed traffic
            # still serves (the PR 5 deadline lesson).
            from ditl_tpu.infer.continuous import SLO_CLASSES

            slo_class = self.headers.get("X-SLO-Class")
            slo_from_header = slo_class is not None
            if slo_class is None:
                slo_class = payload.get("slo_class")
            if slo_class is not None:
                if slo_class not in SLO_CLASSES:
                    self._send_json(400, {"error": {"message":
                        f"unknown slo_class {slo_class!r} (one of "
                        f"{sorted(SLO_CLASSES)})"}})
                    return
                classful = (
                    self.threaded_engine is not None
                    and getattr(self.threaded_engine,
                                "supports_slo_classes", True)
                )
                if not classful:
                    ok, slo_class = self._gate_slo_class(
                        slo_class, slo_from_header)
                    if not ok:
                        return
            try:
                stops = _stop_list(payload.get("stop"))
            except ValueError as e:
                self._send_json(400, {"error": {"message": str(e)}})
                return
            # Multi-LoRA routing: the OpenAI "model" field (or the
            # gateway's X-Adapter-Name tenant pin) selects an adapter by
            # name. Registry-armed servers resolve LIVE (unknown/evicted
            # names 404 with a reason); legacy static servers keep serving
            # base for unknown names.
            from ditl_tpu.infer.adapters import AdapterNotFound

            try:
                adapter_ids = self._resolve_adapter(payload)
            except AdapterNotFound as e:
                self._send_json(e.status, {"error": {
                    "message": str(e), "type": "invalid_request_error",
                    "param": "model", "code": "model_not_found"}})
                return
            try:
                grammar = self._resolve_grammar(payload)
            except ValueError as e:
                self._send_json(400, {"error": {"message": str(e)}})
                return
            if (grammar is not None and adapter_ids is not None
                    and not getattr(self.threaded_engine, "multi_lora", False)):
                self._send_json(400, {"error": {"message":
                    "guided decoding with adapter routing requires a "
                    "multi-LoRA continuous engine"}})
                return
            try:
                n_choices = int(payload.get("n") or 1)
                best_of = int(payload.get("best_of") or n_choices)
            except (TypeError, ValueError):
                self._send_json(400, {"error": {"message":
                    "n and best_of must be integers"}})
                return
            if (slo_class is not None and adapter_ids is not None
                    and not getattr(self.threaded_engine, "multi_lora",
                                    False)):
                # Adapter requests on a non-multi-LoRA engine serve via the
                # lock-step generator — no class-ordered scheduler there.
                ok, slo_class = self._gate_slo_class(
                    slo_class, slo_from_header)
                if not ok:
                    return
            if deadline_s is not None:
                # Deadline ENFORCEMENT (queue/slot eviction) lives in the
                # continuous engine's single-choice path only. Everywhere
                # else — lockstep, the pod driver (per-process clocks would
                # desync its replicated scheduler), the adapter fallback,
                # n/best_of batching — an explicit client `deadline_s` is
                # rejected rather than silently decoded to the full budget
                # (reject-don't-drop), while the gateway's header (stamped
                # on every relay) is a best-effort hint and is dropped.
                enforceable = (
                    self.threaded_engine is not None
                    and getattr(self.threaded_engine, "supports_deadlines",
                                True)
                    and (adapter_ids is None
                         or getattr(self.threaded_engine, "multi_lora",
                                    False))
                    and n_choices == 1 and best_of == 1
                )
                if not enforceable:
                    if from_header:
                        deadline_s = None
                    else:
                        self._send_json(400, {"error": {"message":
                            "deadline_s requires the continuous-engine "
                            "single-choice serving path (no lockstep/pod "
                            "engine, adapter fallback, or n/best_of)"}})
                        return
            if n_choices > 1 or best_of > 1:
                if not (1 <= n_choices <= best_of <= 8):
                    self._send_json(400, {"error": {"message":
                        "need 1 <= n <= best_of <= 8"}})
                    return
                if payload.get("stream"):
                    self._send_json(400, {"error": {"message":
                        "n/best_of do not compose with stream"}})
                    return
                if payload.get("logprobs") not in (None, False):
                    self._send_json(400, {"error": {"message":
                        "logprobs with n > 1 is not supported"}})
                    return
                self._multi_complete(
                    payload, prompt, gen, chat=chat, n=n_choices,
                    best_of=best_of, adapter_ids=adapter_ids, stops=stops,
                    grammar=grammar, slo_class=slo_class,
                    slo_from_header=slo_from_header, trace=span,
                    tenant=tenant,
                )
                return
            # OpenAI semantics: completions' `logprobs: 0` is a real request
            # (chosen-token logprob, zero alternatives) — 0 is falsy, so test
            # presence, not truthiness. Chat's `logprobs: false` means off.
            lp_req = payload.get("logprobs")
            has_lp = lp_req is not None and lp_req is not False
            if payload.get("stream"):
                lp_n = None
                if has_lp:
                    # Streaming logprobs: served through the continuous
                    # engine's per-chunk stats; anything it can't carry
                    # (lock-step-only serving, stop sequences, adapter
                    # routing, N beyond the compiled logprobs_k) fails
                    # loudly instead of silently dropping the field.
                    if chat:
                        tl = payload.get("top_logprobs")
                        lp_n = int(tl) if tl is not None else 1
                    else:
                        lp_n = int(lp_req)
                    lp_n = max(0, min(lp_n, 20))
                    engine_k = getattr(self.threaded_engine, "logprobs_k", 0)
                    if not (self.threaded_engine is not None and engine_k > 0
                            and lp_n <= engine_k and not stops
                            and adapter_ids is None):
                        self._send_json(400, {"error": {"message":
                            "streaming logprobs requires --engine continuous "
                            "with --logprobs-k >= N, no stop sequences, and "
                            "no adapter routing"}})
                        return
                try:
                    self._stream_complete(
                        payload, prompt, gen, chat=chat,
                        adapter_ids=adapter_ids, stops=stops, lp_n=lp_n,
                        grammar=grammar, deadline_s=deadline_s,
                        slo_class=slo_class, trace=span, tenant=tenant,
                    )
                except QueueFullError as e:
                    # The stream's submit is eager (before SSE headers), so
                    # a full queue still becomes a real 429 (ADVICE r2).
                    self._send_429(str(e))
                except ValueError as e:
                    # Eager-submit validation (e.g. fsm_capacity exhausted)
                    # also precedes the SSE headers.
                    status = 503 if "fsm_capacity" in str(e) else 400
                    self._send_json(status, {"error": {"message": str(e)}})
                except (BrokenPipeError, ConnectionError):
                    logger.info("client disconnected mid-stream")
                except Exception:
                    # Headers (200/text-event-stream) may already be out —
                    # a JSON 500 would corrupt the stream; just log and close.
                    logger.exception("streaming completion failed")
                return
            t0 = time.time()
            logprobs_json = None
            lockstep_served = False
            if has_lp:
                # OpenAI logprobs: completions' `logprobs: N` = top-N; chat's
                # `logprobs: true` + `top_logprobs: N`. N is clamped (OpenAI
                # caps at 5/20).
                if chat:
                    # top_logprobs: 0 is a valid explicit request (chosen
                    # token only) — presence, not truthiness, again.
                    tl = payload.get("top_logprobs")
                    n_top = int(tl) if tl is not None else 1
                else:
                    n_top = int(lp_req)
                n_top = max(0, min(n_top, 20))
                engine_k = getattr(self.threaded_engine, "logprobs_k", 0)
                if (
                    self.threaded_engine is not None
                    and adapter_ids is None
                    and engine_k > 0
                    and n_top <= engine_k
                ):
                    # Continuous engine with logprobs armed: the request
                    # rides ordinary decode ticks (sharing the batch with
                    # everyone else) — no lock-step fallback stalling the
                    # engine's throughput for a standard capability.
                    tok = self.threaded_engine.tokenizer
                    prompt_ids = [tok.bos_id] + tok.encode(prompt)
                    gen_ids, lp = self.threaded_engine.generate_one_with_logprobs(
                        prompt_ids, n_top,
                        max_new_tokens=gen.max_new_tokens,
                        temperature=gen.temperature, top_p=gen.top_p,
                        seed=gen.seed,
                        grammar=grammar,
                        deadline_s=deadline_s,
                        slo_class=slo_class,
                        trace=span,
                        tenant=tenant,
                    )
                elif grammar is not None:
                    # Guided requests never fall back to the lock-step
                    # generator (no FSM path there) — the conditions above
                    # (logprobs_k >= N) must hold for guided + logprobs.
                    self._send_json(400, {"error": {"message":
                        "guided decoding with logprobs requires the "
                        "continuous engine armed with --logprobs-k >= N"}})
                    return
                elif not hasattr(self.generator, "generate_tokens_with_logprobs"):
                    # --pod wraps the generator in PodGenerator; its broadcast
                    # protocol doesn't carry logprobs (and device work must
                    # not bypass it).
                    self._send_json(
                        400,
                        {"error": {"message": "logprobs is not supported "
                                   "with --pod serving"}},
                    )
                    return
                else:
                    # Falling back to lock-step loses the class-ordered
                    # scheduler: drop/400 a non-default class first.
                    ok, slo_class = self._gate_slo_class(
                        slo_class, slo_from_header)
                    if not ok:
                        return
                    # Lock-step generator (exact per-step logits): the
                    # no-continuous-engine server, adapter requests, and
                    # n_top beyond the engine's compiled logprobs_k. The
                    # Generator's LRU program cache bounds what other
                    # client-controlled compile-key fields (temperature,
                    # top_p, max_tokens) can pin in memory.
                    tok = self.generator.tokenizer
                    prompt_ids = [tok.bos_id] + tok.encode(prompt)
                    # The engine's top-k needs k >= 1; n_top == 0 is served
                    # by computing one alternative and emitting none.
                    lp_gen = dataclasses.replace(gen, logprobs=max(1, n_top))
                    with self.device_lock:
                        outs, lps = self.generator.generate_tokens_with_logprobs(
                            [prompt_ids], lp_gen, adapter_ids
                        )
                    gen_ids = outs[0]
                    lp = lps[0]
                    lockstep_served = True
                # Apply stop truncation at TOKEN granularity before building
                # the logprobs JSON: the entries must stay aligned with the
                # returned text (keep whole tokens up to the stop cut).
                full_text = tok.decode(gen_ids)
                cut_text, hit_stop = _apply_stop(full_text, stops)
                n_gen_full = len(gen_ids)
                if hit_stop:
                    keep, acc = 0, ""
                    for t in gen_ids:
                        piece = tok.decode([t])
                        if len(acc) + len(piece) > len(cut_text):
                            break
                        acc += piece
                        keep += 1
                    gen_ids = gen_ids[:keep]
                    lp = {k: v[:keep] for k, v in lp.items()}
                    text = acc
                else:
                    text = full_text
                tok_strs = [tok.decode([t]) for t in gen_ids]
                if chat:
                    logprobs_json = {
                        "content": [
                            {
                                "token": s,
                                "logprob": lp["token_logprobs"][i],
                                "top_logprobs": [
                                    {"token": tok.decode([tid]), "logprob": tlp}
                                    for tid, tlp in zip(
                                        lp["top_ids"][i][:n_top],
                                        lp["top_logprobs"][i][:n_top],
                                    )
                                ],
                            }
                            for i, s in enumerate(tok_strs)
                        ]
                    }
                else:
                    offsets, pos = [], len(prompt)
                    for s in tok_strs:
                        offsets.append(pos)
                        pos += len(s)
                    logprobs_json = {
                        "tokens": tok_strs,
                        "token_logprobs": lp["token_logprobs"],
                        "top_logprobs": [
                            {
                                tok.decode([tid]): tlp
                                for tid, tlp in zip(
                                    lp["top_ids"][i][:n_top],
                                    lp["top_logprobs"][i][:n_top],
                                )
                            }
                            for i in range(len(tok_strs))
                        ],
                        "text_offset": offsets,
                    }
                n_prompt = len(prompt_ids)
                n_gen = n_gen_full
            elif self.threaded_engine is not None and (
                adapter_ids is None
                or getattr(self.threaded_engine, "multi_lora", False)
            ):
                tok = self.threaded_engine.tokenizer
                prompt_ids = [tok.bos_id] + tok.encode(prompt)
                out = self.threaded_engine.generate_one(
                    prompt_ids,
                    max_new_tokens=gen.max_new_tokens,
                    temperature=gen.temperature,
                    top_p=gen.top_p,
                    seed=gen.seed,
                    adapter_id=adapter_ids[0] if adapter_ids else None,
                    grammar=grammar,
                    deadline_s=deadline_s,
                    slo_class=slo_class,
                    trace=span,
                    tenant=tenant,
                )
                n_gen = len(out)
                text, hit_stop = _apply_stop(tok.decode(out), stops)
                n_prompt = len(prompt_ids)
            else:
                if grammar is not None:  # unreachable guard: no FSM path
                    self._send_json(400, {"error": {"message":
                        "guided decoding requires the continuous engine"}})
                    return
                tok = self.generator.tokenizer
                prompt_ids = [tok.bos_id] + tok.encode(prompt)
                out = self._lockstep_generate(prompt_ids, gen, adapter_ids)
                n_gen = len(out)
                text, hit_stop = _apply_stop(tok.decode(out), stops)
                n_prompt = len(prompt_ids)
                lockstep_served = True
            # "length" = the GENERATED token count hit the budget (decoded
            # text round-trips are not token-count-preserving, so never
            # re-encode to decide this).
            finish = (
                "stop" if hit_stop or n_gen < gen.max_new_tokens else "length"
            )
            # Bill the tokens actually GENERATED (n_gen), not a re-encode
            # of the decoded/stop-trimmed text — decode->encode is not
            # idempotent for every tokenizer (ADVICE r3; a byte tokenizer
            # stripping non-printables billed 0 for 8 generated tokens).
            n_out = n_gen
            kind = "chat.completion" if chat else "text_completion"
            choice = (
                {"index": 0, "message": {"role": "assistant", "content": text},
                 "finish_reason": finish}
                if chat
                else {"index": 0, "text": text, "finish_reason": finish}
            )
            if logprobs_json is not None:
                choice["logprobs"] = logprobs_json
            if lockstep_served:
                # BEFORE the response write: a client that scrapes /metrics
                # the instant its completion returns must see the counters
                # moved (the response write itself is not service time —
                # and recording after it raced exactly that scrape).
                self._observe_lockstep(t0, n_out)
                self._note_usage_lockstep(tenant, n_prompt, n_out, t0,
                                          slo_class=slo_class)
            self._send_json(
                200,
                {
                    "id": f"cmpl-{uuid.uuid4().hex[:24]}",
                    "object": kind,
                    "created": int(t0),
                    "model": payload.get("model") or self.model_name,
                    # Which adapter GENERATION served (ISSUE 16): a
                    # publication's flip is visible as this changing.
                    **({"system_fingerprint": self._adapter_fp}
                       if getattr(self, "_adapter_fp", None) else {}),
                    "choices": [choice],
                    "usage": {
                        "prompt_tokens": n_prompt,
                        "completion_tokens": n_out,
                        "total_tokens": n_prompt + n_out,
                    },
                },
            )
            logger.info(
                "served %s: %d prompt + %d completion tokens in %.2fs",
                kind, n_prompt, n_out, time.time() - t0,
            )
        except Exception as e:  # total-server: errors become JSON, not crashes
            from ditl_tpu.infer.continuous import BadRequestError, QueueFullError

            span.annotate(error=type(e).__name__)
            if isinstance(e, QueueFullError):
                self._send_429(str(e))
                return
            if isinstance(e, DeadlineExceededError):
                # The engine already evicted the request and counted it
                # (deadline_expired); 504 tells the client (and gateway)
                # the deadline — not the server — ended this request.
                self._send_json(504, {"error": {
                    "message": str(e), "type": "timeout_error",
                }})
                return
            if isinstance(e, ValueError) and "fsm_capacity exhausted" in str(e):
                # Guided table full: a server-capacity condition, not a
                # client error. Rows are never evicted (active slots may
                # point anywhere in the table), so NEW grammars keep
                # failing until the operator restarts with a larger
                # --fsm-capacity; already-registered grammars still serve.
                self._send_json(503, {"error": {"message":
                    str(e) + " (new grammars need a restart with a larger "
                    "--fsm-capacity; already-registered grammars still "
                    "serve)"}})
                return
            if isinstance(e, BadRequestError):
                # Engine request validation (seed/max_tokens bounds, prompt
                # too long, bad adapter, guided-in-pod): the client's fault
                # — 400. Only this dedicated class maps here; any other
                # ValueError is a server bug and stays on the logged 500
                # path below.
                self._send_json(400, {"error": {"message": str(e)}})
                return
            logger.exception("completion failed")
            self._send_json(500, {"error": {"message": str(e)}})
        finally:
            span.end()


def make_server(
    generator: Generator,
    *,
    host: str = "127.0.0.1",
    port: int = 8300,
    model_name: str = "ditl-tpu",
    default_max_tokens: int = 64,
    threaded_engine=None,
    adapter_names: dict | None = None,
    spec_generator=None,
    max_pending: int | None = None,
    tracer: Tracer | None = None,
    slo: BurnRateMonitor | None = None,
    telemetry=None,
    role: str = "hybrid",
    incidents=None,
    serving_metrics: ServingMetrics | None = None,
    cold_start_s: float | None = None,
    kv_handoff: bool = False,
    usage=None,
    usage_ledger=None,
    adapter_registry=None,
    adapter_drain_timeout_s: float = 30.0,
) -> DrainableHTTPServer:
    """Build (not start) the HTTP server — tests drive it on a thread.
    Pass ``threaded_engine`` (infer/continuous.ThreadedEngine) to serve with
    continuous batching instead of the lock-step Generator;
    ``adapter_names`` maps OpenAI "model" names to multi-LoRA adapter ids
    (the generator's params must be a stacked-adapter tree);
    ``spec_generator`` (Speculative/AutoSpeculativeGenerator) serves greedy
    lock-step requests — streaming and non-streaming — speculatively;
    ``max_pending`` caps concurrent in-flight completion work (429 beyond
    it) — the lockstep overload control; ``role`` tags the replica's
    disaggregated-fleet serving shape (gateway/roles.py), echoed on
    /health for the gateway's role-aware routing.

    The returned :class:`DrainableHTTPServer` supports ``drain()`` /
    ``close(drain=True)`` (graceful: /health flips to draining, new work
    gets 503, in-flight finishes) and ``kill()`` (abrupt, for failover
    drills)."""

    # One telemetry bundle per server: an explicit ``serving_metrics``
    # (the incident-armed serve() path shares one bundle between the
    # engine, the incident manager, and this server), else the continuous
    # engine's own (its scheduler records into it), else a fresh bundle
    # the lock-step handler path records into. Either way /metrics
    # renders it. ``incidents`` (telemetry/incident.IncidentManager) arms
    # the /incidents listing endpoint.
    if serving_metrics is None:
        serving_metrics = getattr(threaded_engine, "metrics", None)
    if serving_metrics is None:
        serving_metrics = ServingMetrics()
    # Tracing (ISSUE 6): default to the engine's tracer so one knob
    # (constructing the engine with a journal-backed tracer) arms the whole
    # replica — server.request spans and engine lifecycle spans land in the
    # same per-process journal and nest under one trace.
    if tracer is None:
        tracer = getattr(threaded_engine, "tracer", None) or NULL_TRACER
    # Usage metering (ISSUE 15): default to the engine's own meter (the
    # tracer rule — constructing the engine with one arms the replica
    # end-to-end); the families render on whatever registry this server's
    # /metrics serves. bind is idempotent, so an engine-bound meter keeps
    # its binding.
    if usage is None:
        usage = getattr(threaded_engine, "usage", None)
    if usage is not None:
        usage.bind(serving_metrics.registry)
    if slo is None:
        # SLO burn-rate monitor over this server's bundle; ``telemetry``
        # (config.TelemetryConfig) overrides the objectives, defaults
        # otherwise. Always on: sampling happens only on /slo//metrics
        # scrapes, so an unscraped server pays nothing.
        kw = telemetry.serving_slo_kwargs() if telemetry is not None else {}
        slo = serving_slo(serving_metrics, **kw)
    # Adapter plane (ISSUE 16): auto-arm the registry whenever a
    # multi-LoRA THREADED continuous engine serves (hasattr call = the
    # driver-thread seam exists; the pod driver is excluded on purpose —
    # a hot install on process 0 alone would desync the replicated
    # schedulers, so pod fleets keep the rolling-restart path). Launch
    # adapters seed the registry so /v1/adapters and eviction cover them.
    if (adapter_registry is None and threaded_engine is not None
            and getattr(threaded_engine, "multi_lora", False)
            and hasattr(threaded_engine, "call")):
        from ditl_tpu.infer.adapters import AdapterRegistry

        inner = getattr(threaded_engine, "_engine", threaded_engine)
        adapter_registry = AdapterRegistry(
            threaded_engine,
            journal=getattr(tracer, "journal", None),
            usage_ledger=usage_ledger
            or getattr(inner, "usage_ledger", None),
            drain_timeout_s=adapter_drain_timeout_s,
        )
        for name, row in (adapter_names or {}).items():
            adapter_registry.seed(name, row)
    handler = type(
        "BoundHandler",
        (_Handler,),
        {
            "generator": generator,
            "threaded_engine": threaded_engine,
            "model_name": model_name,
            "device_lock": threading.Lock(),
            "default_max_tokens": default_max_tokens,
            "adapter_names": adapter_names or {},
            "spec_generator": spec_generator,
            "grammar_cache": collections.OrderedDict(),
            "grammar_lock": threading.Lock(),
            "embed_cache": collections.OrderedDict(),
            "serving_metrics": serving_metrics,
            "max_pending": max_pending,
            "tracer": tracer,
            "slo": slo,
            "role": role,
            "incidents": incidents,
            "kv_handoff_enabled": kv_handoff,
            "usage": usage,
            "usage_ledger": usage_ledger,
            "adapter_registry": adapter_registry,
        },
    )
    server = DrainableHTTPServer((host, port), handler)
    if cold_start_s is not None:
        # Measured time-to-first-ready (ISSUE 12): echoed on /health so
        # the gateway's scale-to-zero wake budget uses a measured number.
        server.cold_start_s = float(cold_start_s)
    return server


def serve(argv: list[str] | None = None) -> int:
    # Cold-start clock (ISSUE 12): time-to-first-ready measured from here
    # (before the jax import below — that import and the engine build ARE
    # the cold start; the persistent compile cache is what shrinks it on a
    # warm start) to the moment the listening server is built.
    t_serve_start = time.monotonic()
    import jax

    from ditl_tpu.data.tokenizer import get_tokenizer
    from ditl_tpu.models import llama
    from ditl_tpu.models.presets import get_preset

    parser = argparse.ArgumentParser(prog="ditl_tpu.infer.server")
    parser.add_argument("--preset", default=None)
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=8300)
    parser.add_argument("--tokenizer", default="byte")
    parser.add_argument("--checkpoint-dir", default="")
    parser.add_argument("--max-tokens", type=int, default=64)
    parser.add_argument(
        "--engine", choices=("lockstep", "continuous"), default="lockstep"
    )
    parser.add_argument("--slots", type=int, default=8,
                        help="decode slots for --engine continuous")
    parser.add_argument(
        "--prefill-chunk", type=int, default=0,
        help="chunked prefill for --engine continuous: prompts longer than "
        "this prefill one chunk per tick, interleaved with in-flight "
        "decodes (0 = whole-prompt prefill)",
    )
    parser.add_argument(
        "--token-budget", type=int, default=0,
        help="per-tick token budget for --engine continuous (ISSUE 8): "
        "each scheduler tick spends at most budget - decode_ready x "
        "decode_chunk tokens on prefill chunks, so co-scheduled long "
        "prompts cannot stall decode-ready streams (stall-free batching; "
        "pair with --prefill-chunk). Must cover one full decode tick "
        "(slots x decode-chunk); 0 = unbudgeted",
    )
    parser.add_argument(
        "--host-tier-mb", type=float, default=0.0,
        help="host-RAM prefix-cache tier capacity in MiB (ISSUE 13): "
        "LRU-evicted published KV pages spill to host memory and swap "
        "back in on admission miss, so the shared-prefix working set "
        "stops being bounded by HBM pages. Requires --cache-mode paged; "
        "0 = off",
    )
    parser.add_argument(
        "--spill-max-pages-per-tick", type=int, default=32,
        help="per-tick cap on pages the host tier's spill batch moves "
        "device->host (bounds the one batched device_get a tick pays; "
        "the remainder carries over)",
    )
    parser.add_argument(
        "--kv-handoff", action="store_true",
        help="serve the /internal/prefill + /internal/kv_handoff "
        "endpoints (ISSUE 13): the gateway ships a prefill_heavy "
        "replica's finished prefill here instead of re-prefilling. "
        "Requires a paged continuous engine",
    )
    parser.add_argument(
        "--speculative", choices=("off", "on", "auto"), default="off",
        help="prompt-lookup speculative decoding: 'on' always speculates, "
        "'auto' decides from measured acceptance. Continuous engine: "
        "speculative decode ticks for greedy AND sampled requests (greedy "
        "outputs token-identical; sampled exact in distribution via "
        "rejection sampling). Lock-step engine: greedy requests via "
        "infer/speculative.py",
    )
    parser.add_argument(
        "--logprobs-k", type=int, default=0,
        help="arm the continuous engine to serve per-token logprobs with up "
        "to K alternatives natively (requests ride ordinary decode ticks); "
        "0 = logprob requests fall back to the lock-step generator",
    )
    parser.add_argument(
        "--max-queue", type=int, default=0,
        help="admission-queue depth cap for --engine continuous; beyond it "
        "requests get HTTP 429 (0 = unbounded)",
    )
    parser.add_argument(
        "--max-pending", type=int, default=0,
        help="cap on concurrent in-flight completion requests (the lockstep "
        "overload control — beyond it requests get HTTP 429 instead of "
        "piling up on the device lock); 0 = unbounded",
    )
    parser.add_argument(
        "--admission", choices=("reserve", "optimistic"), default="reserve",
        help="paged admission policy: 'reserve' books worst-case pages "
        "(prompt+max_tokens) up front; 'optimistic' books prompt + one "
        "tick, feeds pages per tick, and preempts the youngest request on "
        "pool exhaustion (exact resume) — more concurrency when clients "
        "set pessimistic max_tokens",
    )
    parser.add_argument(
        "--pipeline-ticks", action="store_true",
        help="double-buffered decode ticks for --engine continuous: "
        "dispatch tick N+1 before fetching tick N, overlapping host "
        "dispatch/fetch round trips with device compute (harvest and "
        "admission lag one tick; outputs are token-identical)",
    )
    parser.add_argument(
        "--fsm-capacity", type=int, default=0,
        help="arm guided (grammar-constrained) decoding on --engine "
        "continuous: total DFA states servable at once (device table rows; "
        "a JSON grammar is ~1.1k states at depth 5, a typical regex tens). "
        "Requests then accept guided_regex / guided_json / response_format "
        "json_object. 0 = off",
    )
    parser.add_argument(
        "--draft-preset", default="",
        help="model-based speculation (--speculative --engine continuous): "
        "preset name of a small DRAFT model whose greedy predictions draft "
        "for the target's verify forwards (same tokenizer/vocab); every "
        "tick speculates. Pair with --draft-checkpoint for trained weights",
    )
    parser.add_argument(
        "--draft-checkpoint", default="",
        help="Orbax checkpoint dir for --draft-preset's weights "
        "(random-init without it — only useful for smoke tests)",
    )
    parser.add_argument(
        "--cache-mode", choices=("contiguous", "paged"), default="contiguous",
        help="KV cache layout for --engine continuous: 'paged' pools KV in "
        "content-hashed pages with automatic prefix reuse "
        "(infer/paged_cache.py, ops/paged_attention.py)",
    )
    parser.add_argument(
        "--page-size", type=int, default=256,
        help="tokens per KV page for --cache-mode paged (256 decodes "
        "~1.5x faster than contiguous on v5e; smaller = finer sharing)",
    )
    parser.add_argument(
        "--pages", type=int, default=0,
        help="page-pool size for --cache-mode paged; 0 = the contiguous "
        "equivalent (slots x max context)",
    )
    parser.add_argument(
        "--quantize", choices=("none", "int8"), default="none",
        help="weight-only int8 (halves decode HBM reads; ops/quant.py)",
    )
    parser.add_argument(
        "--kv-quant", choices=("none", "int8"), default="none",
        help="int8 KV cache (halves cache reads/footprint; composes with "
        "both cache modes)",
    )
    parser.add_argument(
        "--override", action="append", default=[], metavar="FIELD=VALUE",
        help="ModelConfig override (repeatable), e.g. lora_rank=16 or "
        "hidden_size=64 — same dotted-override machinery as the launcher",
    )
    parser.add_argument(
        "--adapter", action="append", default=[], metavar="NAME=ORBAX_DIR",
        help="multi-LoRA serving (repeatable): load the LoRA adapters from "
        "an Orbax checkpoint dir; requests with \"model\": NAME use that "
        "adapter, any other model name serves the base weights",
    )
    parser.add_argument(
        "--adapter-pool", type=int, default=0,
        help="adapter plane (ISSUE 16, --engine continuous): reserve this "
        "many EXTRA zeroed rows in the stacked adapter pool for hot "
        "loads — POST /v1/adapters/load installs manifest-verified "
        "adapter checkpoints into free rows at runtime (no restart), "
        "/v1/adapters/evict drains and frees them. Composes with "
        "--adapter (launch adapters seed the registry); without it, "
        "needs a LoRA-capable config (model.lora_rank > 0)",
    )
    parser.add_argument(
        "--mesh", default="",
        help='shard the model over a device mesh, e.g. "tensor=4" or '
        '"fsdp=2,tensor=4" (axes as in MeshConfig); spans all pod devices',
    )
    parser.add_argument(
        "--pod", action="store_true",
        help="multi-host serving: every process joins the broadcast-driven "
        "SPMD decode loop (infer/podserve.py); process 0 serves HTTP",
    )
    parser.add_argument(
        "--max-cache-len", type=int, default=0,
        help="per-slot KV cache cap for --engine continuous; 0 = model "
        "max_seq_len (set this for long-context presets like llama31-8b, "
        "whose 131072-token cache would be ~17 GB per slot)",
    )
    parser.add_argument(
        "--role", choices=("hybrid", "prefill_heavy", "decode_heavy"),
        default="hybrid",
        help="disaggregated-fleet role tag (ISSUE 9): echoed on /health so "
        "a gateway steering by class+role reads the replica's own claim. "
        "Purely a label — pair it with the matching --slots/--token-budget/"
        "--prefill-chunk knobs (the launcher's gateway.replica_roles does "
        "both)",
    )
    parser.add_argument(
        "--trace-dir", default="",
        help="arm end-to-end request tracing (ISSUE 6): span records "
        "(server.request + the engine's queue/prefill/decode lifecycle, "
        "tick instants) append to {dir}/events-server-<pid>.jsonl; export "
        "with python -m ditl_tpu.telemetry.trace_export --dir DIR",
    )
    parser.add_argument(
        "--telemetry-override", action="append", default=[],
        metavar="FIELD=VALUE",
        help="TelemetryConfig override (repeatable), e.g. slo_ttft_s=0.5 "
        "or journal_max_mb=64 — tunes the /slo objectives and the trace "
        "journal's rotation cap",
    )
    parser.add_argument(
        "--incident-dir", default="",
        help="arm the flight-recorder/anomaly/incident plane (ISSUE 10): "
        "the continuous engine's detectors (deadline/429 storms, "
        "preemption thrash, TTFT/TPOT jumps, hit-ratio collapse) and SLO "
        "burn-alert transitions assemble fingerprint-deduped incident "
        "bundles into this directory, listed at /incidents and via "
        "python -m ditl_tpu.telemetry.incident --dir DIR; detector "
        "thresholds ride --telemetry-override (anomaly_*/incident_*)",
    )
    parser.add_argument(
        "--usage-dir", default="",
        help="arm the crash-consistent per-tenant usage ledger (ISSUE 15): "
        "one JSONL row per terminal request (outcome 200/429/504/cancel, "
        "prompt/generated tokens, cached-token tiers, queue wait, "
        "device-time estimate, interference, preemptions) appended to "
        "{dir}/usage-server-<pid>.jsonl; aggregate with "
        "python -m ditl_tpu.telemetry.usage --dir DIR",
    )
    parser.add_argument(
        "--no-usage-metering", action="store_true",
        help="disable the in-memory per-tenant usage meter (/usage, "
        "ditl_usage_* families, noisy-neighbor conviction windows) — "
        "the metering-off A/B leg; on by default",
    )
    parser.add_argument(
        "--usage-override", action="append", default=[],
        metavar="FIELD=VALUE",
        help="UsageConfig override (repeatable), e.g. "
        "max_tenant_families=64 or conviction_share=0.5",
    )
    args = parser.parse_args(argv)

    from ditl_tpu.config import Config, parse_overrides

    _cfg = parse_overrides(
        Config(), [f"telemetry.{o}" for o in args.telemetry_override]
        + [f"usage.{o}" for o in args.usage_override]
    )
    telemetry_cfg = _cfg.telemetry
    usage_cfg = _cfg.usage
    tracer = None
    if args.trace_dir and jax.process_index() == 0:
        # Process-0-gated like serving itself: pod WORKER replicas replay
        # the coordinator's scheduler ticks with no upstream trace context
        # — an armed worker tracer would journal a rootless phantom span
        # tree per request (N traces for one client request in the export).
        import os

        from ditl_tpu.telemetry.journal import EventJournal

        tag = os.getpid()  # unique per replica subprocess behind a gateway
        tracer = Tracer(EventJournal(
            os.path.join(args.trace_dir, f"events-server-{tag}.jsonl"),
            source=f"server-{tag}",
            max_bytes=telemetry_cfg.journal_max_bytes(),
        ))

    # Per-tenant usage metering (ISSUE 15): the meter is on by default on
    # process 0 (bounded per-tenant state, terminal-path-only updates);
    # --usage-dir additionally arms the crash-consistent ledger. Both are
    # handed to the engine (its terminal paths write the rows) and to
    # make_server (the lockstep paths + /usage).
    usage_meter = usage_ledger = None
    if not args.no_usage_metering and jax.process_index() == 0:
        from ditl_tpu.telemetry.usage import UsageMeter

        usage_meter = UsageMeter(
            max_tenant_families=usage_cfg.max_tenant_families)
    if args.usage_dir and jax.process_index() == 0:
        import os

        from ditl_tpu.telemetry.usage import UsageLedger, usage_ledger_path

        usage_ledger = UsageLedger(
            usage_ledger_path(args.usage_dir, f"server-{os.getpid()}"),
            source=f"server-{os.getpid()}",
            max_bytes=telemetry_cfg.journal_max_bytes(),
        )

    # Flight recorder + anomaly plane (ISSUE 10): the engine's tick ring is
    # always on; --incident-dir additionally arms the serving detectors +
    # the incident manager, all sharing ONE metrics bundle so the bundle's
    # metrics.prom snapshot is exactly what /metrics would have answered.
    serving_metrics = incidents = anomaly_monitor = slo = None
    if args.incident_dir and jax.process_index() == 0:
        import os

        from ditl_tpu.telemetry import (  # noqa: F401 (grouped arm imports)
            AnomalyPlane, FlightRecorder, IncidentManager,
            ServingAnomalyMonitor, ServingDetector, ServingMetrics,
        )
        from ditl_tpu.telemetry.slo import serving_slo

        serving_metrics = ServingMetrics()
        flight = FlightRecorder(telemetry_cfg.flight_ring_size)
        journal = tracer.journal if tracer is not None else None
        incidents = IncidentManager(
            args.incident_dir,
            flight=flight,
            metrics_render=serving_metrics.render,
            journal_dir=args.trace_dir,
            registry=serving_metrics.registry,
            source=f"server-{os.getpid()}",
            **telemetry_cfg.incident_kwargs(),
        )
        plane = AnomalyPlane(incidents=incidents, journal=journal)
        slo = serving_slo(
            serving_metrics, **telemetry_cfg.serving_slo_kwargs(),
            journal=journal, on_alert=plane.on_slo_alert,
        )
        anomaly_monitor = ServingAnomalyMonitor(
            plane,
            ServingDetector(**telemetry_cfg.serving_detector_kwargs()),
            slo=slo,
            check_every=telemetry_cfg.anomaly_check_every_ticks,
            # Noisy-neighbor forensics (ISSUE 15): when a latency storm
            # fires, the monitor convicts the tenant dominating the
            # meter's windowed prefill/device share and names it (plus
            # its usage snapshot) in the incident bundle.
            usage=usage_meter,
            conviction_share=usage_cfg.conviction_share,
            conviction_min_tokens=usage_cfg.conviction_min_tokens,
        )
    else:
        flight = None

    if args.mesh and not args.pod and jax.process_count() > 1:
        parser.error("--mesh on a multi-host pod requires --pod: the mesh "
                     "spans all hosts' devices, so every process must join "
                     "the collective decode loop")
    # --adapter composes with BOTH engines: the continuous engine carries
    # a per-slot adapter id (requests with different adapters share ticks).
    if args.adapter and args.pod and args.engine != "continuous":
        parser.error("--adapter with --pod requires --engine continuous "
                     "(only the continuous tick broadcast carries adapter "
                     "ids)")
    if args.speculative != "off" and args.engine != "continuous":
        # Lock-step speculation rides its own generator (below); the extra
        # compositions (pod, adapters) exist on the continuous engine only.
        if args.pod:
            parser.error("--speculative with --pod requires --engine "
                         "continuous (spec ticks ride the tick broadcast; "
                         "the lock-step pod protocol has no verify path)")
        if args.adapter:
            parser.error("--speculative with --adapter requires --engine "
                         "continuous (spec ticks carry per-slot adapter "
                         "ids; the lock-step spec generator does not)")
    if args.fsm_capacity and args.engine != "continuous":
        parser.error("--fsm-capacity (guided decoding) requires --engine "
                     "continuous: grammar masks ride the slot scheduler's "
                     "decode ticks")
    if args.draft_preset and (
        args.engine != "continuous" or args.speculative == "off"
    ):
        parser.error("--draft-preset requires --engine continuous with "
                     "--speculative on|auto (the draft model drafts for "
                     "speculative ticks)")
    if args.draft_checkpoint and not args.draft_preset:
        parser.error("--draft-checkpoint needs --draft-preset")
    if args.fsm_capacity and args.pod:
        parser.error("--fsm-capacity does not compose with --pod yet (the "
                     "tick broadcast does not carry grammar registrations)")
    if args.pipeline_ticks and args.engine != "continuous":
        parser.error("--pipeline-ticks requires --engine continuous")
    if args.host_tier_mb and (
        args.engine != "continuous" or args.cache_mode != "paged"
    ):
        parser.error("--host-tier-mb requires --engine continuous with "
                     "--cache-mode paged (the tier spills and swaps KV "
                     "pages)")
    if args.host_tier_mb and args.pod:
        parser.error("--host-tier-mb does not compose with --pod yet "
                     "(every process would pay the spill fetch, and "
                     "handoff imports would desync the replicated "
                     "scheduler)")
    if args.kv_handoff and (
        args.engine != "continuous" or args.cache_mode != "paged"
        or args.pod
    ):
        parser.error("--kv-handoff requires a solo paged continuous "
                     "engine (--engine continuous --cache-mode paged, "
                     "no --pod)")
    # --pipeline-ticks and --admission optimistic both compose with --pod:
    # the lagged harvest and the preemption decisions (_topup_pages /
    # _pick_victim) are deterministic functions of the replicated scheduler
    # state, so every replica double-buffers, preempts, and resumes
    # identically. Pinned single-process in tests/test_podserve.py and at
    # real process_count=2 by the "paged" drill leg (tests/multiproc_drill.py).
    if args.admission == "optimistic":
        if args.engine != "continuous" or args.cache_mode != "paged":
            parser.error("--admission optimistic requires --engine "
                         "continuous --cache-mode paged (only the page pool "
                         "can be reclaimed mid-flight)")
    if jax.process_index() != 0 and not args.pod:
        # Without --pod, one process binds the port and the others exit; with
        # --pod every process joins the collective decode loop below.
        logger.info("process %d: serving is process-0 only, exiting", jax.process_index())
        return 0

    mesh = None
    if args.mesh:
        import dataclasses as _dc

        from ditl_tpu.config import MeshConfig
        from ditl_tpu.runtime.mesh import build_mesh

        axes = dict(kv.split("=", 1) for kv in args.mesh.split(","))
        mesh = build_mesh(
            _dc.replace(MeshConfig(), **{k: int(v) for k, v in axes.items()})
        )

    cfg = get_preset(args.preset) if args.preset else ModelConfig()
    if args.override:
        from ditl_tpu.config import Config, parse_overrides

        cfg = parse_overrides(
            Config(model=cfg), [f"model.{o}" for o in args.override]
        ).model
    if args.kv_quant == "int8":
        cfg = dataclasses.replace(cfg, kv_cache_dtype="int8")
    tokenizer = get_tokenizer(args.tokenizer)
    params = llama.init_params(jax.random.key(0), cfg)
    if args.checkpoint_dir:
        from ditl_tpu.train.checkpoint import CheckpointManager

        ckpt = CheckpointManager(args.checkpoint_dir)
        restored = ckpt.restore_latest_params(jax.eval_shape(lambda: params))
        if restored is not None:
            params = restored
            logger.info("restored params from %s", args.checkpoint_dir)
        ckpt.close()
    adapter_names: dict[str, int] = {}
    if args.adapter_pool < 0:
        parser.error("--adapter-pool must be >= 0")
    if args.adapter_pool and (args.engine != "continuous" or args.pod):
        # Hot loads ride the ThreadedEngine.call driver seam; the lockstep
        # path has no driver thread and a pod install on process 0 alone
        # would desync the replicated schedulers.
        parser.error("--adapter-pool requires --engine continuous without "
                     "--pod (hot loads ride the driver-thread seam)")
    if args.adapter or args.adapter_pool:
        if cfg.lora_rank <= 0:
            parser.error("--adapter/--adapter-pool need a LoRA-capable "
                         "config (a preset/checkpoint with "
                         "model.lora_rank > 0)")
        if args.quantize == "int8":
            parser.error("--adapter does not compose with --quantize "
                         "(adapters stay float; merge instead to quantize)")
        from ditl_tpu.models import lora as lora_mod
        from ditl_tpu.train.checkpoint import CheckpointManager

        # Adapter id 0 serves the "base" model name. If the restored base
        # checkpoint was itself LoRA-fine-tuned (its own lora tree is
        # non-zero), that tree IS the base behavior — replacing it with a
        # zeros adapter would silently serve un-adapted weights for the base
        # model name.
        base_lora = params["layers"].get("lora")
        if base_lora is not None and any(
            bool(jax.numpy.any(leaf != 0)) for leaf in jax.tree.leaves(base_lora)
        ):
            stacks = [base_lora]
            logger.info(
                "--adapter: base checkpoint carries a non-zero LoRA tree; "
                "keeping it as adapter slot 0"
            )
        else:
            stacks = [lora_mod.zeros_adapter(cfg)]  # id 0 = base model
        for item in args.adapter:
            if "=" not in item:
                parser.error(f"--adapter wants NAME=ORBAX_DIR, got {item!r}")
            name, path = item.split("=", 1)
            ckpt = CheckpointManager(path)
            restored = ckpt.restore_latest_params(jax.eval_shape(lambda: params))
            ckpt.close()
            if restored is None:
                parser.error(f"--adapter {name}: no checkpoint in {path}")
            adapter = restored["layers"].get("lora")
            if adapter is None:
                parser.error(f"--adapter {name}: checkpoint has no LoRA tree")
            stacks.append(adapter)
            adapter_names[name] = len(stacks) - 1
        # Hot-load pool (ISSUE 16): extra zeroed rows the adapter
        # registry fills at runtime — a zeros row serves exactly base
        # until /v1/adapters/load installs something into it.
        for _ in range(args.adapter_pool):
            stacks.append(lora_mod.zeros_adapter(cfg))
        params = {
            **params,
            "layers": {
                **params["layers"],
                "lora": lora_mod.stack_adapters(stacks),
            },
        }
        logger.info(
            "multi-LoRA serving: base + %d adapters (%s)%s",
            len(adapter_names), ", ".join(adapter_names) or "-",
            f" + {args.adapter_pool} free pool rows"
            if args.adapter_pool else "",
        )
    if args.quantize == "int8":
        from ditl_tpu.ops.quant import quantize_weights

        params = quantize_weights(params)
        logger.info("quantized weights to int8 (weight-only)")
    generator = Generator(params, cfg, tokenizer, mesh=mesh)
    draft_params = draft_cfg = None
    if args.draft_preset:
        draft_cfg = get_preset(args.draft_preset)
        if draft_cfg.vocab_size != cfg.vocab_size:
            parser.error(
                f"--draft-preset vocab {draft_cfg.vocab_size} must match "
                f"the target's {cfg.vocab_size} (same token space)"
            )
        draft_params = llama.init_params(jax.random.key(1), draft_cfg)
        if args.draft_checkpoint:
            from ditl_tpu.train.checkpoint import CheckpointManager

            ckpt = CheckpointManager(args.draft_checkpoint)
            restored = ckpt.restore_latest_params(
                jax.eval_shape(lambda: draft_params)
            )
            ckpt.close()
            if restored is None:
                parser.error(
                    f"--draft-checkpoint: no checkpoint in "
                    f"{args.draft_checkpoint}"
                )
            draft_params = restored
            logger.info("restored draft params from %s", args.draft_checkpoint)

    def build_engine():
        from ditl_tpu.infer.continuous import ContinuousEngine

        return ContinuousEngine(
            params, cfg, tokenizer, n_slots=args.slots,
            max_cache_len=args.max_cache_len or None,
            prefill_chunk=args.prefill_chunk,
            cache_mode=args.cache_mode,
            page_size=args.page_size,
            n_pages=args.pages or None,
            max_queue=args.max_queue or None,
            mesh=mesh,
            speculative=args.speculative != "off",
            # 'on' forces every greedy tick speculative; 'auto' keeps the
            # measured-acceptance decision (engine default threshold).
            spec_threshold=0.0 if args.speculative == "on" else None,
            logprobs_k=args.logprobs_k,
            fsm_capacity=args.fsm_capacity,
            draft_params=draft_params, draft_cfg=draft_cfg,
            pipeline_ticks=args.pipeline_ticks,
            admission=args.admission,
            token_budget=args.token_budget,
            host_tier_mb=args.host_tier_mb,
            spill_max_pages_per_tick=args.spill_max_pages_per_tick,
            tracer=tracer,
            # Incident plane (ISSUE 10): shared metrics bundle + flight
            # recorder + detector monitor when --incident-dir armed them.
            metrics=serving_metrics,
            flight=flight,
            anomaly=anomaly_monitor,
            usage=usage_meter,
            usage_ledger=usage_ledger,
        )

    if args.pod and jax.process_index() != 0:
        if args.engine == "continuous":
            # Pod-wide continuous batching: every process replays the
            # coordinator's scheduler ticks on an identical engine replica.
            from ditl_tpu.infer.podserve import continuous_worker_loop

            continuous_worker_loop(build_engine())
        else:
            from ditl_tpu.infer.podserve import worker_loop

            worker_loop(generator)  # returns on the shutdown opcode
        return 0
    pod = None
    threaded = None
    if args.engine == "continuous":
        if args.pod:
            from ditl_tpu.infer.podserve import PodContinuousDriver

            threaded = pod = PodContinuousDriver(build_engine())

            class _TokenizerOnly:
                """All device work must ride the tick broadcast: direct
                Generator fallbacks (logprobs) would run a pod-wide SPMD
                program on process 0 alone and hang the pod — absent
                methods turn those requests into clean 400s."""

                def __init__(self, tok):
                    self.tokenizer = tok

            generator = _TokenizerOnly(tokenizer)
        else:
            from ditl_tpu.infer.continuous import ThreadedEngine

            threaded = ThreadedEngine(build_engine())
    elif args.pod:
        from ditl_tpu.infer.podserve import PodGenerator

        generator = pod = PodGenerator(generator)
    spec = None
    if args.speculative != "off" and args.engine == "lockstep":
        # The continuous engine speculates inside its own decode ticks
        # (build_engine above); the lock-step path uses the dedicated
        # speculative generator.
        from ditl_tpu.infer.speculative import (
            AutoSpeculativeGenerator, SpeculativeGenerator,
        )

        if args.speculative == "auto":
            spec = AutoSpeculativeGenerator(
                params, cfg, tokenizer, mesh=mesh, plain=generator
            )
        else:
            spec = SpeculativeGenerator(params, cfg, tokenizer, mesh=mesh)
    server = make_server(
        generator, host=args.host, port=args.port, model_name=cfg.name,
        default_max_tokens=args.max_tokens, threaded_engine=threaded,
        adapter_names=adapter_names, spec_generator=spec,
        max_pending=args.max_pending or None,
        tracer=tracer, telemetry=telemetry_cfg, role=args.role,
        slo=slo, incidents=incidents, serving_metrics=serving_metrics,
        cold_start_s=time.monotonic() - t_serve_start,
        kv_handoff=args.kv_handoff and threaded is not None and pod is None,
        usage=usage_meter,
        usage_ledger=usage_ledger,
    )

    # SIGTERM = graceful drain (the gateway/orchestrator rolling-restart
    # protocol): /health flips to draining so routers stop sending traffic,
    # new work answers 503, in-flight requests finish, then the serve loop
    # exits. close() must not run on the serve_forever thread, so the
    # handler hands it to a helper thread.
    import signal as _signal

    def _on_sigterm(signum, frame):
        logger.info("SIGTERM: draining (in-flight requests will finish)")
        threading.Thread(
            target=server.close, kwargs={"drain": True}, daemon=True
        ).start()

    try:
        _signal.signal(_signal.SIGTERM, _on_sigterm)
    except ValueError:
        pass  # not the main thread (embedded serve()); drain via close()
    logger.info("serving %s (%s) on %s:%d", cfg.name, args.engine, args.host, args.port)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        if pod is not None:
            pod.close()  # broadcast shutdown so workers exit their loop
        server.shutdown()
        if threaded is not None:
            threaded.close()
        if usage_ledger is not None:
            usage_ledger.close()
    return 0


if __name__ == "__main__":
    import sys

    from ditl_tpu.utils.logging import setup_logging

    setup_logging()
    sys.exit(serve())
