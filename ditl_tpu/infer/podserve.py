"""Multi-host (pod) serving: every process runs the same SPMD decode,
process 0 talks HTTP.

The reference's serving story is an external endpoint; its multi-node story
is two hand-launched ranks that never communicate after a startup barrier
(ref ``scripts/run_node0.sh``, ``src/distributed_inference.py:18``). Here a
sharded model spanning several hosts must run its generate program on EVERY
process simultaneously (an XLA SPMD program is a lockstep pod-wide program),
while HTTP naturally arrives at one host. This module bridges the two:

- Process 0 owns the listener. Its request threads hand work to a single
  **pump thread** which, on a fixed cadence, broadcasts one fixed-layout
  header (+ payload when work is pending) to all processes
  (``multihost_utils.broadcast_one_to_all`` — the same collective substrate
  as training).
- Every process (0 included) then calls the *identical*
  ``Generator.generate_tokens`` on the broadcast prompts; GSPMD executes the
  sharded program across the pod. Results are fully replicated, so process 0
  answers HTTP locally and the others discard.
- At ``jax.process_count() == 1`` the broadcasts are identity and this
  degenerates to a slightly-buffered Generator — which is how the protocol
  is unit-tested (tests/test_podserve.py); multi-host execution reuses the
  exact code path.

Protocol (per tick): header ``(8,) int32`` =
``[opcode, batch, prompt_len, max_new, temp_bits, top_p_bits, seed, top_k]``
(floats bit-cast); opcode 0 = idle, 1 = generate (followed by an
``(batch, prompt_len)`` ids broadcast and a ``(batch,)`` lengths broadcast),
2 = shutdown. Fixed layout means every process always issues the same
collective sequence — the SPMD discipline that makes this deadlock-free.
"""

from __future__ import annotations

import queue
import threading

import numpy as np

from ditl_tpu.infer.engine import GenerateConfig, Generator
from ditl_tpu.utils.logging import get_logger

logger = get_logger(__name__)

__all__ = [
    "PodGenerator", "worker_loop",
    "PodContinuousDriver", "continuous_worker_loop",
]

_IDLE, _GENERATE, _SHUTDOWN, _CTICK = 0, 1, 2, 3


def _f2i(x: float) -> int:
    return int(np.float32(x).view(np.int32))


def _i2f(x: int) -> float:
    return float(np.int32(x).view(np.float32))


def _broadcast(arr: np.ndarray) -> np.ndarray:
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.broadcast_one_to_all(arr))


def _statuses_agree(ok: bool) -> bool:
    """Post-tick status collective: all processes exchange an ok/fail byte.

    A one-sided failure (transient device error on one host mid-generate)
    would otherwise leave that process waiting at the next header broadcast
    while the others are still inside the generate program's collectives —
    a silent, permanent desync. Every process calls this after every generate
    tick; the gathered vector is identical pod-wide, so all processes take
    the same shutdown decision when statuses diverge."""
    from jax.experimental import multihost_utils

    statuses = np.asarray(
        multihost_utils.process_allgather(np.asarray([1 if ok else 0], np.int32))
    ).reshape(-1)
    return bool(statuses.min() == statuses.max())


def _status_fingerprints_agree(ok: bool, fingerprint: int) -> bool:
    """Continuous-tick status collective carrying the engine's scheduler
    FINGERPRINT (ContinuousEngine.scheduler_fingerprint) alongside the
    ok/fail byte: replicas whose page allocators or slot schedules diverge
    — even while every tick 'succeeds' locally — produce different
    digests, and the whole pod shuts down loudly instead of silently
    gathering different pages inside the same SPMD program."""
    from jax.experimental import multihost_utils

    statuses = np.asarray(multihost_utils.process_allgather(
        np.asarray([1 if ok else 0, fingerprint], np.int64)
    )).reshape(-1, 2)
    return bool((statuses.min(axis=0) == statuses.max(axis=0)).all())


class _Job:
    def __init__(self, token_lists, gen):
        self.token_lists = token_lists
        self.gen = gen
        self.done = threading.Event()
        self.result = None
        self.error: BaseException | None = None


def _run_tick(
    generator: Generator,
    header: np.ndarray,
    ids: np.ndarray | None,
    lengths: np.ndarray | None,
):
    """Execute one broadcast generate tick — identical on every process."""
    _, batch, _, max_new, temp_bits, top_p_bits, seed, top_k = (
        int(v) for v in header
    )
    token_lists = [ids[i, : lengths[i]].tolist() for i in range(batch)]
    gen = GenerateConfig(
        max_new_tokens=max_new,
        temperature=_i2f(temp_bits),
        top_k=top_k,
        top_p=_i2f(top_p_bits),
        seed=seed,
    )
    return generator.generate_tokens(token_lists, gen)


class PodGenerator:
    """Process-0 front: queues HTTP requests and pumps them through the
    pod-wide broadcast protocol. Exposes the ``Generator`` surface the HTTP
    handler uses (``generate``/``generate_tokens``/``tokenizer``)."""

    def __init__(self, generator: Generator, *, poll_s: float = 0.05):
        self.generator = generator
        self.tokenizer = generator.tokenizer
        self.poll_s = poll_s
        self._jobs: queue.Queue[_Job] = queue.Queue()
        self._stop = False
        # Guards the (_stop check, enqueue) pair in generate_tokens against
        # close(): without it a job could slip in after the pump drained the
        # queue and block its waiter forever.
        self._submit_lock = threading.Lock()
        self._pump = threading.Thread(target=self._pump_loop, daemon=True)
        self._pump.start()

    # -- pump (the only thread issuing collectives on process 0) -------------

    def _pump_loop(self) -> None:
        while True:
            try:
                job = self._jobs.get(timeout=self.poll_s)
            except queue.Empty:
                job = None
            if self._stop:
                _broadcast(np.asarray([_SHUTDOWN, 0, 0, 0, 0, 0, 0, 0], np.int32))
                # Fail every queued waiter — leaving any job un-signalled
                # would deadlock its HTTP thread in done.wait(). The submit
                # lock guarantees nothing is enqueued after this drain.
                with self._submit_lock:
                    pending = [job] if job is not None else []
                    while True:
                        try:
                            pending.append(self._jobs.get_nowait())
                        except queue.Empty:
                            break
                    for j in pending:
                        j.error = RuntimeError("pod serving stopped")
                        j.done.set()
                return
            if job is None:
                _broadcast(np.asarray([_IDLE, 0, 0, 0, 0, 0, 0, 0], np.int32))
                continue
            try:
                gen = job.gen
                batch = len(job.token_lists)
                plen = max(1, max(len(t) for t in job.token_lists))
                ids = np.zeros((batch, plen), np.int32)
                lengths = np.zeros((batch,), np.int32)
                for i, toks in enumerate(job.token_lists):
                    ids[i, : len(toks)] = toks
                    lengths[i] = len(toks)
                header = np.asarray(
                    [
                        _GENERATE, batch, plen, gen.max_new_tokens,
                        _f2i(gen.temperature), _f2i(gen.top_p), gen.seed,
                        gen.top_k,
                    ],
                    np.int32,
                )
            except BaseException as e:  # noqa: BLE001 — handed to the waiter
                # Packing failed BEFORE anything was broadcast: the pod never
                # saw this tick, so fail the one job and keep serving.
                job.error = e
                job.done.set()
                continue
            try:
                _broadcast(header)
                ids = _broadcast(ids)
                lengths = _broadcast(lengths)
            except BaseException as e:  # noqa: BLE001
                # A failure mid-broadcast is FATAL: workers that received the
                # header are already inside the ids broadcast / post-tick
                # allgather, so continuing to the next job would misalign the
                # pod's collective sequence and hang everyone (ADVICE r2) —
                # same shutdown path as a status divergence.
                job.error = e
                job.done.set()
                logger.exception(
                    "pod broadcast failed mid-tick; stopping pod serving "
                    "(collective sequence can no longer be trusted)"
                )
                with self._submit_lock:
                    self._stop = True
                    while True:
                        try:
                            j = self._jobs.get_nowait()
                        except queue.Empty:
                            break
                        j.error = RuntimeError(
                            "pod serving stopped (broadcast failure)"
                        )
                        j.done.set()
                return
            ok = True
            try:
                job.result = _run_tick(self.generator, header, ids, lengths)
            except BaseException as e:  # noqa: BLE001 — handed to the waiter
                job.error = e
                ok = False
            if not _statuses_agree(ok):
                # One-sided failure: the pod can no longer be assumed in
                # lockstep. Workers saw the same divergent vector and are
                # exiting their loops, so do NOT broadcast further (a
                # collective with absent participants hangs) — fail local
                # waiters and stop serving.
                job.error = job.error or RuntimeError(
                    "pod tick status diverged across processes"
                )
                job.done.set()
                logger.error(
                    "pod tick status diverged across processes; stopping pod "
                    "serving (workers have shut down)"
                )
                with self._submit_lock:
                    self._stop = True
                    while True:
                        try:
                            j = self._jobs.get_nowait()
                        except queue.Empty:
                            break
                        j.error = RuntimeError("pod serving stopped (desync)")
                        j.done.set()
                return
            job.done.set()

    # -- Generator surface ----------------------------------------------------

    def generate_tokens(
        self,
        token_lists: list[list[int]],
        gen: GenerateConfig | None = None,
        adapter_ids=None,
    ) -> list[list[int]]:
        if adapter_ids is not None:
            raise ValueError(
                "multi-LoRA adapter selection is not carried by the pod "
                "broadcast protocol; serve adapters without --pod"
            )
        if not token_lists:
            return []
        gen = gen or GenerateConfig()
        token_lists = [t if t else [self.tokenizer.bos_id] for t in token_lists]
        job = _Job(token_lists, gen)
        with self._submit_lock:
            if self._stop:
                raise RuntimeError("pod serving stopped")
            self._jobs.put(job)
        job.done.wait()
        if job.error is not None:
            raise job.error
        return job.result

    def generate(
        self,
        prompts: list[str],
        gen: GenerateConfig | None = None,
        adapter_ids=None,
    ) -> list[str]:
        encoded = [
            [self.tokenizer.bos_id] + self.tokenizer.encode(p) for p in prompts
        ]
        return [
            self.tokenizer.decode(t)
            for t in self.generate_tokens(encoded, gen, adapter_ids)
        ]

    def close(self) -> None:
        """Broadcast shutdown to the pod and stop the pump. Waits long enough
        for an in-flight generate (first-request compiles routinely exceed
        10s) to drain — exiting before the shutdown opcode goes out would
        strand every worker in its blocking broadcast."""
        self._stop = True
        self._pump.join(timeout=600)
        if self._pump.is_alive():
            logger.error(
                "pod pump did not drain within 600s; workers may be left "
                "blocked in their broadcast loop"
            )


def worker_loop(generator: Generator) -> None:
    """Run on every process with ``jax.process_index() != 0``: mirror process
    0's collective sequence forever, executing each generate tick, until a
    shutdown opcode arrives. Results are replicated; non-zero processes
    simply drop them."""
    logger.info("pod serve worker: entering broadcast loop")
    while True:
        header = _broadcast(np.zeros((8,), np.int32))
        op = int(header[0])
        if op == _SHUTDOWN:
            logger.info("pod serve worker: shutdown")
            return
        if op == _IDLE:
            continue
        batch, plen = int(header[1]), int(header[2])
        ids = _broadcast(np.zeros((batch, plen), np.int32))
        lengths = _broadcast(np.zeros((batch,), np.int32))
        ok = True
        try:
            _run_tick(generator, header, ids, lengths)
        except Exception:
            # Deterministic per-request errors (validation, OOM-at-shape)
            # raise identically on every process; the status collective below
            # confirms that before continuing. A worker that died here
            # instead would strand the whole pod at the next broadcast.
            ok = False
            logger.exception("pod serve worker: tick failed")
        if not _statuses_agree(ok):
            # One-sided failure — the pod is desynced; every process saw the
            # same divergent status vector, so all exit together.
            logger.error(
                "pod serve worker: tick status diverged across processes; "
                "shutting down"
            )
            return


# ---------------------------------------------------------------------------
# Pod-wide continuous batching
# ---------------------------------------------------------------------------
#
# The lock-step PodGenerator broadcasts whole generate calls; a continuous
# engine instead needs every process to run the SAME scheduler ticks on the
# same state. The protocol broadcasts scheduler INPUTS (submits + cancels)
# once per tick; each process applies them to its own ContinuousEngine
# replica (deterministic: same seeds, same FIFO order, same slot math) and
# calls engine.step() — the tick's prefill/decode programs are then
# pod-wide SPMD programs over the engine's mesh. Results are replicated;
# process 0 answers HTTP.
#
# CTICK payload: header [_CTICK, n_submits, ids_total, n_cancels, 0...];
# then meta (n_submits, 5) int32 = [prompt_len, max_new, temp_bits,
# top_p_bits, seed]; ids (ids_total,) int32 (prompts concatenated);
# cancels (n_cancels,) int32 (req ids). A post-tick status collective
# (_statuses_agree) detects one-sided failures exactly as in lock-step
# pod serving.


def _apply_ctick(engine, meta: np.ndarray, ids: np.ndarray, cancels: np.ndarray,
                 streams: list | None = None, traces: list | None = None):
    """Apply one broadcast tick's scheduler inputs, then run one tick.
    Returns the submitted request ids (identical on every process).
    ``streams`` (process 0 only) attaches per-request stream queues at
    submit time — before the tick's step, so first-tick chunks are not
    lost; worker replicas stream to nowhere. ``traces`` (process 0 only,
    same shape) attaches upstream span contexts: tracing is host-side
    bookkeeping like streams, never broadcast, so worker replicas simply
    record no spans — scheduler state stays identical pod-wide."""
    from ditl_tpu.infer.continuous import QueueFullError

    rids = []
    off = 0
    for i, row in enumerate(meta):
        plen, max_new, temp_bits, top_p_bits, seed, adapter = (
            int(v) for v in row
        )
        prompt = ids[off: off + plen].tolist()
        off += plen
        try:
            rids.append(engine.submit(
                prompt, max_new_tokens=max_new, temperature=_i2f(temp_bits),
                top_p=_i2f(top_p_bits), seed=seed,
                stream=streams[i] if streams is not None else None,
                adapter_id=adapter or None,
                trace=traces[i] if traces is not None else None,
            ))
        except (ValueError, QueueFullError) as e:
            # Deterministic per-request rejection: the same submit fails
            # identically on every process (same engine state), so the pod
            # stays in lockstep while only this request errors.
            rids.append(e)
    for rid in cancels:
        engine.cancel(int(rid))
    engine.step()
    return rids


class PodContinuousDriver:
    """Process-0 driver for pod-wide continuous batching. Exposes the
    ``ThreadedEngine`` surface the HTTP server uses (``generate_one``,
    ``stream_one``, ``cancel``, ``queue_full``, ``close``) while pumping
    scheduler inputs through the pod broadcast so every process ticks the
    same engine state. At ``process_count == 1`` the broadcasts are
    identity and this degenerates to a broadcast-framed ThreadedEngine —
    how the protocol is unit-tested."""

    def __init__(self, engine, *, poll_s: float = 0.02):
        self._engine = engine
        # Per-host wall-clock calibration would desync pod tick decisions.
        engine.freeze_spec_threshold()
        self.tokenizer = engine.tokenizer
        self.poll_s = poll_s
        self._lock = threading.Lock()
        self._staged: list[tuple] = []  # (prompt, max_new, temp, top_p, seed, adapter, ticket)
        self._cancels: set[int] = set()
        self._tickets: dict[int, "_Ticket"] = {}
        self._inflight = 0  # batch swapped out of _staged, not yet submitted
        self._workers_down = False  # divergence detected: never broadcast again
        self._seq = 0  # monotonic default-seed counter (never reset)
        self._stop = False
        self._error: BaseException | None = None
        self._cond = threading.Condition(self._lock)
        self._pump = threading.Thread(target=self._pump_loop, daemon=True)
        self._pump.start()

    def stats(self) -> dict:
        eng_stats = self._engine.stats()
        eng_stats["pod"] = True
        eng_stats["staged"] = len(self._staged)
        return eng_stats

    @property
    def metrics(self):
        """Coordinator-replica telemetry (telemetry/serving.py) for the
        /metrics route — every replica ticks identical scheduler state, so
        process 0's counters ARE the pod's."""
        return self._engine.metrics

    @property
    def queue_full(self) -> bool:
        # Lock-free on purpose: _stage calls this while holding _cond (the
        # same non-reentrant lock), and the check is best-effort anyway —
        # len() reads of a deque/list are atomic under the GIL.
        eng = self._engine
        if eng.max_queue is None:
            return False
        return (len(eng._queue) + len(self._staged) + self._inflight
                >= eng.max_queue)

    def _pump_loop(self) -> None:
        import time as _time

        while True:
            with self._cond:
                while (not self._stop and not self._staged and not self._cancels
                       and self._engine.pending == 0):
                    self._cond.wait(timeout=self.poll_s)
                if self._stop:
                    staged, self._staged = self._staged, []
                    break
                staged, self._staged = self._staged, []
                cancels, self._cancels = self._cancels, set()
                self._inflight = len(staged)
            try:
                self._tick(staged, sorted(cancels))
            except BaseException as e:  # noqa: BLE001
                logger.exception("pod continuous driver died")
                if not self._workers_down:
                    # Wake workers parked in their header broadcast so they
                    # exit instead of hanging forever. Skipped after a
                    # status divergence (the workers already shut down — a
                    # collective with absent participants would hang US).
                    try:
                        _broadcast(np.asarray(
                            [_SHUTDOWN, 0, 0, 0, 0, 0, 0, 0], np.int32
                        ))
                    except Exception:
                        logger.exception("shutdown broadcast failed")
                with self._cond:
                    self._error = e
                    self._stop = True
                    # Fail EVERY outstanding waiter: registered tickets,
                    # the in-flight batch (whose tickets may not have been
                    # registered yet), and anything staged during the tick
                    # — an unset ticket event is a permanently hung HTTP
                    # connection.
                    for t in self._tickets.values():
                        t.fail(e)
                    self._tickets.clear()
                    for (*_, t) in staged:
                        t.fail(e)
                    for (*_, t) in self._staged:
                        t.fail(e)
                    self._staged.clear()
                    self._cond.notify_all()
                return
        # shutdown: one final broadcast releases the workers
        _broadcast(np.asarray([_SHUTDOWN, 0, 0, 0, 0, 0, 0, 0], np.int32))
        with self._cond:
            err = RuntimeError("pod serving stopped")
            for t in self._tickets.values():
                t.fail(err)
            for (*_, ticket) in staged:
                ticket.fail(err)
            self._tickets.clear()
            self._cond.notify_all()

    def _tick(self, staged, cancels) -> None:
        try:
            metas, all_ids = [], []
            for (prompt, max_new, temp, top_p, seed, adapter, _t) in staged:
                metas.append([
                    len(prompt), max_new, _f2i(temp), _f2i(top_p), seed,
                    adapter,
                ])
                all_ids.extend(prompt)
            meta = np.asarray(metas, np.int32).reshape(len(staged), 6)
            ids = np.asarray(all_ids, np.int32)
            cc = np.asarray(cancels, np.int32)
        except Exception as e:
            # Packing failed before anything was broadcast: fail this batch
            # only — the pod never saw the tick, so serving continues.
            with self._cond:
                self._inflight = 0
                for (*_, ticket) in staged:
                    ticket.fail(e)
            return
        header = np.asarray(
            [_CTICK, len(staged), len(all_ids), len(cc), 0, 0, 0, 0], np.int32
        )
        _broadcast(header)
        if len(staged):
            _broadcast(meta)
            _broadcast(ids)
        if len(cc):
            _broadcast(cc)
        ok = True
        rids = []
        try:
            rids = _apply_ctick(
                self._engine, meta, ids, cc,
                streams=[t.stream for (*_, t) in staged],
                traces=[t.trace for (*_, t) in staged],
            )
        except Exception as e:  # noqa: BLE001 — surfaced via tickets
            ok = False
            err = e
        if not _status_fingerprints_agree(
            ok, self._engine.scheduler_fingerprint() if ok else 0
        ):
            self._workers_down = True
            raise RuntimeError(
                "pod tick status/scheduler-state diverged across processes "
                "(workers have shut down)"
            )
        with self._cond:
            self._inflight = 0
            if not ok:
                for (*_, ticket) in staged:
                    ticket.fail(err)
                return
            for (*_, ticket), rid in zip(staged, rids):
                if isinstance(rid, BaseException):
                    ticket.fail(rid)  # deterministic per-request rejection
                    continue
                ticket.req_id = rid
                if ticket.abandoned:
                    # generate_many failed mid-stage after this copy was
                    # staged: cancel on the next tick, never register.
                    self._cancels.add(rid)
                    continue
                self._tickets[rid] = ticket
            for req in self._engine.take_finished():
                t = self._tickets.pop(req.req_id, None)
                if t is not None:
                    t.finish(req.tokens)
            self._cond.notify_all()

    # -- ThreadedEngine surface ----------------------------------------------

    def _stage(self, prompt_tokens, max_new_tokens, temperature, top_p, seed,
               stream=None, adapter_id=None, grammar=None,
               trace=None) -> "_Ticket":
        from ditl_tpu.infer.continuous import BadRequestError, QueueFullError

        if grammar is not None:
            # The server CLI already refuses --fsm-capacity with --pod, so a
            # guided request can only reach here via a direct driver call;
            # ValueError (not TypeError) means request validation — the
            # server's completion handlers map it to HTTP 400.
            raise BadRequestError(
                "guided decoding does not compose with --pod serving (the "
                "tick broadcast does not carry grammar registrations)"
            )
        gen = self._engine.gen
        ticket = _Ticket(stream, trace)
        prompt = list(prompt_tokens) or [self.tokenizer.bos_id]
        max_new = (max_new_tokens if max_new_tokens is not None
                   else gen.max_new_tokens)
        # Validate on the HTTP thread: a bad request must fail HERE, not
        # inside the broadcast tick it would share with innocent requests.
        self._engine.validate_request(prompt, max_new)
        if seed is not None and not (-2**31 <= int(seed) < 2**31):
            raise BadRequestError("seed must fit in int32")
        if not (0 < max_new < 2**31):
            raise BadRequestError("max_tokens out of range")
        adapter = int(adapter_id or 0)
        if adapter and not (
            self._engine.multi_lora
            and 0 <= adapter < self._engine.n_adapters
        ):
            raise BadRequestError(
                f"adapter_id {adapter} invalid for this engine"
            )
        with self._cond:
            if self._stop:
                raise RuntimeError("pod serving stopped") from self._error
            if self.queue_full:
                # The driver-level rejection bypasses engine.submit (the
                # other queue_full.inc site) — count it here or pod-mode
                # overload would read 0 on the 429-rate alert the
                # troubleshooting doc tells operators to build.
                self._engine.metrics.queue_full.inc()
                raise QueueFullError("admission queue full (pod)")
            self._staged.append((
                prompt,
                max_new,
                gen.temperature if temperature is None else float(temperature),
                gen.top_p if top_p is None else float(top_p),
                int(seed) if seed is not None else
                # Driver-level monotonic counter: unlike engine._next_id +
                # len(staged) (which races with an in-flight tick swapping
                # the staged list), _seq only moves forward, so concurrent
                # default-seeded requests never collide.
                self._engine._base_seed + self._seq,
                adapter,
                ticket,
            ))
            self._seq += 1
            self._cond.notify_all()
        return ticket

    @property
    def multi_lora(self) -> bool:
        return self._engine.multi_lora

    # The server consults this for HEADER-derived deadlines (the gateway
    # stamps every relay with its remaining budget): a best-effort hint is
    # dropped rather than 400-ing every gateway-routed request. An explicit
    # client `deadline_s` payload still goes through _reject_deadline.
    supports_deadlines = False
    # Same stance for SLO classes (ISSUE 8): queue order is replicated
    # scheduler state, and staging does not broadcast a class lane, so a
    # non-default class on one process would desync admission order pod-
    # wide. Header-derived hints are dropped by the server; explicit
    # payload values go through _reject_slo_class.
    supports_slo_classes = False

    @staticmethod
    def _reject_slo_class(slo_class) -> None:
        """Pod serving carries no SLO classes: the tick broadcast stages
        requests FIFO and every replica must sort its queue identically.
        Reject-don't-drop for explicit client values."""
        if slo_class is not None and slo_class != "interactive":
            from ditl_tpu.infer.continuous import BadRequestError

            raise BadRequestError(
                "slo_class does not compose with --pod serving (the tick "
                "broadcast stages requests FIFO; a per-process priority "
                "queue would desync the replicated scheduler)"
            )

    @staticmethod
    def _reject_deadline(deadline_s) -> None:
        """Pod serving carries no deadlines: the tick broadcast replicates
        the scheduler on every process, and per-process wall-clock expiry
        sweeps would desync the replicas (divergent slot tables -> SPMD
        fingerprint shutdown). Reject-don't-drop, so a client's deadline is
        never silently ignored."""
        if deadline_s is not None:
            from ditl_tpu.infer.continuous import BadRequestError

            raise BadRequestError(
                "deadline_s does not compose with --pod serving (the tick "
                "broadcast carries no deadlines; per-process clocks would "
                "desync the replicated scheduler)"
            )

    @property
    def tracer(self):
        """Process-0 engine's tracer — make_server derives the HTTP span
        layer from it, same as solo serving."""
        return self._engine.tracer

    def generate_one(self, prompt_tokens, *, max_new_tokens=None,
                     temperature=None, top_p=None, seed=None,
                     adapter_id=None, grammar=None,
                     deadline_s=None, slo_class=None, trace=None,
                     tenant=None) -> list[int]:
        # ``tenant`` (ISSUE 15) is accepted-and-dropped: the tick
        # broadcast carries no tenant lane, so pod usage rows attribute
        # to "anonymous" — the same reduced-feature stance as deadlines
        # and SLO classes (metering per tenant wants solo replicas
        # behind the gateway).
        self._reject_deadline(deadline_s)
        self._reject_slo_class(slo_class)
        ticket = self._stage(prompt_tokens, max_new_tokens, temperature,
                             top_p, seed, adapter_id=adapter_id,
                             grammar=grammar, trace=trace)
        return ticket.wait()

    def generate_many(self, prompt_tokens, n, *, max_new_tokens=None,
                      temperature=None, top_p=None, seed=None,
                      adapter_id=None, grammar=None, logprobs=None,
                      slo_class=None, trace=None, tenant=None):
        """OpenAI ``n``/``best_of`` over the pod: stage ``n`` copies with
        derived seeds (same 7919-stride rule as ThreadedEngine.generate_many
        so pod and solo serving replay identically for a given seed), then
        block until all finish. Returns objects with ``.tokens`` and
        ``.lp_token`` — the server's candidate surface."""
        self._reject_slo_class(slo_class)
        if logprobs is not None:
            from ditl_tpu.infer.continuous import BadRequestError

            raise BadRequestError(
                "logprobs do not compose with --pod serving (the tick "
                "broadcast carries token ids only)"
            )
        if seed is None:
            import random as _random

            seed = _random.getrandbits(31)
        tickets: list[_Ticket] = []

        def _abandon_siblings():
            # A failure on copy k must not leave siblings decoding dead
            # budget pod-wide. Still-staged copies are pulled out of
            # self._staged entirely (never broadcast); in-flight ones are
            # flagged so the pump cancels instead of registering them;
            # admitted ones get a real cancel tick.
            with self._cond:
                live = set(id(t) for t in tickets)
                self._staged = [
                    entry for entry in self._staged
                    if id(entry[-1]) not in live
                ]
                for t in tickets:
                    t.abandoned = True
                    if t.req_id is not None and not t.done.is_set():
                        self._cancels.add(t.req_id)
                        self._tickets.pop(t.req_id, None)
                self._cond.notify_all()

        try:
            from ditl_tpu.infer.continuous import derive_copy_seed

            for i in range(n):
                tickets.append(self._stage(
                    prompt_tokens, max_new_tokens, temperature, top_p,
                    derive_copy_seed(seed, i),
                    adapter_id=adapter_id, grammar=grammar, trace=trace,
                ))
            return [_PodResult(t.wait()) for t in tickets]
        except BaseException:
            _abandon_siblings()
            raise

    def stream_one(self, prompt_tokens, *, max_new_tokens=None,
                   temperature=None, top_p=None, seed=None, adapter_id=None,
                   grammar=None, deadline_s=None, slo_class=None, trace=None,
                   tenant=None):
        import queue as _queue

        self._reject_deadline(deadline_s)
        self._reject_slo_class(slo_class)
        stream: _queue.Queue = _queue.Queue()
        # Staged EAGERLY (not on first next()): QueueFullError must raise
        # while the HTTP layer can still answer 429 — after the SSE headers
        # there is no status left to send (ADVICE r2).
        ticket = self._stage(prompt_tokens, max_new_tokens, temperature,
                             top_p, seed, stream=stream,
                             adapter_id=adapter_id, grammar=grammar,
                             trace=trace)

        def chunks():
            try:
                while True:
                    try:
                        chunk = stream.get(timeout=1.0)
                    except _queue.Empty:
                        if self._stop:
                            raise RuntimeError(
                                "pod serving stopped mid-stream"
                            ) from self._error
                        continue
                    if chunk is None:
                        if ticket.error is not None:
                            # fail() uses the same end-of-stream sentinel; a
                            # driver error must not present a truncated
                            # stream as a clean completion.
                            raise RuntimeError(
                                "pod serving stopped mid-stream"
                            ) from ticket.error
                        # The engine enqueues the sentinel inside the tick;
                        # the pump marks the ticket finished moments later
                        # (take_finished). Wait for that so the finally
                        # clause below doesn't broadcast a spurious pod-wide
                        # cancel tick for a cleanly finished request
                        # (ADVICE r2).
                        ticket.done.wait(timeout=2.0)
                        return
                    yield chunk
            finally:
                # Cancel only abandoned/failed streams: a cleanly finished
                # request was already removed by take_finished, and a dead
                # cancel would cost one pointless pod-wide broadcast tick.
                if ticket.req_id is not None and not ticket.done.is_set():
                    self.cancel(ticket.req_id)

        return chunks()

    def cancel(self, req_id: int) -> None:
        with self._cond:
            self._cancels.add(req_id)
            self._cond.notify_all()

    def close(self) -> None:
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        self._pump.join(timeout=600)
        if self._pump.is_alive():
            logger.error("pod continuous pump did not drain within 600s")


class _PodResult:
    """Finished-candidate surface for ``generate_many`` (the server reads
    ``.tokens`` and ``.lp_token``; the tick broadcast carries no logprobs,
    so ``lp_token`` is always None in pod mode)."""

    __slots__ = ("tokens", "lp_token")

    def __init__(self, tokens: list[int]):
        self.tokens = tokens
        self.lp_token = None


class _Ticket:
    """One staged request's handoff between an HTTP thread and the pump."""

    def __init__(self, stream=None, trace=None):
        self.stream = stream
        self.trace = trace  # upstream span context (process-0 spans only)
        self.req_id: int | None = None
        self.result: list[int] | None = None
        self.error: BaseException | None = None
        self.abandoned = False  # generate_many sibling failed mid-stage
        self.done = threading.Event()

    def finish(self, tokens: list[int]) -> None:
        self.result = tokens
        self.done.set()

    def fail(self, err: BaseException) -> None:
        self.error = err
        self.done.set()
        if self.stream is not None:
            self.stream.put(None)

    def wait(self) -> list[int]:
        self.done.wait()
        if self.error is not None:
            raise self.error
        return self.result


def continuous_worker_loop(engine) -> str:
    """Run on every ``jax.process_index() != 0`` process under
    ``--pod --engine continuous``: mirror the coordinator's tick broadcasts
    on an identical engine replica until shutdown. Returns the exit reason
    (``"shutdown"`` | ``"desync"`` | ``"bad-opcode"``) so launchers and the
    multi-process drill can tell a clean teardown from a loud divergence
    halt."""
    engine.freeze_spec_threshold()  # same reason as PodContinuousDriver
    logger.info("pod continuous worker: entering broadcast loop")
    while True:
        header = _broadcast(np.zeros((8,), np.int32))
        op = int(header[0])
        if op == _SHUTDOWN:
            logger.info("pod continuous worker: shutdown")
            return "shutdown"
        if op != _CTICK:
            logger.error("pod continuous worker: unexpected opcode %d", op)
            return "bad-opcode"
        n_sub, ids_total, n_cancel = int(header[1]), int(header[2]), int(header[3])
        meta = (_broadcast(np.zeros((n_sub, 6), np.int32))
                if n_sub else np.zeros((0, 6), np.int32))
        ids = (_broadcast(np.zeros((ids_total,), np.int32))
               if n_sub else np.zeros((0,), np.int32))
        cc = (_broadcast(np.zeros((n_cancel,), np.int32))
              if n_cancel else np.zeros((0,), np.int32))
        ok = True
        try:
            _apply_ctick(engine, meta, ids, cc)
            engine.take_finished()  # drop replicated results
        except Exception:
            ok = False
            logger.exception("pod continuous worker: tick failed")
        if not _status_fingerprints_agree(
            ok, engine.scheduler_fingerprint() if ok else 0
        ):
            logger.error(
                "pod continuous worker: tick status/scheduler-state "
                "diverged; shutting down"
            )
            return "desync"
