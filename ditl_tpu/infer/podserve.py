"""Multi-host (pod) serving: every process runs the same SPMD decode,
process 0 talks HTTP.

The reference's serving story is an external endpoint; its multi-node story
is two hand-launched ranks that never communicate after a startup barrier
(ref ``scripts/run_node0.sh``, ``src/distributed_inference.py:18``). Here a
sharded model spanning several hosts must run its generate program on EVERY
process simultaneously (an XLA SPMD program is a lockstep pod-wide program),
while HTTP naturally arrives at one host. This module bridges the two:

- Process 0 owns the listener. Its request threads hand work to a single
  **pump thread** which, on a fixed cadence, broadcasts one fixed-layout
  header (+ payload when work is pending) to all processes
  (``multihost_utils.broadcast_one_to_all`` — the same collective substrate
  as training).
- Every process (0 included) then calls the *identical*
  ``Generator.generate_tokens`` on the broadcast prompts; GSPMD executes the
  sharded program across the pod. Results are fully replicated, so process 0
  answers HTTP locally and the others discard.
- At ``jax.process_count() == 1`` the broadcasts are identity and this
  degenerates to a slightly-buffered Generator — which is how the protocol
  is unit-tested (tests/test_podserve.py); multi-host execution reuses the
  exact code path.

Protocol (per tick): header ``(8,) int32`` =
``[opcode, batch, prompt_len, max_new, temp_bits, top_p_bits, seed, top_k]``
(floats bit-cast); opcode 0 = idle, 1 = generate (followed by an
``(batch, prompt_len)`` ids broadcast and a ``(batch,)`` lengths broadcast),
2 = shutdown. Fixed layout means every process always issues the same
collective sequence — the SPMD discipline that makes this deadlock-free.
"""

from __future__ import annotations

import queue
import threading

import numpy as np

from ditl_tpu.infer.engine import GenerateConfig, Generator
from ditl_tpu.utils.logging import get_logger

logger = get_logger(__name__)

__all__ = ["PodGenerator", "worker_loop"]

_IDLE, _GENERATE, _SHUTDOWN = 0, 1, 2


def _f2i(x: float) -> int:
    return int(np.float32(x).view(np.int32))


def _i2f(x: int) -> float:
    return float(np.int32(x).view(np.float32))


def _broadcast(arr: np.ndarray) -> np.ndarray:
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.broadcast_one_to_all(arr))


def _statuses_agree(ok: bool) -> bool:
    """Post-tick status collective: all processes exchange an ok/fail byte.

    A one-sided failure (transient device error on one host mid-generate)
    would otherwise leave that process waiting at the next header broadcast
    while the others are still inside the generate program's collectives —
    a silent, permanent desync. Every process calls this after every generate
    tick; the gathered vector is identical pod-wide, so all processes take
    the same shutdown decision when statuses diverge."""
    from jax.experimental import multihost_utils

    statuses = np.asarray(
        multihost_utils.process_allgather(np.asarray([1 if ok else 0], np.int32))
    ).reshape(-1)
    return bool(statuses.min() == statuses.max())


class _Job:
    def __init__(self, token_lists, gen):
        self.token_lists = token_lists
        self.gen = gen
        self.done = threading.Event()
        self.result = None
        self.error: BaseException | None = None


def _run_tick(
    generator: Generator,
    header: np.ndarray,
    ids: np.ndarray | None,
    lengths: np.ndarray | None,
):
    """Execute one broadcast generate tick — identical on every process."""
    _, batch, _, max_new, temp_bits, top_p_bits, seed, top_k = (
        int(v) for v in header
    )
    token_lists = [ids[i, : lengths[i]].tolist() for i in range(batch)]
    gen = GenerateConfig(
        max_new_tokens=max_new,
        temperature=_i2f(temp_bits),
        top_k=top_k,
        top_p=_i2f(top_p_bits),
        seed=seed,
    )
    return generator.generate_tokens(token_lists, gen)


class PodGenerator:
    """Process-0 front: queues HTTP requests and pumps them through the
    pod-wide broadcast protocol. Exposes the ``Generator`` surface the HTTP
    handler uses (``generate``/``generate_tokens``/``tokenizer``)."""

    def __init__(self, generator: Generator, *, poll_s: float = 0.05):
        self.generator = generator
        self.tokenizer = generator.tokenizer
        self.poll_s = poll_s
        self._jobs: queue.Queue[_Job] = queue.Queue()
        self._stop = False
        # Guards the (_stop check, enqueue) pair in generate_tokens against
        # close(): without it a job could slip in after the pump drained the
        # queue and block its waiter forever.
        self._submit_lock = threading.Lock()
        self._pump = threading.Thread(target=self._pump_loop, daemon=True)
        self._pump.start()

    # -- pump (the only thread issuing collectives on process 0) -------------

    def _pump_loop(self) -> None:
        while True:
            try:
                job = self._jobs.get(timeout=self.poll_s)
            except queue.Empty:
                job = None
            if self._stop:
                _broadcast(np.asarray([_SHUTDOWN, 0, 0, 0, 0, 0, 0, 0], np.int32))
                # Fail every queued waiter — leaving any job un-signalled
                # would deadlock its HTTP thread in done.wait(). The submit
                # lock guarantees nothing is enqueued after this drain.
                with self._submit_lock:
                    pending = [job] if job is not None else []
                    while True:
                        try:
                            pending.append(self._jobs.get_nowait())
                        except queue.Empty:
                            break
                    for j in pending:
                        j.error = RuntimeError("pod serving stopped")
                        j.done.set()
                return
            if job is None:
                _broadcast(np.asarray([_IDLE, 0, 0, 0, 0, 0, 0, 0], np.int32))
                continue
            try:
                gen = job.gen
                batch = len(job.token_lists)
                plen = max(1, max(len(t) for t in job.token_lists))
                ids = np.zeros((batch, plen), np.int32)
                lengths = np.zeros((batch,), np.int32)
                for i, toks in enumerate(job.token_lists):
                    ids[i, : len(toks)] = toks
                    lengths[i] = len(toks)
                header = np.asarray(
                    [
                        _GENERATE, batch, plen, gen.max_new_tokens,
                        _f2i(gen.temperature), _f2i(gen.top_p), gen.seed,
                        gen.top_k,
                    ],
                    np.int32,
                )
                _broadcast(header)
                ids = _broadcast(ids)
                lengths = _broadcast(lengths)
            except BaseException as e:  # noqa: BLE001 — handed to the waiter
                job.error = e
                job.done.set()
                continue
            ok = True
            try:
                job.result = _run_tick(self.generator, header, ids, lengths)
            except BaseException as e:  # noqa: BLE001 — handed to the waiter
                job.error = e
                ok = False
            if not _statuses_agree(ok):
                # One-sided failure: the pod can no longer be assumed in
                # lockstep. Workers saw the same divergent vector and are
                # exiting their loops, so do NOT broadcast further (a
                # collective with absent participants hangs) — fail local
                # waiters and stop serving.
                job.error = job.error or RuntimeError(
                    "pod tick status diverged across processes"
                )
                job.done.set()
                logger.error(
                    "pod tick status diverged across processes; stopping pod "
                    "serving (workers have shut down)"
                )
                with self._submit_lock:
                    self._stop = True
                    while True:
                        try:
                            j = self._jobs.get_nowait()
                        except queue.Empty:
                            break
                        j.error = RuntimeError("pod serving stopped (desync)")
                        j.done.set()
                return
            job.done.set()

    # -- Generator surface ----------------------------------------------------

    def generate_tokens(
        self,
        token_lists: list[list[int]],
        gen: GenerateConfig | None = None,
        adapter_ids=None,
    ) -> list[list[int]]:
        if adapter_ids is not None:
            raise ValueError(
                "multi-LoRA adapter selection is not carried by the pod "
                "broadcast protocol; serve adapters without --pod"
            )
        if not token_lists:
            return []
        gen = gen or GenerateConfig()
        token_lists = [t if t else [self.tokenizer.bos_id] for t in token_lists]
        job = _Job(token_lists, gen)
        with self._submit_lock:
            if self._stop:
                raise RuntimeError("pod serving stopped")
            self._jobs.put(job)
        job.done.wait()
        if job.error is not None:
            raise job.error
        return job.result

    def generate(
        self,
        prompts: list[str],
        gen: GenerateConfig | None = None,
        adapter_ids=None,
    ) -> list[str]:
        encoded = [
            [self.tokenizer.bos_id] + self.tokenizer.encode(p) for p in prompts
        ]
        return [
            self.tokenizer.decode(t)
            for t in self.generate_tokens(encoded, gen, adapter_ids)
        ]

    def close(self) -> None:
        """Broadcast shutdown to the pod and stop the pump. Waits long enough
        for an in-flight generate (first-request compiles routinely exceed
        10s) to drain — exiting before the shutdown opcode goes out would
        strand every worker in its blocking broadcast."""
        self._stop = True
        self._pump.join(timeout=600)
        if self._pump.is_alive():
            logger.error(
                "pod pump did not drain within 600s; workers may be left "
                "blocked in their broadcast loop"
            )


def worker_loop(generator: Generator) -> None:
    """Run on every process with ``jax.process_index() != 0``: mirror process
    0's collective sequence forever, executing each generate tick, until a
    shutdown opcode arrives. Results are replicated; non-zero processes
    simply drop them."""
    logger.info("pod serve worker: entering broadcast loop")
    while True:
        header = _broadcast(np.zeros((8,), np.int32))
        op = int(header[0])
        if op == _SHUTDOWN:
            logger.info("pod serve worker: shutdown")
            return
        if op == _IDLE:
            continue
        batch, plen = int(header[1]), int(header[2])
        ids = _broadcast(np.zeros((batch, plen), np.int32))
        lengths = _broadcast(np.zeros((batch,), np.int32))
        ok = True
        try:
            _run_tick(generator, header, ids, lengths)
        except Exception:
            # Deterministic per-request errors (validation, OOM-at-shape)
            # raise identically on every process; the status collective below
            # confirms that before continuing. A worker that died here
            # instead would strand the whole pod at the next broadcast.
            ok = False
            logger.exception("pod serve worker: tick failed")
        if not _statuses_agree(ok):
            # One-sided failure — the pod is desynced; every process saw the
            # same divergent status vector, so all exit together.
            logger.error(
                "pod serve worker: tick status diverged across processes; "
                "shutting down"
            )
            return
