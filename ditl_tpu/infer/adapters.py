"""Adapter registry: per-tenant multi-LoRA lifecycle (ISSUE 16 tentpole).

The engine has had the multi-adapter GATHER since r3 (models/lora.py
``stack_adapters`` -> per-slot ``adapter_ids`` inside every compiled
program) but no lifecycle around it: the stack was frozen at launch, names
were a frozen dict in the HTTP handler, and changing a single adapter
meant a full-weights rolling restart. This module is the lifecycle:

- **Rows, not restarts.** The stacked pool's rows 1..K-1 (row 0 is the
  base model by convention) are a tiny allocator: free rows accept hot
  loads, live rows serve, evicted rows drain then free. Every mutation of
  engine/device state goes through the driver-thread-only
  ``ThreadedEngine.call`` seam — a swap lands BETWEEN ticks, and an
  in-flight request keeps its slot's adapter id pointing at the old,
  still-intact row until the drain frees it: nothing ever samples a
  half-swapped adapter.
- **Verify before HBM.** A load reads a manifest-carrying adapter
  checkpoint dir (utils/adapterfmt.py, the PR 5 torn-save rule), crcs the
  EXACT bytes it decoded, and validates the geometry against the serving
  model — corrupt bytes are refused on the host; they never reach the
  device. The ``adapter.load`` chaos site (corrupt action) drills this.
- **Generations.** Every (name -> row) binding carries a monotonically
  increasing generation; a publication loads the new version into a SPARE
  row, then flips the name pointer under the registry lock (journaled),
  then drains and frees the old row. Clients see responses stamped
  ``adapter:<name>@g<gen>`` flip at one journaled boundary.
- **Billing.** Residency (HBM row-seconds) and per-request gather cost
  accrue against the adapter's OWNING tenant and flush as dedicated
  ``outcome="adapter"`` ledger rows (telemetry/usage.py) — the requester
  pays for tokens, the owner pays for the pool row.

Lock discipline: ``_lock`` guards the row/name tables only and is NEVER
held across an engine call — the driver thread takes the same lock in
``bill_request`` (terminal usage rows), so holding it while waiting on
the driver would deadlock the replica.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

from ditl_tpu.chaos.plane import InjectedFault, maybe_inject
from ditl_tpu.telemetry.usage import sanitize_label
from ditl_tpu.utils import adapterfmt
from ditl_tpu.utils.logging import get_logger

logger = get_logger(__name__)

__all__ = [
    "AdapterBusy",
    "AdapterError",
    "AdapterNotFound",
    "AdapterPoolFull",
    "AdapterRegistry",
    "AdapterVerifyError",
]

PREFIX = "ditl_adapter"
# Swap latencies are host-dominated (npz decode + one .at[].set dispatch).
SWAP_BUCKETS_S = (0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 15.0)


class AdapterError(Exception):
    """Base adapter-plane error; ``status`` is the HTTP mapping the server
    uses (reject-don't-drop: every refusal names its reason)."""

    status = 400


class AdapterNotFound(AdapterError):
    status = 404

    def __init__(self, name: str, *, evicted: bool = False):
        self.name, self.evicted = name, evicted
        super().__init__(
            f"adapter {name!r} was evicted and no longer serves"
            if evicted else f"unknown adapter {name!r}")


class AdapterVerifyError(AdapterError):
    """Checkpoint failed manifest/crc/geometry verification — refused
    before any bytes reached the device."""

    status = 422


class AdapterPoolFull(AdapterError):
    status = 409


class AdapterBusy(AdapterError):
    status = 409


@dataclass
class _Row:
    """One stacked-pool row's lifecycle record. guarded-by: registry _lock
    (every field; the installed weights themselves live in the engine's
    params tree and move only on the driver thread)."""

    row: int
    state: str = "free"  # free | loading | live | evicting
    name: str = ""
    owner: str = ""  # sanitized owning-tenant label ("" = unowned)
    generation: int = 0
    step: int = -1
    source: str = ""  # checkpoint dir ("" = launch-time/static install)
    loaded_at: float = 0.0  # clock() at flip-to-live
    residency_mark: float = 0.0  # last billing flush (clock())


class AdapterRegistry:
    """Lifecycle manager for one engine's stacked adapter pool.

    ``engine`` is a ``ThreadedEngine`` (production: mutations ride
    ``call`` onto the driver thread) or a bare ``ContinuousEngine``
    (tests driving ticks synchronously — calls run inline)."""

    def __init__(self, engine, *, journal=None, usage_ledger=None,
                 drain_timeout_s: float = 30.0, clock=time.monotonic):
        inner = getattr(engine, "_engine", engine)
        if not getattr(inner, "multi_lora", False):
            raise ValueError(
                "adapter registry needs an engine serving a stacked "
                "multi-adapter pool (serve with --adapter/--adapter-pool)")
        self._engine = engine
        self._inner = inner
        self.journal = journal
        self.usage_ledger = usage_ledger
        self.drain_timeout_s = float(drain_timeout_s)
        self._clock = clock
        self.n_rows = int(inner.n_adapters)
        self._lock = threading.Lock()
        # guarded-by: _lock
        self._rows: list[_Row] = [_Row(row=i) for i in range(self.n_rows)]
        self._rows[0] = _Row(row=0, state="live", name="", owner="",
                             generation=0)  # base model; never allocated
        self._names: dict[str, int] = {}  # live name -> row
        self._tombstones: set[str] = set()  # evicted names (404, not base)
        self._generation = 0
        # Owner bills: sanitized owner label -> [gather_s, requests].
        self._bills: dict[str, list] = {}  # guarded-by: _lock
        # Per-token gather cost share: the adapter gather's FLOPs relative
        # to the base forward (both per token) — scales each request's
        # device-time estimate into the slice the gather added. A model,
        # not a measurement; consistent across tenants, which is what
        # billing shares need.
        self._gather_frac = self._gather_cost_frac(inner.cfg)
        r = inner.metrics.registry
        self._m_live = r.gauge(
            f"{PREFIX}_rows_live", "stacked pool rows serving an adapter")
        self._m_total = r.gauge(
            f"{PREFIX}_rows",
            "stacked pool rows managed (excluding base row 0)")
        self._m_loads = r.counter(
            f"{PREFIX}_loads", "adapter hot loads committed")
        self._m_load_failures = r.counter(
            f"{PREFIX}_load_failures",
            "adapter loads refused (verification/geometry/pool)")
        self._m_evictions = r.counter(
            f"{PREFIX}_evictions", "adapter rows evicted and freed")
        self._m_swap = r.histogram(
            f"{PREFIX}_swap_seconds",
            "hot load/publish swap latency (verify -> row live)",
            SWAP_BUCKETS_S)
        self._m_total.set(max(0, self.n_rows - 1))
        self._m_live.set(0)
        inner.adapter_registry = self

    # -- engine seam ---------------------------------------------------------

    def _call(self, fn):
        call = getattr(self._engine, "call", None)
        return call(fn) if call is not None else fn()

    @staticmethod
    def _gather_cost_frac(cfg) -> float:
        """lora-gather FLOPs / base-forward FLOPs, per token (host-side
        constant). Targets are attention q/v (models/lora.LORA_TARGETS);
        the base per-layer cost counts the attention projections + MLP."""
        d, r = cfg.hidden_size, max(1, cfg.lora_rank)
        q_out = cfg.num_heads * cfg.head_dim
        kv_out = cfg.num_kv_heads * cfg.head_dim
        lora = (d * r + r * q_out) + (d * r + r * kv_out)
        base = d * (2 * q_out + 2 * kv_out) + 3 * d * cfg.intermediate_size
        return lora / max(1, base)

    # -- read side (HTTP handler threads) ------------------------------------

    def resolve(self, name: str) -> tuple[int, int]:
        """(row, generation) serving ``name`` right now. Raises
        :class:`AdapterNotFound` for unknown names and — with
        ``evicted=True`` — for tombstoned ones: an evicted adapter must
        404, never silently serve base (the frozen-dict bug this
        registry replaces)."""
        with self._lock:
            row_id = self._names.get(name)
            if row_id is None:
                raise AdapterNotFound(name, evicted=name in self._tombstones)
            row = self._rows[row_id]
            return row_id, row.generation

    def list(self) -> dict:
        """The /v1/adapters body: pool occupancy + every named binding."""
        with self._lock:
            adapters = [
                {
                    "name": row.name,
                    "row": row.row,
                    "generation": row.generation,
                    "step": row.step,
                    "owner": row.owner,
                    "state": row.state,
                    "source": row.source,
                }
                for row in self._rows[1:]
                if row.state in ("live", "evicting") and row.name
            ]
            adapters.sort(key=lambda a: a["name"])
            return {
                "pool_rows": max(0, self.n_rows - 1),
                "free_rows": sum(
                    1 for row in self._rows[1:] if row.state == "free"),
                "adapters": adapters,
                "evicted": sorted(self._tombstones),
            }

    def names(self) -> dict[str, int]:
        """Live name -> row map (one locked snapshot; the /v1/models
        path)."""
        with self._lock:
            return dict(self._names)

    # -- lifecycle -----------------------------------------------------------

    def seed(self, name: str, row: int, *, owner: str = "",
             step: int = -1, source: str = "") -> None:
        """Adopt a launch-time-installed adapter (the legacy ``--adapter``
        CLI path stacks them before the engine builds): marks ``row``
        live under ``name`` without loading anything."""
        with self._lock:
            binding = self._bind_locked(name, row, owner=owner, step=step,
                                        source=source)
        self._journal("adapter.loaded", name=name, row=row,
                      generation=binding["generation"], step=step,
                      checkpoint=source or "launch")

    def load(self, name: str, directory: str, *, owner: str = "") -> dict:
        """Hot-load a manifest-verified adapter checkpoint into a free
        row and bind ``name`` to it (new name or re-publication — the
        binding flips atomically either way). Returns the new binding."""
        t0 = self._clock()
        directory = adapterfmt.resolve_latest(directory)
        try:
            # An `error` rule raises InjectedFault (RuntimeError) from
            # inside the consult — it must ride the infrastructure-failure
            # path (a 5xx), never become a client error; only `corrupt` is
            # returned for this seam to apply.
            fault = maybe_inject("adapter.load")
        except InjectedFault:
            self._m_load_failures.inc()
            self._journal("adapter.load_failed", name=name,
                          checkpoint=directory, chaos=True)
            raise
        try:
            tree, meta = self._verify_host_side(
                directory, flip_byte=fault is not None
                and fault.action == "corrupt")
        except AdapterError:
            self._m_load_failures.inc()
            self._journal("adapter.load_failed", name=name,
                          checkpoint=directory)
            raise
        row_id = self._reserve_row(name)
        try:
            self._call(lambda: self._inner.install_adapter(row_id, tree))
        except BaseException:
            with self._lock:
                self._rows[row_id] = _Row(row=row_id)  # back to free
            self._m_load_failures.inc()
            self._journal("adapter.load_failed", name=name, row=row_id,
                          checkpoint=directory)
            raise
        with self._lock:
            binding = self._bind_locked(
                name, row_id, owner=owner, step=int(meta.get("step", -1)),
                source=directory)
        self._m_loads.inc()
        self._m_swap.observe(self._clock() - t0)
        self._journal("adapter.loaded", name=name, row=row_id,
                      generation=binding["generation"],
                      step=binding["step"], checkpoint=directory)
        # A re-publication left the PREVIOUS row bound to nothing: drain
        # and free it so the pool does not leak a row per publish. The
        # flip already happened — a drain timeout here must not fail the
        # load, so a still-busy old row is left `evicting` (journaled)
        # and reaped on the next lifecycle call.
        old_row = binding.pop("_replaced_row", None)
        if old_row is not None:
            try:
                self._drain_and_free(old_row)
            except AdapterBusy:
                self._journal("adapter.drain_pending", name=name,
                              row=old_row)
        self._reap()
        return binding

    def evict(self, name: str) -> dict:
        """Unbind ``name`` (immediately — no new request resolves it),
        drain in-flight users of its row, purge the row's published
        prefix pages, zero the weights, and free the row. The name
        tombstones: its next resolution is a 404-with-reason, never a
        silent fall-through to base."""
        with self._lock:
            row_id = self._names.pop(name, None)
            if row_id is None:
                raise AdapterNotFound(name, evicted=name in self._tombstones)
            self._tombstones.add(name)
            row = self._rows[row_id]
            row.state = "evicting"
            self._flush_row_residency_locked(row)
            generation = row.generation
        try:
            self._drain_and_free(row_id)
        except AdapterBusy:
            # Drain timed out: restore the binding — reject-don't-drop, a
            # busy row must fail the evict, not tear it.
            with self._lock:
                self._names[name] = row_id
                self._tombstones.discard(name)
                self._rows[row_id].state = "live"
            raise
        self._m_evictions.inc()
        self._refresh_gauges()
        self._journal("adapter.evicted", name=name, row=row_id,
                      generation=generation)
        return {"name": name, "row": row_id, "evicted": True}

    def publish(self, name: str, directory: str, *, owner: str = "") -> dict:
        """One replica's half of the publication protocol: verify ->
        load-to-spare-row -> flip the name pointer (generation bump,
        journaled) -> drain + free the old row. Exactly :meth:`load` —
        named separately so the journal reads as a publication."""
        binding = self.load(name, directory, owner=owner)
        self._journal("adapter.published", name=name,
                      row=binding["row"], generation=binding["generation"],
                      step=binding["step"])
        return binding

    # -- internals -----------------------------------------------------------

    def _verify_host_side(self, directory: str,
                          *, flip_byte: bool) -> tuple[dict, dict]:
        """Manifest+crc verify and decode ON THE HOST, then geometry-check
        against the serving model. Raises AdapterVerifyError; nothing
        reaches the device on any failure path."""
        try:
            arrays = adapterfmt.verify_and_read(directory,
                                                flip_byte=flip_byte)
            meta = adapterfmt.read_meta(directory)
        except (OSError, ValueError, KeyError) as e:
            raise AdapterVerifyError(str(e)) from e
        cfg = self._inner.cfg
        if int(meta.get("lora_rank", -1)) != cfg.lora_rank:
            raise AdapterVerifyError(
                f"adapter rank {meta.get('lora_rank')} != serving rank "
                f"{cfg.lora_rank}")
        if int(meta.get("num_layers", -1)) != cfg.num_layers:
            raise AdapterVerifyError(
                f"adapter layers {meta.get('num_layers')} != serving "
                f"layers {cfg.num_layers}")
        if int(meta.get("hidden_size", -1)) != cfg.hidden_size:
            raise AdapterVerifyError(
                f"adapter hidden {meta.get('hidden_size')} != serving "
                f"hidden {cfg.hidden_size}")
        tree: dict = {}
        for key, arr in arrays.items():
            target, _, leaf = key.partition(".")
            tree.setdefault(target, {})[leaf] = arr
        want = set(self._inner.params["layers"]["lora"])
        if set(tree) != want:
            raise AdapterVerifyError(
                f"adapter targets {sorted(tree)} != serving targets "
                f"{sorted(want)}")
        return tree, meta

    def _reap(self) -> None:
        """Free `evicting` rows whose name binding already moved on (a
        drain that timed out during a publish) once their in-flight users
        are gone — opportunistic, called from lifecycle entry points."""
        with self._lock:
            stale = [row.row for row in self._rows[1:]
                     if row.state == "evicting"
                     and self._names.get(row.name) != row.row]
        for row_id in stale:
            if self._call(
                    lambda r=row_id: self._inner.adapter_row_in_use(r)) == 0:
                def _scrub(r=row_id):
                    self._inner.purge_adapter_pages(r)
                    self._inner.clear_adapter(r)
                self._call(_scrub)
                with self._lock:
                    self._rows[row_id] = _Row(row=row_id)
                    self._refresh_gauges_locked()

    def _reserve_row(self, name: str) -> int:
        with self._lock:
            for row in self._rows[1:]:
                if row.state == "free":
                    row.state = "loading"
                    row.name = name
                    return row.row
        raise AdapterPoolFull(
            f"no free adapter rows (pool {self.n_rows - 1}, all "
            f"live/loading); evict one or serve with a larger "
            f"--adapter-pool")

    def _bind_locked(self, name: str, row_id: int, *, owner: str,
                     step: int, source: str) -> dict:
        """Flip ``name`` to ``row_id`` (caller holds ``_lock``): the one
        atomic visibility point — resolve() sees either the old complete
        row or the new complete row, generation strictly increasing."""
        if not 1 <= row_id < self.n_rows:
            raise ValueError(f"adapter row {row_id} out of range")
        self._generation += 1
        now = self._clock()
        old_row = self._names.get(name)
        row = self._rows[row_id]
        row.state = "live"
        row.name = name
        row.owner = sanitize_label(owner) if owner else ""
        row.generation = self._generation
        row.step = step
        row.source = source
        row.loaded_at = now
        row.residency_mark = now
        self._names[name] = row_id
        self._tombstones.discard(name)
        self._refresh_gauges_locked()
        binding = {"name": name, "row": row_id,
                   "generation": row.generation, "step": step,
                   "owner": row.owner}
        if old_row is not None and old_row != row_id:
            self._rows[old_row].state = "evicting"
            self._flush_row_residency_locked(self._rows[old_row])
            binding["_replaced_row"] = old_row
        return binding

    def _drain_and_free(self, row_id: int) -> None:
        """Wait until nothing in flight references ``row_id`` (slots +
        queue, checked on the driver thread), then purge its published
        prefix pages, zero the weights, and free it."""
        deadline = self._clock() + self.drain_timeout_s
        while self._call(
                lambda: self._inner.adapter_row_in_use(row_id)) > 0:
            if self._clock() > deadline:
                raise AdapterBusy(
                    f"adapter row {row_id} still serving in-flight "
                    f"requests after {self.drain_timeout_s:.1f}s drain")
            time.sleep(0.005)

        def _scrub():
            self._inner.purge_adapter_pages(row_id)
            self._inner.clear_adapter(row_id)

        self._call(_scrub)
        with self._lock:
            self._rows[row_id] = _Row(row=row_id)
            self._refresh_gauges_locked()

    def _refresh_gauges(self) -> None:
        with self._lock:
            self._refresh_gauges_locked()

    def _refresh_gauges_locked(self) -> None:
        self._m_live.set(sum(
            1 for row in self._rows[1:] if row.state == "live"))

    def _journal(self, event: str, **attrs) -> None:
        if self.journal is not None:
            try:
                self.journal.event(event, **attrs)
            except Exception:  # noqa: BLE001 - journaling never kills serving
                logger.exception("adapter journal write failed")

    # -- billing (ISSUE 16 usage satellite) ----------------------------------

    def bill_request(self, row_id: int, usage_row: dict) -> None:
        """Annotate one terminal usage row (driver thread, from
        ``_note_usage_terminal``) and accrue the gather cost against the
        adapter's OWNER — the requester's row carries the adapter name
        for visibility, but the gather seconds land on the owner's bill
        (flushed by :meth:`flush_billing`), never on the requester's."""
        with self._lock:
            if not 0 <= row_id < self.n_rows:
                return
            row = self._rows[row_id]
            if row.state not in ("live", "evicting") or not row.name:
                return
            usage_row["adapter"] = row.name
            usage_row["adapter_generation"] = row.generation
            if row.owner:
                gather = self._gather_frac * max(
                    0.0, float(usage_row.get("device_time_est_s") or 0.0))
                bill = self._bills.setdefault(row.owner, [0.0, 0, 0.0])
                bill[0] += gather
                bill[1] += 1

    def _flush_row_residency_locked(self, row: _Row) -> None:
        """Accrue (now - mark) HBM residency-seconds against the row
        owner's bill; caller holds ``_lock``."""
        if row.owner and row.residency_mark:
            now = self._clock()
            dt = max(0.0, now - row.residency_mark)
            row.residency_mark = now
            self._bills.setdefault(row.owner, [0.0, 0, 0.0])[2] += dt

    def flush_billing(self) -> list[dict]:
        """Flush accrued owner bills as dedicated ``outcome="adapter"``
        ledger rows (one per owner): residency-seconds for every owned
        live row plus the accumulated per-request gather estimate.
        Called by the server's /usage path and at evict/close — billing
        is additive across flushes (each row carries deltas only)."""
        with self._lock:
            for row in self._rows[1:]:
                if row.state == "live":
                    self._flush_row_residency_locked(row)
            bills, self._bills = self._bills, {}
        rows_out = []
        for owner, bill in sorted(bills.items()):
            gather = round(bill[0], 9)
            residency = round(bill[2], 6)
            if gather <= 0 and residency <= 0:
                continue
            out = {
                "tenant": owner,
                "outcome": "adapter",
                "adapter_gather_est_s": gather,
                "adapter_residency_s": residency,
                "adapter_requests": int(bill[1]),
            }
            rows_out.append(out)
            if self.usage_ledger is not None:
                try:
                    self.usage_ledger.record(**out)
                except Exception:  # noqa: BLE001 - billing must not crash
                    logger.exception("adapter bill flush failed")
        return rows_out
