"""KV-cache generation engine (L1/L5) — the local, TPU-native "inference" the
reference only reaches over HTTP (ref ``src/distributed_inference.py:34-41``).

Design (TPU-first):
- **Prefill + decode split**: the prompt is processed in one batched forward
  (MXU-friendly big matmuls) writing the KV cache; decode then feeds one token
  per step through a ``lax.scan`` — the whole generation loop is a single XLA
  program, no host round-trips between tokens.
- **Static shapes**: prompts are right-padded to a power-of-two bucket and the
  decode loop has a static ``max_new_tokens``, so each (batch, bucket,
  GenerateConfig) compiles once and is cached.
- **Masked-slot validity instead of causal masks**: every (b, slot) pair in
  the cache carries an implicit validity rule — prompt slots ``< lengths[b]``
  plus generated slots — so right-padding, per-example prompt lengths, and
  EOS freezing all work inside one jitted program.
- **Sharding-aware**: with a mesh, the cache is sharded batch-over-data/fsdp
  and KV-heads-over-tensor via the same rule table as training
  (parallel/sharding.py), so a TP/FSDP-sharded model decodes without
  resharding its weights.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ditl_tpu.config import ModelConfig
from ditl_tpu.data.tokenizer import Tokenizer
from ditl_tpu.infer.cache import cache_logical_axes, init_cache
from ditl_tpu.infer.sampling import sample_logits
from ditl_tpu.models import llama
from ditl_tpu.utils.logging import get_logger

logger = get_logger(__name__)

__all__ = ["GenerateConfig", "Generator"]


@dataclass(frozen=True)
class GenerateConfig:
    """Per-request sampling parameters (static: part of the compile key)."""

    max_new_tokens: int = 64
    temperature: float = 0.0  # 0 => greedy
    top_k: int = 0  # 0 => disabled
    top_p: float = 1.0  # 1.0 => disabled
    seed: int = 0
    # >0 => also return the chosen token's logprob and the top-N
    # alternatives per step (OpenAI `logprobs` semantics; engine
    # `generate_with_logprobs`). Part of the compile key.
    logprobs: int = 0


def lru_program(cache, key, build, bound: int = 32):
    """Bounded compile-cache access: move-to-front on hit, build on miss,
    evict oldest past ``bound``. Compile keys include client-controlled
    fields (max_tokens, temperature...), so every program cache on a
    serving path must be bounded or it is an unbounded memory leak."""
    if key in cache:
        cache.move_to_end(key)
        return cache[key]
    prog = build()
    cache[key] = prog
    while len(cache) > bound:
        cache.popitem(last=False)
    return prog


def _next_pow2(n: int, floor: int = 16) -> int:
    p = floor
    while p < n:
        p *= 2
    return p


class Generator:
    """Batch text generation over a (possibly sharded) Llama-family model."""

    def __init__(
        self,
        params: llama.Params,
        model_cfg: ModelConfig,
        tokenizer: Tokenizer,
        *,
        mesh=None,
        rules=None,
    ):
        from ditl_tpu.data.tokenizer import check_vocab

        check_vocab(tokenizer, model_cfg.vocab_size, "Generator")
        self.params = params
        self.cfg = model_cfg
        self.tokenizer = tokenizer
        self.mesh = mesh
        self.rules = rules
        # Multi-LoRA serving tree (models/lora.stack_adapters): full-tree
        # adapter leaves are (L, K, d, r); requests then pick adapters by id.
        lora = params.get("layers", {}).get("lora") or {}
        self.multi_lora = bool(lora) and next(iter(lora.values()))["a"].ndim == 4
        # LRU: the compile key includes client-controlled GenerateConfig
        # fields (temperature, top_p, max_new_tokens...), so an unbounded
        # cache is an unbounded memory leak on a public server — a client
        # sweeping temperatures would pin one program per distinct float.
        import collections

        self._compiled: collections.OrderedDict = collections.OrderedDict()
        self._compile_cache_size = 32

    # -- compiled program ---------------------------------------------------

    def _build(self, batch: int, prompt_len: int, gen: GenerateConfig):
        """Compile the full prefill+decode program for one shape bucket."""
        cfg, mesh, rules = self.cfg, self.mesh, self.rules
        max_len = prompt_len + gen.max_new_tokens
        if max_len > cfg.max_seq_len:
            raise ValueError(
                f"prompt {prompt_len} + max_new {gen.max_new_tokens} exceeds "
                f"model max_seq_len {cfg.max_seq_len}"
            )
        from ditl_tpu.parallel.sharding import seq_shards

        seq_n = seq_shards(mesh, rules)
        if seq_n > 1:
            # Round the cache up so the context dim always divides the
            # sequence axis — sequence-sharded serving must never silently
            # fall back to a replicated cache (the continuous engine raises
            # for the same condition; here the bucket is internal, so
            # padding it is the kinder fix).
            max_len = -(-max_len // seq_n) * seq_n
        pad_id = jnp.int32(self.tokenizer.pad_id)
        eos_id = jnp.int32(self.tokenizer.eos_id)
        slots = jnp.arange(max_len, dtype=jnp.int32)

        def run(params, input_ids, lengths, rng, adapter_ids=None):
            cache = init_cache(cfg, batch, max_len)
            if mesh is not None:
                from ditl_tpu.parallel.sharding import named_sharding_tree

                cache = jax.lax.with_sharding_constraint(
                    cache,
                    named_sharding_tree(
                        mesh,
                        cache_logical_axes(cfg, seq_sharded=seq_n > 1),
                        rules,
                    ),
                )
            # Prefill: causal over real (non-pad) prompt slots — pure causal
            # self-attention from an empty cache, so the flash kernel
            # applies (prefill_causal; pad validity rides segment ids).
            q_pos = jnp.arange(prompt_len, dtype=jnp.int32)
            seg = (q_pos[None, :] < lengths[:, None]).astype(jnp.int32)
            positions = jnp.broadcast_to(q_pos, (batch, prompt_len))
            logits, cache = llama.forward(
                params,
                input_ids,
                cfg,
                positions=positions,
                segment_ids=seg,
                mesh=mesh,
                rules=rules,
                cache=cache,
                cache_index=jnp.int32(0),
                adapter_ids=adapter_ids,
                prefill_causal=True,
            )
            last = jnp.take_along_axis(
                logits, (lengths - 1)[:, None, None], axis=1
            )[:, 0]  # (B, V)
            rng, sub = jax.random.split(rng)
            first = sample_logits(
                last, sub, temperature=gen.temperature, top_k=gen.top_k,
                top_p=gen.top_p,
            )
            done0 = first == eos_id
            n_lp = gen.logprobs

            def lp_stats(step_logits, tok):
                """Chosen-token logprob + top-N alternatives (OpenAI
                `logprobs` semantics: of the raw distribution, before any
                temperature/top-k/top-p shaping)."""
                lp = jax.nn.log_softmax(step_logits.astype(jnp.float32), -1)
                chosen = jnp.take_along_axis(lp, tok[:, None], 1)[:, 0]
                top_lp, top_id = jax.lax.top_k(lp, n_lp)
                return chosen, top_id.astype(jnp.int32), top_lp

            stats0 = lp_stats(last, first) if n_lp else None

            def body(carry, t):
                cache, cur, cur_stats, done, rng = carry
                rng, sub = jax.random.split(rng)
                write_idx = prompt_len + t
                # Attend to: real prompt slots + generated slots so far
                # (including the one being written at write_idx).
                mask = (
                    (slots[None, :] < lengths[:, None])
                    | ((slots[None, :] >= prompt_len) & (slots[None, :] <= write_idx))
                )[:, None, :]
                step_logits, cache = llama.forward(
                    params,
                    cur[:, None],
                    cfg,
                    positions=(lengths + t)[:, None],
                    mesh=mesh,
                    rules=rules,
                    cache=cache,
                    cache_index=write_idx,
                    attn_mask=mask,
                    adapter_ids=adapter_ids,
                )
                nxt = sample_logits(
                    step_logits[:, 0], sub, temperature=gen.temperature,
                    top_k=gen.top_k, top_p=gen.top_p,
                )
                # cur's stats were computed when cur was sampled (previous
                # iteration / prefill); emit them alongside cur.
                nxt_stats = lp_stats(step_logits[:, 0], nxt) if n_lp else None
                new_done = done | (cur == eos_id)
                nxt = jnp.where(new_done, pad_id, nxt)
                return (cache, nxt, nxt_stats, new_done, rng), (cur, cur_stats)

            _, (tokens, stats) = jax.lax.scan(
                body,
                (cache, first, stats0, done0, rng),
                jnp.arange(gen.max_new_tokens, dtype=jnp.int32),
            )
            out = {"tokens": tokens.T}  # (steps, B) -> (B, steps)
            if n_lp:
                chosen, top_id, top_lp = stats
                out["token_logprobs"] = chosen.T  # (B, steps)
                out["top_ids"] = jnp.swapaxes(top_id, 0, 1)  # (B, steps, N)
                out["top_logprobs"] = jnp.swapaxes(top_lp, 0, 1)
            return out

        jitted = jax.jit(run)
        logger.info(
            "compiling generate program: batch=%d prompt_len=%d max_new=%d",
            batch, prompt_len, gen.max_new_tokens,
        )
        return jitted

    def _get_compiled(self, batch: int, prompt_len: int, gen: GenerateConfig):
        # seed is runtime data (the rng argument), not part of the program —
        # keep it out of the compile key or every new seed recompiles.
        key = (batch, prompt_len, dataclasses.replace(gen, seed=0))
        return lru_program(
            self._compiled, key, lambda: self._build(batch, prompt_len, gen),
            bound=self._compile_cache_size,
        )

    # -- public surface -----------------------------------------------------

    def generate_tokens(
        self,
        token_lists: list[list[int]],
        gen: GenerateConfig | None = None,
        adapter_ids: list[int] | None = None,
    ) -> list[list[int]]:
        """Token-id prompts in, generated token ids out (EOS-trimmed).
        ``adapter_ids`` selects each prompt's LoRA adapter when the params
        tree is a multi-adapter stack (0 = the conventional base slot)."""
        return self._generate(token_lists, gen, adapter_ids)[0]

    def generate_tokens_with_logprobs(
        self,
        token_lists: list[list[int]],
        gen: GenerateConfig,
        adapter_ids: list[int] | None = None,
    ) -> tuple[list[list[int]], list[dict]]:
        """Like ``generate_tokens`` but also returns, per prompt, a dict of
        ``token_logprobs`` (chosen token, raw distribution) and aligned
        ``top_ids``/``top_logprobs`` (N = ``gen.logprobs``) lists."""
        if gen.logprobs < 1:
            raise ValueError("generate_tokens_with_logprobs needs gen.logprobs >= 1")
        results, lps = self._generate(token_lists, gen, adapter_ids)
        return results, lps

    def _generate(
        self,
        token_lists: list[list[int]],
        gen: GenerateConfig | None,
        adapter_ids: list[int] | None = None,
    ) -> tuple[list[list[int]], list[dict]]:
        gen = gen or GenerateConfig()
        n = len(token_lists)
        if n == 0:
            return [], []
        if adapter_ids is not None and not self.multi_lora:
            raise ValueError(
                "adapter_ids given but params are not a multi-adapter stack "
                "(models/lora.stack_adapters)"
            )
        token_lists = [t if t else [self.tokenizer.bos_id] for t in token_lists]
        batch = _next_pow2(n, floor=1)
        prompt_len = _next_pow2(max(len(t) for t in token_lists))
        ids = np.full((batch, prompt_len), self.tokenizer.pad_id, np.int32)
        lengths = np.ones((batch,), np.int32)  # dummy rows attend to slot 0
        for i, toks in enumerate(token_lists):
            ids[i, : len(toks)] = toks
            lengths[i] = len(toks)
        run = self._get_compiled(batch, prompt_len, gen)
        rng = jax.random.key(gen.seed)
        args = [self.params, jnp.asarray(ids), jnp.asarray(lengths), rng]
        if self.multi_lora:
            aid = np.zeros((batch,), np.int32)
            if adapter_ids is not None:
                if len(adapter_ids) != n:
                    raise ValueError(
                        f"adapter_ids has {len(adapter_ids)} entries for {n} prompts"
                    )
                lora = self.params["layers"]["lora"]
                k = next(iter(lora.values()))["a"].shape[1]
                bad = [i for i in adapter_ids if not 0 <= i < k]
                if bad:
                    # JAX gathers clamp out-of-range indices under jit, which
                    # would silently serve the wrong adapter.
                    raise ValueError(f"adapter ids {bad} out of range [0, {k})")
                aid[:n] = adapter_ids
            args.append(jnp.asarray(aid))
        out = jax.device_get(run(*args))
        tokens = np.asarray(out["tokens"])
        results = []
        keep: list[int] = []
        for i in range(n):
            row = tokens[i].tolist()
            trimmed = []
            for tok in row:
                if tok == self.tokenizer.eos_id or tok == self.tokenizer.pad_id:
                    break
                trimmed.append(tok)
            results.append(trimmed)
            keep.append(len(trimmed))
        lps: list[dict] = []
        if gen.logprobs:
            lps = [
                {
                    "token_logprobs": np.asarray(out["token_logprobs"])[i, : keep[i]].tolist(),
                    "top_ids": np.asarray(out["top_ids"])[i, : keep[i]].tolist(),
                    "top_logprobs": np.asarray(out["top_logprobs"])[i, : keep[i]].tolist(),
                }
                for i in range(n)
            ]
        return results, lps

    def generate(
        self,
        prompts: list[str],
        gen: GenerateConfig | None = None,
        adapter_ids: list[int] | None = None,
    ) -> list[str]:
        """Text prompts in, generated continuations out."""
        encoded = [
            [self.tokenizer.bos_id] + self.tokenizer.encode(p) for p in prompts
        ]
        out = self.generate_tokens(encoded, gen, adapter_ids)
        return [self.tokenizer.decode(toks) for toks in out]
