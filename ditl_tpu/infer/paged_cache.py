"""Host-side page allocator for the paged KV cache (infer/continuous.py
``cache_mode="paged"``; device op: ops/paged_attention.py).

The device holds one pool of KV pages per layer — ``(L, n_pages, K,
page_size, D)``, kv-heads before page slots (ops/paged_attention.py's
Mosaic trailing-dim requirement) — and per-slot page tables map logical
block index -> physical page.
This module is the host bookkeeping around that pool:

- **Free-list allocation** with refcounts: a page may back several slots'
  tables at once (shared prefix blocks).
- **Content-addressed dedup**: every FULL page of a prompt is published
  under the key ``(parent_physical_page_id, exact_tokens_in_page)``; a
  later prompt whose leading blocks walk to published pages reuses them
  (refcount bump, no prefill) — vLLM-style automatic prefix caching, no
  ``register_prefix`` call required. The key chains through the *physical*
  parent page id and compares the block's actual tokens, so equal keys
  mean equal full prefixes by construction — no reliance on hash
  collision resistance (a colliding ``hash()`` key would silently serve
  another prompt's KV). Only full, immutable pages are ever shared: a
  slot's partial tail page and its decode pages are private, so there is
  no copy-on-write fault path — sharing is read-only by construction.
- **LRU eviction**: published pages whose only reference is the hash cache
  are reclaimable; allocation pressure evicts them oldest-first.

Page 0 is a reserved sentinel: dead slots' table tails point at it, the
kernel's out-of-range page fetches clamp to it, and the per-tick tail
flush aims its invalid rows at it — so live data can never collide with a
stale table entry.

The allocator is plain Python on the host — admission policy is not a TPU
problem (same stance as the continuous engine's scheduler).
"""

from __future__ import annotations

from collections import OrderedDict, deque

__all__ = ["EvictedPage", "PageAllocator", "block_keys"]

PageKey = tuple[int, tuple[int, ...]]

# One page leaving the content cache, as ``on_evict`` reports it: the
# physical id being reclaimed, the chain ROOT (<= 0 adapter namespace),
# and the exact token blocks from the root up to and including this page.
# The blocks — not the physical key — are what survive the tier boundary:
# host_tier.py re-interns them under never-recycled node ids, so a spilled
# entry can never verify against a recycled physical id's new content
# (ISSUE 13).
EvictedPage = tuple[int, int, tuple[tuple[int, ...], ...]]


def block_keys(tokens: list[int], page_size: int, parents: list[int]) -> list[PageKey]:
    """Content keys for the FULL pages of ``tokens``: page i's key is
    ``(physical id of page i-1, page i's exact tokens)`` (parent 0 = the
    sentinel for the first page). Equal keys mean equal full prefixes by
    induction over verified parents — no hash-collision exposure."""
    out: list[PageKey] = []
    for i, start in enumerate(range(0, len(tokens) - page_size + 1, page_size)):
        parent = parents[i - 1] if i > 0 else 0
        out.append((parent, tuple(tokens[start:start + page_size])))
    return out


class PageAllocator:
    """Refcounted page pool bookkeeping with content-hash reuse."""

    def __init__(self, n_pages: int, on_evict=None, group_payload=None):
        if n_pages < 2:
            raise ValueError(f"need >= 2 pages (page 0 is reserved), got {n_pages}")
        self.n_pages = n_pages
        # LRU reclaims of published (cache-only) pages. ``on_evict`` is an
        # optional callback fired once per reclaim with the full evicted
        # GROUP — the claimed page plus every cascaded descendant, parent
        # first, each as an :data:`EvictedPage` — BEFORE the pages are
        # handed back, so the engine can both count the eviction
        # (``prefix_cache_evictions``, ISSUE 8) and capture the KV for the
        # host-RAM tier spill (ISSUE 13) while the content is still
        # addressable. ``group_payload`` (zero-arg predicate, default
        # always-True) gates that collection: a tier-less, handoff-less
        # engine consumes only the eviction COUNT, and walking chains /
        # materializing block tuples inside ``alloc`` on the admission
        # path would be pure waste there — the callback then receives an
        # empty tuple.
        self.evictions = 0
        self._on_evict = on_evict
        self._group_payload = group_payload
        self._free: deque[int] = deque(range(1, n_pages))
        self._ref = [0] * n_pages
        self._key_to_page: dict[PageKey, int] = {}
        self._page_key: dict[int, PageKey] = {}
        # parent physical page -> keys of published children chained to it.
        # Needed so evicting a parent CASCADES: a child key (parent_pid,
        # tokens) left behind after parent_pid is recycled and republished
        # with different content would match a later prompt and serve KV
        # computed under the OLD prefix — silent cross-request corruption.
        self._children: dict[int, set[PageKey]] = {}
        # Insertion-ordered: oldest published key evicts first.
        self._lru: OrderedDict[PageKey, None] = OrderedDict()
        # Incrementally-maintained count of published pages whose only
        # reference is the content cache (ref == 1). The gateway's
        # freshness window polls every replica's /stats AND /health each
        # interval, and the old O(published-pages) scan ran on every poll —
        # at fleet scale that is a per-second full-cache walk (ISSUE 13
        # satellite). Updated at every ref/publish transition; pinned
        # equal to the scan by test_kvtier's equivalence drill.
        self._evictable = 0

    # -- capacity ------------------------------------------------------------

    @property
    def n_free(self) -> int:
        return len(self._free)

    @property
    def n_evictable(self) -> int:
        """Published pages reclaimable right now (cache-only reference).
        O(1): an incrementally-updated counter, not a scan — /stats and
        /health poll this from HTTP threads every gateway interval."""
        return self._evictable

    def scan_evictable(self) -> int:
        """The O(published-pages) ground truth ``n_evictable`` used to
        recompute per call — kept as the equivalence-test oracle."""
        # list() snapshots atomically under the GIL: callers may read this
        # from HTTP threads while the driver thread publishes/evicts.
        return sum(
            1 for k, p in list(self._key_to_page.items()) if self._ref[p] == 1
        )

    # -- alloc / free --------------------------------------------------------

    def alloc(self, n: int) -> list[int]:
        """Allocate ``n`` private pages (ref 1 each), evicting LRU published
        pages if the free list runs short. Raises when truly out."""
        out: list[int] = []
        while len(out) < n:
            if self._free:
                pid = self._free.popleft()
            else:
                pid = self._evict_one()
                if pid is None:
                    # Roll back so a failed multi-page request leaks nothing.
                    for p in out:
                        self.release(p)
                    raise MemoryError(
                        f"page pool exhausted ({self.n_pages} pages, 0 evictable)"
                    )
            self._ref[pid] = 1
            out.append(pid)
        return out

    def _evict_one(self) -> int | None:
        for key in self._lru:
            pid = self._key_to_page[key]
            if self._ref[pid] == 1:  # only the content cache holds it
                # Collect the whole group (claimed page + cascaded
                # descendants, parent first) BEFORE unpublishing: the
                # chain walk needs the maps intact, and the host-tier
                # spill needs every page the reclaim is about to make
                # unmatchable, not just the one the allocator claims.
                group = ()
                if self._on_evict is not None and (
                    self._group_payload is None or self._group_payload()
                ):
                    group = self._collect_group(key, pid)
                self._unpublish(key, pid, claimed=True)
                self.evictions += 1
                if self._on_evict is not None:
                    self._on_evict(group)
                return pid
        return None

    def _chain_blocks(self, pid: int) -> tuple[int, tuple[tuple[int, ...], ...]]:
        """``(root, token blocks root..pid)`` for a PUBLISHED page — walks
        parent keys up. Every published page's ancestors are published (the
        unpublish cascade guarantees it), so the walk always reaches a
        non-positive root."""
        blocks: list[tuple[int, ...]] = []
        cur = pid
        while cur > 0:
            key = self._page_key[cur]
            blocks.append(key[1])
            cur = key[0]
        return cur, tuple(reversed(blocks))

    def _collect_group(
        self, key: PageKey, pid: int,
        root: int | None = None,
        blocks: tuple[tuple[int, ...], ...] | None = None,
    ) -> list[EvictedPage]:
        """Claimed page + cascaded descendants, parent first. The chain
        walk runs ONCE for the head; descendants extend the parent's
        blocks incrementally (token tuples shared by reference) — a
        per-member walk would make a deep cascade O(depth^2) of tuple
        materialization inside alloc() on the admission path."""
        if blocks is None:
            root, blocks = self._chain_blocks(pid)
        out: list[EvictedPage] = [(pid, root, blocks)]
        for child_key in list(self._children.get(pid, ())):
            child_pid = self._key_to_page.get(child_key)
            if child_pid is not None:
                out.extend(self._collect_group(
                    child_key, child_pid, root, blocks + (child_key[1],)
                ))
        return out

    def _unpublish(self, key: PageKey, pid: int, *, claimed: bool) -> None:
        """Remove a published key (and cascade through descendants).

        ``claimed=True`` means the caller (eviction inside ``alloc``) takes
        ownership of ``pid`` directly — it must NOT also land on the free
        list. Cascaded descendants are never claimed: dropping the cache's
        reference frees them when nothing else holds them (in-flight users
        keep their refcounts; only matchability and the cache ref go)."""
        if self._ref[pid] == 1:
            # Leaving the published set while cache-only: no longer counted
            # evictable (release() below won't see it published anymore).
            self._evictable -= 1
        del self._key_to_page[key]
        del self._page_key[pid]
        self._lru.pop(key, None)
        parent_kids = self._children.get(key[0])
        if parent_kids is not None:
            parent_kids.discard(key)
            if not parent_kids:
                del self._children[key[0]]
        # Cascade: children's keys chain through THIS physical id; once it
        # can be recycled, those keys would verify against the wrong
        # content.
        for child_key in list(self._children.pop(pid, ())):
            child_pid = self._key_to_page.get(child_key)
            if child_pid is not None:
                self._unpublish(child_key, child_pid, claimed=False)
        if claimed:
            self._ref[pid] -= 1  # the cache's reference passes to the caller
        else:
            self.release(pid)  # the cache's own reference

    def purge_root(self, root: int) -> int:
        """Unpublish every chain published under content root ``root``
        (non-positive adapter namespace, see ``publish_chain``) — the
        adapter-evict seam (ISSUE 16): a freed pool row's published pages
        would otherwise prefix-match a future adapter installed into the
        same row and serve KV computed under the OLD weights. In-flight
        users keep their refcounts (only matchability and the cache ref
        go — the registry drains the row before calling this anyway).
        Returns the number of first-level chains purged."""
        purged = 0
        for key in list(self._children.get(root, ())):
            pid = self._key_to_page.get(key)
            if pid is not None:
                self._unpublish(key, pid, claimed=False)
                purged += 1
        return purged

    def retain(self, pid: int) -> None:
        if self._ref[pid] == 1 and pid in self._page_key:
            self._evictable -= 1  # published cache-only page gains a user
        self._ref[pid] += 1

    def release(self, pid: int) -> None:
        if pid == 0:
            return
        self._ref[pid] -= 1
        if self._ref[pid] < 0:
            raise AssertionError(f"double release of page {pid}")
        if self._ref[pid] == 1 and pid in self._page_key:
            self._evictable += 1  # published page dropped to cache-only
        if self._ref[pid] == 0:
            self._free.append(pid)

    # -- content cache -------------------------------------------------------

    def lookup(self, key: PageKey) -> int | None:
        """Published page for content key ``key`` (bumps LRU recency)."""
        pid = self._key_to_page.get(key)
        if pid is not None:
            self._lru.move_to_end(key)
        return pid

    def publish(self, key: PageKey, pid: int) -> None:
        """Register ``pid`` as the page for content key ``key``. The cache
        takes its own reference, keeping the page reclaimable-but-resident
        after the owning request finishes."""
        if key in self._key_to_page:
            return  # first publisher wins; the duplicate stays private
        self._key_to_page[key] = pid
        self._page_key[pid] = key
        self._children.setdefault(key[0], set()).add(key)
        self._lru[key] = None
        self._ref[pid] += 1
        if self._ref[pid] == 1:
            # Publishers normally hold their own reference (so ref lands at
            # >= 2 here); a publish from a bare cache insert — the host-tier
            # swap-in path releases its alloc ref after publishing — makes
            # the page immediately evictable.
            self._evictable += 1

    def publish_chain(
        self, tokens: list[int], page_size: int, own_pages: list[int],
        root: int = 0,
    ) -> None:
        """Publish the full pages of ``tokens`` backed by ``own_pages``
        (the owner's physical page per block, shared or private). Walks the
        CANONICAL chain: when a key is already published, the cached page —
        not the owner's private duplicate — becomes the parent for the next
        key, so all equal prefixes share one chain. ``root`` namespaces the
        chain's first parent (multi-LoRA: identical tokens under different
        adapters produce different KV, so each adapter id gets its own
        non-positive root, disjoint from physical page ids)."""
        parent = root
        for i, pid in enumerate(own_pages):
            block = tuple(tokens[i * page_size:(i + 1) * page_size])
            key = (parent, block)
            existing = self._key_to_page.get(key)
            if existing is None:
                self.publish(key, pid)
                parent = pid
            else:
                self._lru.move_to_end(key)
                parent = existing

    def match_prefix(self, tokens: list[int], page_size: int,
                     root: int = 0) -> list[int]:
        """Longest run of published pages covering ``tokens``' leading FULL
        pages — each returned page is retained for the caller. At least one
        token is always left unmatched so the caller's prefill produces the
        next-token logits. ``root``: see ``publish_chain``."""
        usable = len(tokens) - 1
        if usable < page_size:
            return []
        pages: list[int] = []
        parent = root
        for i in range(usable // page_size):
            block = tuple(tokens[i * page_size:(i + 1) * page_size])
            pid = self.lookup((parent, block))
            if pid is None:
                break
            pages.append(pid)
            parent = pid
        for pid in pages:
            self.retain(pid)
        return pages
