"""Host-side page allocator for the paged KV cache (infer/continuous.py
``cache_mode="paged"``; device op: ops/paged_attention.py).

The device holds one pool of KV pages per layer — ``(L, n_pages, page_size,
K, D)`` — and per-slot page tables map logical block index -> physical page.
This module is the host bookkeeping around that pool:

- **Free-list allocation** with refcounts: a page may back several slots'
  tables at once (shared prefix blocks).
- **Content-addressed dedup**: every FULL page of a prompt is published
  under a progressive hash ``h_i = hash((h_{i-1}, tokens_in_page_i))``; a
  later prompt whose leading blocks hash to published pages reuses them
  (refcount bump, no prefill) — vLLM-style automatic prefix caching, no
  ``register_prefix`` call required. Only full, immutable pages are ever
  shared: a slot's partial tail page and its decode pages are private, so
  there is no copy-on-write fault path — sharing is read-only by
  construction.
- **LRU eviction**: published pages whose only reference is the hash cache
  are reclaimable; allocation pressure evicts them oldest-first.

Page 0 is a reserved sentinel: dead slots' table tails point at it and dead
decode rows write their no-op writes into it, so live writes can never
collide with a stale table entry (ops/paged_attention.write_page_tokens).

The allocator is plain Python on the host — admission policy is not a TPU
problem (same stance as the continuous engine's scheduler).
"""

from __future__ import annotations

from collections import OrderedDict, deque

__all__ = ["PageAllocator", "block_hashes"]


def block_hashes(tokens: list[int], page_size: int) -> list[int]:
    """Progressive content hashes of the FULL pages of ``tokens``. Page i's
    hash covers every token up to and including page i (chained), so equal
    hashes mean equal full prefixes — the property that makes reuse safe."""
    out: list[int] = []
    h = 0
    for start in range(0, len(tokens) - page_size + 1, page_size):
        h = hash((h, tuple(tokens[start:start + page_size])))
        out.append(h)
    return out


class PageAllocator:
    """Refcounted page pool bookkeeping with content-hash reuse."""

    def __init__(self, n_pages: int):
        if n_pages < 2:
            raise ValueError(f"need >= 2 pages (page 0 is reserved), got {n_pages}")
        self.n_pages = n_pages
        self._free: deque[int] = deque(range(1, n_pages))
        self._ref = [0] * n_pages
        self._hash_to_page: dict[int, int] = {}
        self._page_hash: dict[int, int] = {}
        # Insertion-ordered: oldest published hash evicts first.
        self._lru: OrderedDict[int, None] = OrderedDict()

    # -- capacity ------------------------------------------------------------

    @property
    def n_free(self) -> int:
        return len(self._free)

    @property
    def n_evictable(self) -> int:
        return sum(
            1 for h, p in self._hash_to_page.items() if self._ref[p] == 1
        )

    def can_alloc(self, n: int) -> bool:
        return n <= self.n_free + self.n_evictable

    # -- alloc / free --------------------------------------------------------

    def alloc(self, n: int) -> list[int]:
        """Allocate ``n`` private pages (ref 1 each), evicting LRU published
        pages if the free list runs short. Raises when truly out."""
        out: list[int] = []
        while len(out) < n:
            if self._free:
                pid = self._free.popleft()
            else:
                pid = self._evict_one()
                if pid is None:
                    # Roll back so a failed multi-page request leaks nothing.
                    for p in out:
                        self.release(p)
                    raise MemoryError(
                        f"page pool exhausted ({self.n_pages} pages, 0 evictable)"
                    )
            self._ref[pid] = 1
            out.append(pid)
        return out

    def _evict_one(self) -> int | None:
        for h in self._lru:
            pid = self._hash_to_page[h]
            if self._ref[pid] == 1:  # only the hash cache holds it
                self._unpublish(h, pid)
                return pid
        return None

    def _unpublish(self, h: int, pid: int) -> None:
        del self._hash_to_page[h]
        del self._page_hash[pid]
        self._lru.pop(h, None)
        self._ref[pid] -= 1  # the cache's own reference

    def retain(self, pid: int) -> None:
        self._ref[pid] += 1

    def release(self, pid: int) -> None:
        if pid == 0:
            return
        self._ref[pid] -= 1
        if self._ref[pid] < 0:
            raise AssertionError(f"double release of page {pid}")
        if self._ref[pid] == 0:
            self._free.append(pid)

    # -- content cache -------------------------------------------------------

    def lookup(self, h: int) -> int | None:
        """Published page for hash ``h`` (bumps its LRU recency), or None."""
        pid = self._hash_to_page.get(h)
        if pid is not None:
            self._lru.move_to_end(h)
        return pid

    def publish(self, h: int, pid: int) -> None:
        """Register ``pid`` as the page for content hash ``h``. The cache
        takes its own reference, keeping the page reclaimable-but-resident
        after the owning request finishes."""
        if h in self._hash_to_page:
            return  # first publisher wins; the duplicate stays private
        self._hash_to_page[h] = pid
        self._page_hash[pid] = h
        self._lru[h] = None
        self._ref[pid] += 1

    def match_prefix(self, tokens: list[int], page_size: int) -> list[int]:
        """Longest run of published pages covering ``tokens``' leading FULL
        pages — each returned page is retained for the caller. At least one
        token is always left unmatched so the caller's prefill produces the
        next-token logits."""
        usable = len(tokens) - 1
        if usable < page_size:
            return []
        pages: list[int] = []
        for h in block_hashes(tokens[: usable - usable % page_size], page_size):
            pid = self.lookup(h)
            if pid is None:
                break
            pages.append(pid)
        for pid in pages:
            self.retain(pid)
        return pages
