"""Speculative decoding: verify K drafted tokens per forward pass, fully
on-device.

Sequential decode reads every weight byte per generated token; a K+1-token
verify forward reads them once for up to K+1 tokens — on a
weight-bandwidth-bound decoder (BASELINE.md) accepted drafts are nearly free
MXU work. Drafts come from **prompt-lookup** (n-gram lookup à la
prompt-lookup decoding / vLLM's ngram speculator; see PAPERS.md): the most
recent earlier occurrence of the trailing ``ngram`` tokens proposes the K
tokens that followed it — no second model, no extra HBM, high acceptance on
the repetitive spans (code, quotes, retrieval-stuffed prompts) where decode
time actually goes.

**The whole generation is one XLA program**: prefill, then a
``lax.while_loop`` whose body drafts (vectorized n-gram search over the
on-device token history), verifies (one K+1-token forward with per-row
scatter cache writes), and accepts — zero host round-trips between rounds.
A host-side loop would pay dispatch + transfer latency per round (measured
~225 ms/round through this environment's remote-device transport, turning a
win into a 25x loss); the reference's serving story is one *HTTP* round-trip
per whole completion (ref ``src/distributed_inference.py:34-41``), and the
lock-step engine already runs its token loop on device — speculation follows
the same rule.

Exactness: greedy speculative output is IDENTICAL to lock-step greedy decode
in exact arithmetic — the verify step accepts exactly the longest draft
prefix the target model itself would have produced, and the first
non-matching position emits the target's own argmax (the "bonus" token).
Tested token-for-token against ``engine.Generator`` in float32 (bf16 can
legitimately flip near-ties between the chunked and 1-token schedules).

Cache note: rejected draft positions leave stale KV behind; they are masked
out (validity is ``slot <= pos[row]+q``) and the next round's K+1-slot write
(starting at ``pos+n+1 <= pos+K+1``) overwrites them, so no rollback pass is
needed.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ditl_tpu.config import ModelConfig
from ditl_tpu.data.tokenizer import Tokenizer
from ditl_tpu.infer.cache import cache_logical_axes, init_cache
from ditl_tpu.infer.engine import _next_pow2
from ditl_tpu.models import llama
from ditl_tpu.utils.logging import get_logger

logger = get_logger(__name__)

__all__ = [
    "AutoSpeculativeGenerator", "SpeculativeGenerator", "lookup_draft",
    "device_lookup_draft", "spec_sample_tokens",
]


def spec_sample_tokens(
    logits: jax.Array,  # (B, K+1, V) raw verify logits, positions pos..pos+K
    draft: jax.Array,  # (B, K) drafted tokens for positions pos+1..pos+K
    keys: jax.Array,  # (B,) PRNG keys (consumed whole; split outside)
    temps: jax.Array,  # (B,) temperature; <= 0 rows take the greedy rule
    top_ps,  # (B,) or float nucleus parameter
    *,
    top_k: int = 0,
) -> tuple[jax.Array, jax.Array]:
    """Rejection-sampling acceptance for POINT-MASS (prompt-lookup) drafts —
    speculative decoding at temperature > 0 (Leviathan et al.; q is a delta
    at the drafted token, so the acceptance probability is simply
    ``p[draft]`` and the residual on rejection is ``p`` with the draft
    entry removed, renormalized). The emitted sequence is distributed
    EXACTLY as ancestral sampling from the target model under the same
    temperature/top-k/top-p shaping (pinned by a distributional test).

    Returns ``(n_acc, next_tok)``: per-row accepted-draft count and the
    pending token for position ``pos + n_acc + 1`` — the residual sample at
    the first rejected position, or the bonus sample from position K's
    distribution when every draft is accepted. Greedy rows (``temps <= 0``)
    reduce to the exact-match rule: accept while ``draft == argmax``,
    pending token = the argmax at the first mismatch — bit-identical to the
    greedy speculative program."""
    b, k1, v = logits.shape
    k = k1 - 1
    greedy_row = temps <= 0.0
    cand = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # (B, K+1)

    # Shaped probabilities per position (flatten positions into rows so the
    # per-row temperature/top-p helpers broadcast correctly).
    from ditl_tpu.infer.sampling import shaped_logits

    flat = shaped_logits(
        logits.reshape(b * k1, v),
        jnp.repeat(temps, k1),
        top_k=top_k,
        top_p=(jnp.repeat(jnp.asarray(top_ps, jnp.float32), k1)
               if not isinstance(top_ps, (int, float)) else top_ps),
    )
    probs = jax.nn.softmax(flat, axis=-1).reshape(b, k1, v)

    split = jax.vmap(lambda kk: jax.random.split(kk, 2))(keys)
    u_key, cat_key = split[:, 0], split[:, 1]
    u = jax.vmap(lambda kk: jax.random.uniform(kk, (k,)))(u_key)  # (B, K)
    p_draft = jnp.take_along_axis(
        probs[:, :k], draft[..., None], axis=2
    )[..., 0]  # (B, K)
    acc_sampled = u < p_draft
    acc_greedy = draft == cand[:, :k]
    acc = jnp.where(greedy_row[:, None], acc_greedy, acc_sampled)
    n_acc = jnp.sum(jnp.cumprod(acc.astype(jnp.int32), axis=-1), axis=-1)

    # Pending-token distribution: position n_acc's shaped probs, with the
    # rejected draft's entry removed (residual) when a rejection happened.
    p_sel = jnp.take_along_axis(probs, n_acc[:, None, None], axis=1)[:, 0]
    rejected = n_acc < k
    d_sel = jnp.take_along_axis(
        draft, jnp.clip(n_acc, 0, k - 1)[:, None], axis=1
    )[:, 0]
    vocab = jnp.arange(v, dtype=jnp.int32)
    residual = jnp.where(
        rejected[:, None] & (vocab[None, :] == d_sel[:, None]), 0.0, p_sel
    )
    # Degenerate guard (float-only; p[draft] == 1 implies acceptance a.s.):
    # fall back to the unadjusted distribution rather than sampling NaNs.
    z = jnp.sum(residual, axis=-1, keepdims=True)
    residual = jnp.where(z > 0.0, residual / jnp.maximum(z, 1e-30), p_sel)
    next_sampled = jax.vmap(
        lambda kk, row: jax.random.categorical(kk, jnp.log(row + 1e-38))
    )(cat_key, residual).astype(jnp.int32)
    next_greedy = jnp.take_along_axis(cand, n_acc[:, None], axis=1)[:, 0]
    return n_acc, jnp.where(greedy_row, next_greedy, next_sampled)


def _emit_rows(buf: jax.Array, chunk: jax.Array, idx: jax.Array, count: jax.Array):
    """Write the first ``count[b]`` entries of ``chunk`` (B, S, ...) into
    ``buf`` (B, T, ...) at per-row offsets ``idx`` (B,) — trailing feature
    dims broadcast (the speculative logprob buffers are (B, T, N)). Same
    gather+select formulation as infer/cache._scatter_rows (TPU scatters
    serialize; dense selects don't), with the per-row prefix length
    bound."""
    s = chunk.shape[1]
    tail = (1,) * (buf.ndim - 2)
    rel = jnp.arange(buf.shape[1], dtype=jnp.int32)[None, :] - idx[:, None]
    in_chunk = (rel >= 0) & (rel < jnp.minimum(count, s)[:, None])
    gathered = jnp.take_along_axis(
        chunk.astype(buf.dtype),
        jnp.clip(rel, 0, s - 1).reshape(rel.shape + tail),
        axis=1,
    )
    return jnp.where(in_chunk.reshape(in_chunk.shape + tail), gathered, buf)


def lookup_draft(context: list[int], k: int, ngram: int,
                 min_ngram: int | None = None) -> list[int]:
    """Host reference implementation of prompt-lookup drafting (the device
    version below must match it — tests/test_speculative.py): find the most
    recent earlier occurrence of the trailing ``ngram`` of ``context`` and
    return the ``k`` tokens that followed it, 0-padded when no match or the
    history runs out. With ``min_ngram < ngram``, BACKS OFF to shorter
    n-grams when the longer one has no earlier occurrence — a 1-gram floor
    is a "most recent successor" bigram predictor, which keeps drafting on
    merely statistically repetitive text where exact long n-grams are
    rare."""
    min_n = ngram if min_ngram is None else min_ngram
    n = len(context)
    for level in range(ngram, min_n - 1, -1):
        draft: list[int] = []
        if n > level:
            tail = context[n - level:]
            fallback: list[int] | None = None
            for start in range(n - level - 1, -1, -1):
                if context[start:start + level] == tail:
                    follow = list(context[start + level: start + level + k])
                    if len(follow) == k:  # prefer a full continuation
                        draft = follow
                        break
                    if fallback is None:
                        fallback = follow
            if not draft and fallback is not None:
                draft = fallback
        if draft:
            return (draft + [0] * (k - len(draft)))[:k]
    return [0] * k


def _device_lookup_level(
    tokens: jax.Array,  # (B, T) token history buffer
    ctx_len: jax.Array,  # (B,) valid length per row
    *,
    k: int,
    ngram: int,
) -> tuple[jax.Array, jax.Array]:
    """One n-gram level of the device lookup: ((B, k) draft, (B,) found)."""
    b, t = tokens.shape
    # Trailing ngram per row: tokens[ctx_len-ngram : ctx_len].
    tail_idx = ctx_len[:, None] - ngram + jnp.arange(ngram)  # (B, ngram)
    tail = jnp.take_along_axis(tokens, jnp.clip(tail_idx, 0, t - 1), axis=1)
    # Candidate window starts i: tokens[i : i+ngram] == tail, i strictly
    # before the trailing occurrence itself. Built from ngram STATIC slices
    # (shifted compares), not a (B, W, ngram) gather — TPU lowers computed-
    # index gathers poorly, and this runs inside every decode round.
    w = t - ngram
    starts = jnp.arange(w, dtype=jnp.int32)  # (W,)
    eq = jnp.ones((b, w), bool)
    for j in range(ngram):
        eq &= tokens[:, j: j + w] == tail[:, j][:, None]
    valid = (starts[None, :] < (ctx_len - ngram)[:, None]) & (
        ctx_len[:, None] > ngram
    )
    hit = eq & valid
    # Prefer the most recent match whose k-token continuation fits inside the
    # context (a tail-adjacent match drafts mostly padding — e.g. a constant
    # token would cap acceptance at 1/round); fall back to the most recent.
    hit_full = hit & ((starts[None, :] + ngram + k) <= ctx_len[:, None])
    best_any = jnp.max(jnp.where(hit, starts[None, :], -1), axis=-1)  # (B,)
    best_full = jnp.max(jnp.where(hit_full, starts[None, :], -1), axis=-1)
    best = jnp.where(best_full >= 0, best_full, best_any)
    found = best >= 0
    src = (best + ngram)[:, None] + jnp.arange(k, dtype=jnp.int32)[None, :]
    draft = jnp.take_along_axis(tokens, jnp.clip(src, 0, t - 1), axis=1)
    in_ctx = src < ctx_len[:, None]
    return jnp.where(found[:, None] & in_ctx, draft, 0).astype(jnp.int32), found


def device_lookup_draft(
    tokens: jax.Array,  # (B, T) token history buffer
    ctx_len: jax.Array,  # (B,) valid length per row
    *,
    k: int,
    ngram: int,
    min_ngram: int | None = None,
) -> jax.Array:
    """Vectorized on-device prompt-lookup with n-gram BACKOFF: per row, the
    longest n-gram level (``ngram`` down to ``min_ngram``) with an earlier
    occurrence supplies the draft. O(T·ngram·levels) compares per row — VPU
    noise next to the verify forward. Matches ``lookup_draft``."""
    min_n = ngram if min_ngram is None else min_ngram
    draft = jnp.zeros((tokens.shape[0], k), jnp.int32)
    taken = jnp.zeros((tokens.shape[0],), bool)
    for level in range(ngram, min_n - 1, -1):
        d, f = _device_lookup_level(tokens, ctx_len, k=k, ngram=level)
        use = f & ~taken
        draft = jnp.where(use[:, None], d, draft)
        taken = taken | f
    return draft


class SpeculativeGenerator:
    """Greedy batch generation with on-device prompt-lookup speculation.

    Drop-in for ``engine.Generator`` restricted to greedy decoding
    (temperature 0) — the rejection-sampling extension for temperature > 0
    changes acceptance from exact-match to probability-ratio and is out of
    scope here."""

    def __init__(
        self,
        params: llama.Params,
        model_cfg: ModelConfig,
        tokenizer: Tokenizer,
        *,
        k: int = 8,
        ngram: int = 3,
        min_ngram: int = 1,
        rounds_per_check: int = 8,
        mesh=None,
        rules=None,
    ):
        if k < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        if ngram < 1:
            raise ValueError(f"ngram must be >= 1, got {ngram}")
        if not (1 <= min_ngram <= ngram):
            raise ValueError(
                f"min_ngram must be in [1, ngram], got {min_ngram}"
            )
        if rounds_per_check < 1:
            raise ValueError(f"rounds_per_check must be >= 1, got {rounds_per_check}")
        self.rounds_per_check = rounds_per_check
        self.params = params
        self.cfg = model_cfg
        self.tokenizer = tokenizer
        self.k = k
        self.ngram = ngram
        self.min_ngram = min_ngram
        # Per-ROW tokens per verify forward of the latest call (None before
        # the first): the number that must clear the verify/decode step-cost
        # ratio for speculation to win. Per-row, not batch-aggregate — plain
        # decode also produces one token per row per forward, so the
        # breakeven ratio is batch-size-independent.
        self.last_acceptance: float | None = None
        self.last_rounds: int = 0
        self.mesh = mesh
        self.rules = rules
        # LRU-bounded: the compile key includes client-controlled max_new
        # (same rationale as engine.Generator's cache — unbounded would be
        # an unbounded memory leak on a public server).
        import collections

        self._compiled: collections.OrderedDict = collections.OrderedDict()
        self._compile_cache_size = 32

    # -- the one compiled program --------------------------------------------

    def _build(self, batch: int, prompt_len: int, max_new: int):
        cfg, mesh, rules, k, ngram = self.cfg, self.mesh, self.rules, self.k, self.ngram
        min_ngram = self.min_ngram
        rounds_per_check = max(1, min(self.rounds_per_check, max_new))
        max_len = prompt_len + max_new + k + 1  # KV slots incl. overshoot slack
        if max_len > cfg.max_seq_len:
            raise ValueError(
                f"prompt {prompt_len} + max_new {max_new} + k {k} exceeds "
                f"model max_seq_len {cfg.max_seq_len}"
            )
        t_buf = prompt_len + max_new + 1  # token history: prompt + first + out
        pad_id = jnp.int32(self.tokenizer.pad_id)
        eos_id = jnp.int32(self.tokenizer.eos_id)
        slots = jnp.arange(max_len, dtype=jnp.int32)
        q_idx = jnp.arange(k + 1, dtype=jnp.int32)
        rows = jnp.arange(batch, dtype=jnp.int32)[:, None]

        def shard_cache(cache):
            if mesh is None:
                return cache
            from ditl_tpu.parallel.sharding import named_sharding_tree

            return jax.lax.with_sharding_constraint(
                cache, named_sharding_tree(mesh, cache_logical_axes(cfg), rules)
            )

        def run(params, input_ids, lengths, n_real):
            # ---- prefill ----
            cache = shard_cache(init_cache(cfg, batch, max_len))
            p_pos = jnp.arange(prompt_len, dtype=jnp.int32)
            # Empty-cache prefill = causal self-attention: flash-kernel path
            # (validity via segment ids), same as the lock-step engine.
            seg = (p_pos[None, :] < lengths[:, None]).astype(jnp.int32)
            logits, cache = llama.forward(
                params, input_ids, cfg,
                positions=jnp.broadcast_to(p_pos, (batch, prompt_len)),
                segment_ids=seg, mesh=mesh, rules=rules,
                cache=cache, cache_index=jnp.int32(0), prefill_causal=True,
            )
            first = jnp.argmax(
                jnp.take_along_axis(logits, (lengths - 1)[:, None, None], axis=1)[:, 0],
                axis=-1,
            ).astype(jnp.int32)
            # Pad rows (batch bucketing) start DONE: they would otherwise
            # decode to the full budget, inflating the round count that the
            # acceptance metric divides by.
            is_pad_row = jnp.arange(batch, dtype=jnp.int32) >= n_real

            tokens_buf = jnp.zeros((batch, t_buf), jnp.int32)
            tokens_buf = jax.lax.dynamic_update_slice(
                tokens_buf, input_ids, (0, 0)
            )
            done0 = (first == eos_id) | is_pad_row
            tokens_buf = tokens_buf.at[rows[:, 0], lengths].set(
                jnp.where(done0, 0, first)
            )
            out_buf = jnp.full((batch, max_new), pad_id, jnp.int32)
            out_buf = out_buf.at[:, 0].set(jnp.where(done0, pad_id, first))
            n_out = jnp.where(done0, 0, 1)
            ctx_len = lengths + n_out
            state = dict(
                cache=cache,
                tokens=tokens_buf,
                out=out_buf,
                cur=jnp.where(done0, pad_id, first),
                pos=lengths,  # KV depth; cur's KV is written next round
                ctx_len=ctx_len,
                n_out=n_out,
                done=done0 | (n_out >= max_new),
                rounds=jnp.int32(0),
            )

            # ---- speculative rounds, all on device ----
            def cond(s):
                return ~jnp.all(s["done"])

            def body(s):
                draft = device_lookup_draft(
                    s["tokens"], s["ctx_len"], k=k, ngram=ngram,
                    min_ngram=min_ngram,
                )  # (B, k)
                tokens_in = jnp.concatenate([s["cur"][:, None], draft], axis=1)
                positions = s["pos"][:, None] + q_idx[None, :]
                mask = slots[None, None, :] <= positions[:, :, None]
                logits, cache = llama.forward(
                    params, tokens_in, cfg,
                    positions=positions, mesh=mesh, rules=rules,
                    cache=s["cache"], cache_index=s["pos"], attn_mask=mask,
                )
                cand = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # (B, K+1)
                eq = tokens_in[:, 1:] == cand[:, :k]
                n_acc = jnp.sum(
                    jnp.cumprod(eq.astype(jnp.int32), axis=-1), axis=-1
                )  # (B,)

                # Emit the accepted prefix + bonus, truncated at EOS/budget.
                in_span = q_idx[None, :] <= n_acc[:, None]
                is_eos = cand == eos_id
                eos_before = (jnp.cumsum(is_eos, axis=1) - is_eos.astype(jnp.int32)) > 0
                budget_ok = (s["n_out"][:, None] + q_idx[None, :]) < max_new
                emit = (
                    in_span & ~is_eos & ~eos_before & budget_ok
                    & ~s["done"][:, None]
                )
                e = jnp.sum(emit, axis=1)  # emitted this round (B,)
                hit_eos = jnp.any(in_span & is_eos & ~eos_before, axis=1)

                # Emitted tokens are a per-row prefix of cand: dense
                # select-writes, no TPU scatter.
                out = _emit_rows(s["out"], cand, s["n_out"], e)
                tokens = _emit_rows(s["tokens"], cand, s["ctx_len"], e)

                n_out = s["n_out"] + e
                done = s["done"] | hit_eos | (n_out >= max_new)
                take = n_acc + 1
                pos = jnp.where(
                    s["done"], s["pos"],
                    jnp.minimum(s["pos"] + take, max_len - k - 2),
                )
                cur = jnp.where(
                    done, pad_id, jnp.take_along_axis(cand, n_acc[:, None], 1)[:, 0]
                )
                return dict(
                    cache=cache, tokens=tokens, out=out, cur=cur, pos=pos,
                    ctx_len=s["ctx_len"] + e, n_out=n_out, done=done,
                    # Count only rounds where some row was still live: the
                    # chunked while-loop runs whole R-round chunks, and
                    # phantom tail rounds would deflate measured acceptance.
                    rounds=s["rounds"]
                    + jnp.any(~s["done"]).astype(jnp.int32),
                )

            # Chunked loop: R rounds per while iteration. A bare while_loop
            # costs ~4.5 ms/iteration extra on this chip (no cross-iteration
            # pipelining with an unknown trip count); scanning R rounds per
            # check amortizes that to noise. Rows that finish mid-chunk
            # no-op (emission masked, pos frozen) for <= R-1 wasted rounds.
            def chunk(s):
                def sbody(c, _):
                    return body(c), None
                s, _ = jax.lax.scan(sbody, s, None, length=rounds_per_check)
                return s

            state = jax.lax.while_loop(cond, chunk, state)
            return state["out"], state["rounds"], state["n_out"]

        logger.info(
            "compiling speculative program: batch=%d prompt_len=%d max_new=%d k=%d",
            batch, prompt_len, max_new, k,
        )
        return jax.jit(run)

    # -- public surface -------------------------------------------------------

    def generate_tokens(
        self, token_lists: list[list[int]], max_new_tokens: int = 64
    ) -> list[list[int]]:
        """Greedy speculative decode; token-id prompts in, EOS-trimmed
        generated ids out. Token-identical to ``Generator.generate_tokens``
        at temperature 0 (exact arithmetic)."""
        n = len(token_lists)
        if n == 0:
            return []
        tok = self.tokenizer
        token_lists = [t if t else [tok.bos_id] for t in token_lists]
        batch = _next_pow2(n, floor=1)
        prompt_len = _next_pow2(max(len(t) for t in token_lists))
        ids = np.full((batch, prompt_len), tok.pad_id, np.int32)
        lengths = np.ones((batch,), np.int32)
        for i, toks in enumerate(token_lists):
            ids[i, : len(toks)] = toks
            lengths[i] = len(toks)

        from ditl_tpu.infer.engine import lru_program

        key = (batch, prompt_len, max_new_tokens)
        program = lru_program(
            self._compiled, key,
            lambda: self._build(batch, prompt_len, max_new_tokens),
            bound=self._compile_cache_size,
        )
        out, rounds, n_out = program(
            self.params, jnp.asarray(ids), jnp.asarray(lengths), jnp.int32(n)
        )
        out = np.asarray(jax.device_get(out))
        rounds = int(jax.device_get(rounds))
        self.last_rounds = rounds
        self.last_acceptance = None
        if rounds:
            total = int(np.asarray(jax.device_get(n_out))[:n].sum())
            self.last_acceptance = total / rounds / n
            logger.info(
                "speculative decode: %d tokens, %d rows, %d rounds "
                "(%.2f tokens/forward/row)",
                total, n, rounds, self.last_acceptance,
            )
        results = []
        for i in range(n):
            trimmed = []
            for t in out[i].tolist():
                if t == tok.eos_id or t == tok.pad_id:
                    break
                trimmed.append(t)
            results.append(trimmed)
        return results

    def generate(self, prompts: list[str], max_new_tokens: int = 64) -> list[str]:
        return _generate_text(self, prompts, max_new_tokens)


def _generate_text(gen, prompts: list[str], max_new_tokens: int) -> list[str]:
    """Shared text round-trip (BOS + encode -> generate_tokens -> decode)."""
    encoded = [
        [gen.tokenizer.bos_id] + gen.tokenizer.encode(p) for p in prompts
    ]
    return [
        gen.tokenizer.decode(t)
        for t in gen.generate_tokens(encoded, max_new_tokens)
    ]


class AutoSpeculativeGenerator:
    """Per-request speculation auto-enable driven by MEASURED acceptance.

    Speculation pays only when accepted tokens per verify forward PER ROW
    exceed the verify/decode step-cost ratio (~2-2.5x on v5e for the bench
    model, BASELINE.md) — and acceptance is a property of the WORKLOAD (repetitive
    continuations accept; high-entropy text does not). This wrapper serves
    each request speculatively while the exponentially-averaged acceptance
    clears ``threshold``, falls back to the plain lock-step ``Generator``
    when it does not, and re-probes with a speculative request every
    ``probe_every`` requests so a workload shift back to repetitive text is
    re-detected. Greedy only (the speculative path's restriction)."""

    def __init__(
        self,
        params: llama.Params,
        model_cfg: ModelConfig,
        tokenizer: Tokenizer,
        *,
        threshold: float = 2.5,
        probe_every: int = 16,
        ema: float = 0.7,
        mesh=None,
        rules=None,
        plain=None,
        **spec_kw,
    ):
        from ditl_tpu.infer.engine import Generator

        if probe_every < 1:
            raise ValueError(f"probe_every must be >= 1, got {probe_every}")
        if not (0.0 <= ema < 1.0):
            raise ValueError(f"ema must be in [0, 1), got {ema}")
        self.spec = SpeculativeGenerator(
            params, model_cfg, tokenizer, mesh=mesh, rules=rules, **spec_kw
        )
        # Reuse the caller's Generator when given (the server already holds
        # one): a second instance would keep a second 32-program compile
        # cache for the same shapes.
        self.plain = plain if plain is not None else Generator(
            params, model_cfg, tokenizer, mesh=mesh, rules=rules
        )
        self.tokenizer = tokenizer
        self.threshold = threshold
        self.probe_every = probe_every
        self._ema_w = ema
        self.acceptance_ema: float | None = None
        self._n_requests = 0

    @property
    def speculating(self) -> bool:
        """Would the next (non-probe) request use the speculative path?"""
        return (
            self.acceptance_ema is None
            or self.acceptance_ema >= self.threshold
        )

    def generate_tokens(
        self, token_lists: list[list[int]], max_new_tokens: int = 64
    ) -> list[list[int]]:
        probe = self._n_requests % self.probe_every == 0
        self._n_requests += 1
        if self.speculating or probe:
            out = self.spec.generate_tokens(token_lists, max_new_tokens)
            acc = self.spec.last_acceptance
            if acc is not None:
                self.acceptance_ema = (
                    acc if self.acceptance_ema is None
                    else self._ema_w * self.acceptance_ema
                    + (1.0 - self._ema_w) * acc
                )
            return out
        from ditl_tpu.infer.engine import GenerateConfig

        return self.plain.generate_tokens(
            token_lists, GenerateConfig(max_new_tokens=max_new_tokens)
        )

    def generate(self, prompts: list[str], max_new_tokens: int = 64) -> list[str]:
        return _generate_text(self, prompts, max_new_tokens)
