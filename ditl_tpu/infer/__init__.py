"""TPU-native inference/serving subsystem.

The reference performs "distributed inference" by HTTP-calling a remote 70B
model through LiteLLM (ref ``src/distributed_inference.py:34-41``) — it never
runs a model locally. This package is the local, TPU-native half of that
story: KV-cache incremental decoding over the sharded Llama/MoE models
(engine.py), jit-compiled sampling (sampling.py), and an OpenAI-compatible
HTTP server (server.py) that the existing L4 client (client/llm.py) — or any
LiteLLM user — can point at, closing the loop entirely on-TPU.
"""

from ditl_tpu.infer.cache import cache_logical_axes, init_cache
from ditl_tpu.infer.engine import GenerateConfig, Generator
from ditl_tpu.infer.sampling import sample_logits

__all__ = [
    "GenerateConfig",
    "Generator",
    "cache_logical_axes",
    "init_cache",
    "sample_logits",
]
