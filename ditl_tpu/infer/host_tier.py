"""Host-RAM prefix-cache tier (ISSUE 13): the level below the HBM page
pool.

The paged KV cache (infer/paged_cache.py) makes the shared-prefix working
set content-addressable, but its capacity is HBM pages — the difference
between caching one system prompt and caching a million users'
conversation histories. This module is the next level of the hierarchy:
when the allocator LRU-evicts a published page, the engine spills its KV
bytes here (one batched ``jax.device_get`` per tick, off the ``@hot_path``
— infer/continuous.py ``_process_spills``); when a later admission's
prompt misses in HBM but its block keys match host entries, the engine
swaps the pages back in (``device_put`` + republish) instead of
re-prefilling them.

**Keying — the no-hash-collision invariant across the tier boundary.**
The allocator's content keys chain through *physical* page ids
(``(parent_pid, exact_tokens)``), which are recycled the moment a page is
reclaimed — a spilled entry keyed by a physical id would verify against
whatever content the recycled id holds next (silent cross-request KV
corruption, the exact failure the chain keys exist to prevent). The tier
therefore interns its own **chain nodes**: ``(parent_node_id,
exact_tokens) -> node_id`` where node ids are monotonically assigned and
NEVER recycled. Equal node ids mean equal full prefixes by the same
induction the allocator's keys give — exact token comparison at every
link, zero reliance on hash collision resistance — and the identity
survives any number of HBM evict/republish cycles because nothing on the
host side is ever renumbered. Roots are the allocator's non-positive
adapter roots (``-adapter_id``), so multi-LoRA isolation carries over
unchanged.

**Integrity.** Every stored page carries a crc32 over its KV bytes,
verified at swap-in: a corrupt entry (bit rot, a torn write, the
``kvtier.swap_in:corrupt`` chaos drill) is detected, dropped, and
counted — never served. Corruption is a per-entry event; the rest of the
tier stays usable.

**Capacity.** ``capacity_bytes`` caps resident KV bytes; inserting past
the cap evicts least-recently-used entries first (and an entry larger
than the whole cap is refused, counted as dropped). Unlike the HBM
allocator there is NO eviction cascade: node ids are never recycled, so a
child entry whose parent entry was evicted is still exactly correct — and
still useful whenever the parent's pages are matched in HBM. Nodes
without entries, children, or pins are pruned so the chain map stays
bounded by live structure.

Plain Python + numpy on the host, importable without jax — admission
policy is not a TPU problem (the same stance as the allocator), and the
unit tests drive every eviction/corruption edge without a device.
"""

from __future__ import annotations

import zlib
from collections import OrderedDict
from dataclasses import dataclass, field

import numpy as np

__all__ = ["HostTier", "HostTierEntry"]

TokenBlock = tuple[int, ...]


@dataclass
class HostTierEntry:
    """One spilled page: per-pool KV bytes + shape/dtype to rebuild the
    arrays, and a crc32 per part verified at swap-in. ``data`` holds
    bytearrays (not bytes) so the corruption drill can flip a bit in
    place, exactly like real rot would."""

    node_id: int
    nbytes: int
    # (bytes, dtype OBJECT, shape) per pool part: the dtype object round-
    # trips extension dtypes (ml_dtypes bfloat16's ``.str`` is an opaque
    # '<V2' that np.dtype() cannot rebuild — a string key would silently
    # corrupt bf16 pools); nothing here ever leaves the process.
    parts: dict[str, tuple[bytearray, np.dtype, tuple[int, ...]]] = field(
        default_factory=dict
    )
    crcs: dict[str, int] = field(default_factory=dict)

    def arrays(self) -> dict[str, np.ndarray]:
        return {
            name: np.frombuffer(buf, dtype=dt).reshape(shape)
            for name, (buf, dt, shape) in self.parts.items()
        }


class HostTier:
    """Size-capped, chain-keyed host store for spilled KV pages."""

    def __init__(self, capacity_bytes: int):
        if capacity_bytes < 1:
            raise ValueError(
                f"capacity_bytes must be >= 1, got {capacity_bytes}"
            )
        self.capacity_bytes = int(capacity_bytes)
        self.bytes_used = 0
        # Chain nodes: (parent_node_id, tokens) -> node_id. Parent ids are
        # prior node ids (> 0) or allocator adapter roots (<= 0); node ids
        # count up from 1 and are never reused.
        self._nodes: dict[tuple[int, TokenBlock], int] = {}
        self._node_key: dict[int, tuple[int, TokenBlock]] = {}
        self._children: dict[int, set[int]] = {}  # node id -> child node ids
        self._next_id = 1
        # Entries keyed by node id, insertion/touch-ordered (LRU evicts the
        # front).
        self._entries: OrderedDict[int, HostTierEntry] = OrderedDict()
        # Lifetime accounting (mirrored into ServingMetrics by the engine).
        self.spilled = 0  # entries stored
        self.swapped_in = 0  # entries served back to HBM
        self.dropped = 0  # refused at the cap / oversized
        self.evictions = 0  # LRU reclaims under the cap
        self.corrupt_dropped = 0  # crc mismatches detected at fetch

    # -- chain nodes ---------------------------------------------------------

    def intern(self, root: int, blocks: list[TokenBlock]) -> int:
        """Node id for the chain ``root -> blocks[0] -> ... -> blocks[-1]``,
        creating missing nodes. ``root`` must be a non-positive allocator
        adapter root so roots and node ids can never collide."""
        if root > 0:
            raise ValueError(f"chain root must be <= 0, got {root}")
        if not blocks:
            raise ValueError("a chain needs at least one block")
        parent = root
        for block in blocks:
            key = (parent, tuple(block))
            nid = self._nodes.get(key)
            if nid is None:
                nid = self._next_id
                self._next_id += 1
                self._nodes[key] = nid
                self._node_key[nid] = key
                if parent > 0:
                    self._children.setdefault(parent, set()).add(nid)
            parent = nid
        return parent

    def walk(self, root: int, blocks: list[TokenBlock]) -> list[int | None]:
        """Lookup-only chain walk: node id per block, stopping (None-filled)
        at the first link no spill ever interned."""
        out: list[int | None] = []
        parent: int | None = root
        for block in blocks:
            nid = (
                self._nodes.get((parent, tuple(block)))
                if parent is not None else None
            )
            out.append(nid)
            parent = nid
        return out

    def _prune(self, nid: int) -> None:
        """Drop chain nodes that anchor nothing (no entry, no children),
        walking toward the root — keeps the chain map bounded by live
        structure instead of by everything ever spilled."""
        while nid > 0 and nid not in self._entries \
                and not self._children.get(nid):
            key = self._node_key.pop(nid, None)
            if key is None:
                return
            del self._nodes[key]
            self._children.pop(nid, None)
            parent = key[0]
            kids = self._children.get(parent)
            if kids is not None:
                kids.discard(nid)
            nid = parent

    # -- entries -------------------------------------------------------------

    def has_entry(self, node_id: int) -> bool:
        return node_id in self._entries

    @property
    def n_entries(self) -> int:
        return len(self._entries)

    def put(self, node_id: int, arrays: dict[str, np.ndarray]) -> bool:
        """Store one page's KV under ``node_id``. Evicts LRU entries to
        fit; refuses (False, counted dropped) when the page alone exceeds
        the cap. Re-putting a resident node is a no-op touch. A node id
        that no longer exists is also a refusal, not an error: a pending
        spill's node can be PRUNED before its put runs (its descendants'
        entries were evicted/dropped in the same batch, and pruning walks
        up through entry-less ancestors) — spills are best-effort by
        contract and must never raise into the engine driver."""
        if node_id not in self._node_key:
            self.dropped += 1
            return False
        if node_id in self._entries:
            self._entries.move_to_end(node_id)
            return True
        entry = HostTierEntry(node_id=node_id, nbytes=0)
        for name, arr in arrays.items():
            arr = np.ascontiguousarray(arr)
            buf = bytearray(arr.tobytes())
            entry.parts[name] = (buf, arr.dtype, arr.shape)
            entry.crcs[name] = zlib.crc32(buf)
            entry.nbytes += len(buf)
        if entry.nbytes > self.capacity_bytes:
            self.dropped += 1
            return False
        while self.bytes_used + entry.nbytes > self.capacity_bytes:
            self._evict_one()
        self._entries[node_id] = entry
        self.bytes_used += entry.nbytes
        self.spilled += 1
        return True

    def _evict_one(self) -> None:
        nid, entry = self._entries.popitem(last=False)
        self.bytes_used -= entry.nbytes
        self.evictions += 1
        self._prune(nid)

    def _drop(self, node_id: int) -> None:
        entry = self._entries.pop(node_id, None)
        if entry is not None:
            self.bytes_used -= entry.nbytes
            self._prune(node_id)

    def fetch(self, node_id: int) -> dict[str, np.ndarray] | None:
        """crc-verified arrays for ``node_id`` (LRU touch), or None when
        absent or corrupt — a corrupt entry is dropped and counted, never
        served (the integrity contract the chaos drill pins)."""
        entry = self._entries.get(node_id)
        if entry is None:
            return None
        for name, (buf, _, _) in entry.parts.items():
            if zlib.crc32(buf) != entry.crcs[name]:
                self.corrupt_dropped += 1
                self._drop(node_id)
                return None
        self._entries.move_to_end(node_id)
        self.swapped_in += 1
        return entry.arrays()

    def corrupt(self, node_id: int, bit: int = 0) -> bool:
        """Flip one bit of a resident entry IN PLACE (the
        ``kvtier.swap_in:corrupt`` chaos action and the bit-rot drills) —
        the next fetch must detect and drop it."""
        entry = self._entries.get(node_id)
        if entry is None:
            return False
        name = next(iter(entry.parts))
        buf = entry.parts[name][0]
        buf[(bit // 8) % len(buf)] ^= 1 << (bit % 8)
        return True

    def stats(self) -> dict:
        return {
            "capacity_bytes": self.capacity_bytes,
            "bytes_used": self.bytes_used,
            "entries": len(self._entries),
            "nodes": len(self._nodes),
            "spilled": self.spilled,
            "swapped_in": self.swapped_in,
            "dropped": self.dropped,
            "evictions": self.evictions,
            "corrupt_dropped": self.corrupt_dropped,
        }
