"""Token sampling (L1) — jit-compatible, static-config branching.

Greedy, temperature, top-k, and nucleus (top-p) sampling over a (B, V) logits
slab. All control flow branches on *static* Python config values, so each
``GenerateConfig`` compiles to a straight-line XLA program — no data-dependent
Python control flow inside jit (SURVEY.md §7 design stance).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["sample_logits", "shaped_logits"]


def shaped_logits(
    logits: jax.Array,
    temperature,
    *,
    top_k: int = 0,
    top_p=1.0,
) -> jax.Array:
    """(B, V) raw logits -> shaped logits under per-row temperature / top-k /
    top-p — exactly the distribution ``sample_logits``' traced-temperature
    path draws from. Exposed for speculative rejection sampling, which needs
    the PROBABILITIES (acceptance = p[draft]) rather than one draw. Rows
    with ``temperature <= 0`` get the clamped 1e-6 scale (callers handle
    the greedy limit explicitly)."""
    logits = logits.astype(jnp.float32)
    temperature = jnp.asarray(temperature, jnp.float32)
    scaled = logits / jnp.maximum(temperature, 1e-6)[..., None]
    if top_k > 0:
        scaled = _apply_top_k(scaled, min(top_k, logits.shape[-1]))
    per_row_p = not isinstance(top_p, (int, float))
    if per_row_p or top_p < 1.0:
        scaled = _apply_top_p(scaled, top_p)
    return scaled


def _apply_top_k(logits: jax.Array, k: int) -> jax.Array:
    """Mask everything below the k-th largest logit (per row)."""
    kth = jax.lax.top_k(logits, k)[0][..., -1:]  # (B, 1)
    return jnp.where(logits < kth, -jnp.inf, logits)


def _apply_top_p(logits: jax.Array, p) -> jax.Array:
    """Nucleus sampling: keep the smallest prefix of the sorted distribution
    whose cumulative probability exceeds ``p`` (always keeping the top token).
    ``p`` may be a float or a per-row (B,) array."""
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]  # descending
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    if not isinstance(p, (int, float)):
        # Rows with p >= 1 mean "disabled": use +inf so float cumsum error
        # can never mask extreme-tail tokens on those rows.
        p = jnp.asarray(p, jnp.float32)[..., None]
        p = jnp.where(p >= 1.0, jnp.inf, p)
    # Token i is kept if the cumulative mass *before* it is still < p.
    keep_sorted = (cum - probs) < p
    # Threshold = smallest kept logit; everything below it is masked.
    threshold = jnp.min(
        jnp.where(keep_sorted, sorted_logits, jnp.inf), axis=-1, keepdims=True
    )
    return jnp.where(logits < threshold, -jnp.inf, logits)


def sample_logits(
    logits: jax.Array,
    rng: jax.Array,
    *,
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 1.0,
) -> jax.Array:
    """(B, V) float logits -> (B,) int32 token ids.

    ``temperature`` may be a static float (``0`` compiles to pure greedy
    argmax) or a traced (B,) array — per-row temperatures for continuous
    batching, where rows with ``temperature <= 0`` are greedy and the rest
    sample; both paths are computed and selected with ``where`` (static
    shapes, no data-dependent control flow).
    """
    logits = logits.astype(jnp.float32)
    if isinstance(temperature, (int, float)):
        if temperature == 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        logits = logits / temperature
        if top_k > 0:
            logits = _apply_top_k(logits, min(top_k, logits.shape[-1]))
        if top_p < 1.0:
            logits = _apply_top_p(logits, top_p)
        return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)

    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = shaped_logits(logits, temperature, top_k=top_k, top_p=top_p)
    temperature = jnp.asarray(temperature, jnp.float32)
    if rng.ndim >= 1:  # per-row keys (continuous batching: per-request seeds)
        sampled = jax.vmap(
            lambda k, row: jax.random.categorical(k, row).astype(jnp.int32)
        )(rng, scaled)
    else:
        sampled = jax.random.categorical(rng, scaled, axis=-1).astype(jnp.int32)
    return jnp.where(temperature <= 0.0, greedy, sampled)
