"""KV cache for incremental decoding (L1).

Layout mirrors the model's scanned-layer convention (models/llama.py): all
layers stacked on a leading ``layers`` axis so the decode forward scans
``(layer_params, layer_cache)`` together — one layer's HLO compiled once.

Shapes: ``k``/``v`` are ``(L, B, Smax, K, D)`` in the model's compute dtype
(bf16 on TPU — cache reads are the HBM-bandwidth cost of decoding, so half
the bytes is double the decode speed). With ``ModelConfig.kv_cache_dtype ==
"int8"`` the cache stores int8 values plus per-(layer, row, slot, head)
float32 scales — 8.25 bits/value vs bf16's 16, paying off exactly where
decode is cache-bandwidth-bound (long contexts, many slots). Quantization is
symmetric per-head absmax: one scale per (b, slot, kv_head) covering the D
lane values written together, so dequantization is a fused multiply on the
cache read.

Sharding: batch over the data/fsdp axes, KV heads over the tensor axis — the
same rule table as training (parallel/sharding.py), so a TP-sharded model
decodes with a TP-sharded cache and no resharding.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ditl_tpu.config import ModelConfig

__all__ = ["init_cache", "cache_logical_axes", "write_kv", "read_kv", "scatter_tail"]


def init_cache(cfg: ModelConfig, batch_size: int, max_len: int) -> dict:
    """Zero-filled cache pytree for ``batch_size`` sequences of ≤ ``max_len``."""
    shape = (cfg.num_layers, batch_size, max_len, cfg.num_kv_heads, cfg.head_dim)
    if cfg.kv_cache_dtype == "int8":
        # Distinct scale arrays: sharing one buffer between both leaves breaks
        # donation (the same buffer would be donated twice per program call).
        return {
            "k": jnp.zeros(shape, jnp.int8),
            "v": jnp.zeros(shape, jnp.int8),
            "k_scale": jnp.ones(shape[:-1], jnp.float32),
            "v_scale": jnp.ones(shape[:-1], jnp.float32),
        }
    if cfg.kv_cache_dtype not in ("", "model"):
        raise ValueError(
            f"unknown kv_cache_dtype {cfg.kv_cache_dtype!r} ('', 'model', 'int8')"
        )
    dtype = jnp.dtype(cfg.dtype)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def cache_logical_axes(cfg: ModelConfig, *, seq_sharded: bool = False) -> dict:
    """Logical axes for the cache pytree (same table as params/activations).
    ``seq_sharded`` splits the CONTEXT dim over the ``cache_seq`` rule
    (sequence mesh axis): per-device cache memory and attention reads drop
    by the shard factor, and decode merges per-shard partial softmax over
    ICI (ops/attention._seq_sharded_decode) — long-context serving beyond
    one chip's HBM."""
    seq = "cache_seq" if seq_sharded else None
    axes = ("layers", "batch", seq, "act_kv_heads", "head_dim")
    out = {"k": axes, "v": axes}
    if cfg.kv_cache_dtype == "int8":
        out["k_scale"] = axes[:-1]
        out["v_scale"] = axes[:-1]
    return out


def _scatter_rows(cache: jax.Array, chunk: jax.Array, idx: jax.Array) -> jax.Array:
    """Write ``chunk`` (B, S, ...) into ``cache`` (B, Smax, ...) at per-row
    slot offsets ``idx`` (B,). Used by the continuous-batching and
    speculative decode paths where each sequence sits at a different depth.

    Implemented as gather + select over the whole slot axis, NOT an XLA
    scatter: TPU lowers multi-row scatters poorly (serialized updates),
    while this form is a dense vectorized rewrite of the cache — and cache
    bytes are noise next to the weight reads that bound decode."""
    s = chunk.shape[1]
    smax = cache.shape[1]
    tail = (1,) * (cache.ndim - 2)  # broadcast over trailing (K, D, ...) dims
    rel = jnp.arange(smax, dtype=jnp.int32)[None, :] - idx[:, None]  # (B, Smax)
    in_chunk = (rel >= 0) & (rel < s)
    gathered = jnp.take_along_axis(
        chunk.astype(cache.dtype),
        jnp.clip(rel, 0, s - 1).reshape(rel.shape + tail),
        axis=1,
    )
    return jnp.where(in_chunk.reshape(in_chunk.shape + tail), gathered, cache)


def scatter_tail(tail: jax.Array, chunk: jax.Array, off: jax.Array) -> jax.Array:
    """Write ``chunk`` (B, K, S, D) into the decode tail buffer ``tail``
    (B, K, T, D) at per-row column offsets ``off`` (B,) — the speculative
    verify's K+1-token write, where each slot sits at its own tail depth.
    Same dense gather+select formulation as ``_scatter_rows`` (axis moved
    to position 1; XLA fuses the transposes into the select)."""
    t = jnp.swapaxes(tail, 1, 2)  # (B, T, K, D)
    c = jnp.swapaxes(chunk, 1, 2)
    return jnp.swapaxes(_scatter_rows(t, c, off), 1, 2)


def _quantize(chunk: jax.Array) -> tuple[jax.Array, jax.Array]:
    """(B, S, K, D) -> int8 values + per-(B, S, K) float32 scales."""
    absmax = jnp.max(jnp.abs(chunk.astype(jnp.float32)), axis=-1)
    scale = jnp.where(absmax == 0.0, 1.0, absmax / 127.0)
    q = jnp.round(chunk.astype(jnp.float32) / scale[..., None])
    return jnp.clip(q, -127, 127).astype(jnp.int8), scale


def _write_one(cache: jax.Array, chunk: jax.Array, idx: jax.Array) -> jax.Array:
    if idx.ndim == 1:
        return _scatter_rows(cache, chunk, idx)
    pad = (0,) * (cache.ndim - 2)
    return jax.lax.dynamic_update_slice(
        cache, chunk.astype(cache.dtype), (0, idx) + pad
    )


def write_kv(layer_cache: dict, k: jax.Array, v: jax.Array, idx: jax.Array) -> dict:
    """Write a (B, S, K, D) K/V chunk into one layer's cache slice at slot
    ``idx`` — scalar (lock-step decode: every row at the same depth) or (B,)
    (continuous batching: per-row depths, scatter write). Quantizes on the way
    in when the cache is int8."""
    idx = jnp.asarray(idx, jnp.int32)
    out = dict(layer_cache)
    if "k_scale" in layer_cache:
        k_q, k_s = _quantize(k)
        v_q, v_s = _quantize(v)
        out["k"] = _write_one(layer_cache["k"], k_q, idx)
        out["v"] = _write_one(layer_cache["v"], v_q, idx)
        out["k_scale"] = _write_one(layer_cache["k_scale"], k_s, idx)
        out["v_scale"] = _write_one(layer_cache["v_scale"], v_s, idx)
        return out
    out["k"] = _write_one(layer_cache["k"], k, idx)
    out["v"] = _write_one(layer_cache["v"], v, idx)
    return out


def read_kv(layer_cache: dict, dtype) -> tuple[jax.Array, jax.Array]:
    """One layer's full (B, Smax, K, D) K/V in the compute dtype; dequantizes
    int8 caches (XLA fuses the convert+scale into the attention matmul's
    operand read, so the HBM traffic stays int8-sized)."""
    k, v = layer_cache["k"], layer_cache["v"]
    if "k_scale" in layer_cache:
        k = (k.astype(jnp.float32) * layer_cache["k_scale"][..., None]).astype(dtype)
        v = (v.astype(jnp.float32) * layer_cache["v_scale"][..., None]).astype(dtype)
        return k, v
    return k.astype(dtype), v.astype(dtype)
