"""KV cache for incremental decoding (L1).

Layout mirrors the model's scanned-layer convention (models/llama.py): all
layers stacked on a leading ``layers`` axis so the decode forward scans
``(layer_params, k_cache, v_cache)`` together — one layer's HLO compiled once.

Shapes: ``k``/``v`` are ``(L, B, Smax, K, D)`` in the model's compute dtype
(bf16 on TPU — cache reads are the HBM-bandwidth cost of decoding, so half
the bytes is double the decode speed). Sharding: batch over the data/fsdp
axes, KV heads over the tensor axis — the same rule table as training
(parallel/sharding.py), so a TP-sharded model decodes with a TP-sharded cache
and no resharding.
"""

from __future__ import annotations

import jax.numpy as jnp

from ditl_tpu.config import ModelConfig

__all__ = ["init_cache", "cache_logical_axes"]


def init_cache(cfg: ModelConfig, batch_size: int, max_len: int) -> dict:
    """Zero-filled cache pytree for ``batch_size`` sequences of ≤ ``max_len``."""
    shape = (cfg.num_layers, batch_size, max_len, cfg.num_kv_heads, cfg.head_dim)
    dtype = jnp.dtype(cfg.dtype)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def cache_logical_axes(cfg: ModelConfig) -> dict:
    """Logical axes for the cache pytree (same table as params/activations)."""
    axes = ("layers", "batch", None, "act_kv_heads", "head_dim")
    return {"k": axes, "v": axes}
