"""Grammar-constrained decoding (L1/L5): regex / JSON grammars compiled to
token-level DFA transition tables that run ON DEVICE as one gather per step.

The reference has no serving stack at all (its only "model" is a remote API,
ref ``src/distributed_inference.py:34-41``); guided decoding is part of this
framework's production serving surface (vLLM/outlines-class capability),
designed TPU-first:

- **All constraint work happens at compile time, on the host.** A grammar is
  compiled once into a dense ``(n_states, vocab)`` int32 transition table:
  ``table[s, t] = next state`` if token ``t`` is allowed in state ``s``, else
  ``-1``. The decode program then needs exactly one row gather per step
  (``table[state]``), a ``where`` mask into the logits, and one scalar gather
  for the state transition — static shapes, no host round-trips, no
  data-dependent control flow (SURVEY.md §7 design stance).
- **Byte-level automata.** The char-level machine operates on UTF-8 bytes
  (alphabet 256), so multi-byte characters need no special-casing in the
  token walk and the in-repo ``ByteTokenizer`` (1 byte = 1 token) is exact by
  construction. For subword tokenizers the token table is built from each
  token's decoded string (the standard outlines-style construction, exact for
  byte-level BPEs whose per-token decode concatenates).
- **Bounded-depth JSON is built directly as a DFA**, not via a regex: the
  pushdown stack is expanded into the state id (mode × container-stack
  tuple), which stays small (a few hundred states at depth 5) where the
  equivalent regex would blow up exponentially.

Pipeline: pattern -> AST -> Thompson NFA (byte-set edges) -> subset-construction
DFA over an alphabet partition (distinct byte-class equivalence, so the hot
loop is ~n_classes wide, not 256) -> numpy-vectorized token-table walk.
"""

from __future__ import annotations

import json
from dataclasses import dataclass

import numpy as np

__all__ = [
    "CompiledGrammar",
    "compile_regex",
    "compile_json",
    "compile_json_schema",
    "token_strings",
]

# ---------------------------------------------------------------------------
# Regex AST. Byte sets are 256-bit int masks (bit b set = byte b matches).
# Sharing AST nodes is safe: the NFA builder allocates fresh states per visit.
# ---------------------------------------------------------------------------

_ASCII_ALL = (1 << 128) - 1  # bytes 0..127


def _mask_of(*bs: int) -> int:
    m = 0
    for b in bs:
        m |= 1 << b
    return m


def _range_mask(lo: int, hi: int) -> int:
    return ((1 << (hi + 1)) - 1) & ~((1 << lo) - 1)


@dataclass(frozen=True)
class ByteSet:
    """One transition consuming a single byte from ``mask``."""

    mask: int


@dataclass(frozen=True)
class AnyMultibyte:
    """Any non-ASCII UTF-8 character (2-4 byte sequence).

    Slightly permissive at the byte level (overlong/surrogate encodings are
    not rejected) — it constrains structure, and every real tokenizer only
    carries valid UTF-8 anyway."""


@dataclass(frozen=True)
class Seq:
    parts: tuple


@dataclass(frozen=True)
class Alt:
    options: tuple


@dataclass(frozen=True)
class Repeat:
    """min..max repetitions of ``node``; max=None means unbounded."""

    node: object
    min: int
    max: int | None


@dataclass(frozen=True)
class OrderFree:
    """An object body admitting its property ``pairs`` in ANY order, each
    at most once, ``sep`` between consecutive pairs, pairs whose bit is in
    ``required_mask`` mandatory. Expanded in the NFA as a seen-bitmask hub
    graph — hub(S) per subset S of emitted pairs, pair i bridging
    hub(S) → hub(S | 1<<i) — so n properties cost n·2^(n-1) pair
    fragments instead of the n! permutation bodies a regex union needs
    (VERDICT r4 weak #4: the DFA this determinizes to is the minimal one;
    the ~2^n factor is inherent to order-freedom, the factorial was not)."""

    pairs: tuple  # AST nodes
    sep: object  # AST node
    required_mask: int


_CLASS_ESCAPES = {
    "d": _range_mask(0x30, 0x39),
    "w": _range_mask(0x30, 0x39) | _range_mask(0x41, 0x5A) | _range_mask(0x61, 0x7A) | _mask_of(0x5F),
    "s": _mask_of(0x20, 0x09, 0x0A, 0x0D, 0x0C, 0x0B),
}
_CHAR_ESCAPES = {"n": 0x0A, "t": 0x09, "r": 0x0D, "f": 0x0C, "v": 0x0B, "0": 0x00, "a": 0x07, "b": 0x08}


class RegexError(ValueError):
    pass


class _Parser:
    """Recursive-descent parser for the supported regex subset:
    literals, escapes (incl. ``\\xHH``, ``\\d\\w\\s`` and negations), ``.``,
    classes ``[...]`` with ranges/negation, ``|``, groups ``(...)`` (and
    non-capturing ``(?:...)``), quantifiers ``* + ? {m} {m,} {m,n}``.
    Anchored fullmatch semantics (``^``/``$`` are implicit and rejected)."""

    def __init__(self, pattern: str):
        self.p = pattern
        self.i = 0

    def error(self, msg: str):
        raise RegexError(f"{msg} at position {self.i} in regex {self.p!r}")

    def peek(self) -> str | None:
        return self.p[self.i] if self.i < len(self.p) else None

    def next(self) -> str:
        c = self.p[self.i]
        self.i += 1
        return c

    def parse(self):
        node = self._alt()
        if self.i != len(self.p):
            self.error("unexpected character")
        return node

    def _alt(self):
        options = [self._seq()]
        while self.peek() == "|":
            self.next()
            options.append(self._seq())
        return options[0] if len(options) == 1 else Alt(tuple(options))

    def _seq(self):
        parts = []
        while (c := self.peek()) is not None and c not in "|)":
            parts.append(self._quantified())
        if len(parts) == 1:
            return parts[0]
        return Seq(tuple(parts))

    def _quantified(self):
        node = self._atom()
        c = self.peek()
        if c == "*":
            self.next()
            node = Repeat(node, 0, None)
        elif c == "+":
            self.next()
            node = Repeat(node, 1, None)
        elif c == "?":
            self.next()
            node = Repeat(node, 0, 1)
        elif c == "{":
            node = self._braces(node)
        if self.peek() == "?":
            self.error("non-greedy quantifiers are meaningless for a DFA")
        return node

    def _braces(self, node):
        self.next()  # {
        start = self.i
        while self.peek() not in ("}", None):
            self.next()
        if self.peek() is None:
            self.error("unterminated {")
        body = self.p[start : self.i]
        self.next()  # }
        try:
            if "," in body:
                lo_s, hi_s = body.split(",", 1)
                lo = int(lo_s)
                hi = int(hi_s) if hi_s.strip() else None
            else:
                lo = hi = int(body)
        except ValueError:
            self.error(f"bad repetition {{{body}}}")
        if lo < 0 or (hi is not None and hi < lo):
            self.error(f"bad repetition {{{body}}}")
        return Repeat(node, lo, hi)

    def _atom(self):
        c = self.next()
        if c == "(":
            if self.peek() == "?":
                self.next()
                if self.peek() != ":":
                    self.error("only (?:...) groups are supported")
                self.next()
            node = self._alt()
            if self.peek() != ")":
                self.error("unterminated group")
            self.next()
            return node
        if c == "[":
            return self._char_class()
        if c == ".":
            # Python-re semantics: any character except newline.
            return Alt((ByteSet(_ASCII_ALL & ~_mask_of(0x0A)), AnyMultibyte()))
        if c == "\\":
            return self._escape(in_class=False)
        if c in "*+?{":
            self.error(f"quantifier {c!r} with nothing to repeat")
        if c in ")]^$":
            self.error(f"unsupported metacharacter {c!r}")
        return self._literal_char(c)

    def _literal_char(self, c: str):
        data = c.encode("utf-8")
        if len(data) == 1:
            return ByteSet(_mask_of(data[0]))
        return Seq(tuple(ByteSet(_mask_of(b)) for b in data))

    def _escape(self, in_class: bool):
        if self.peek() is None:
            self.error("dangling backslash")
        c = self.next()
        if c in _CLASS_ESCAPES:
            return ByteSet(_CLASS_ESCAPES[c])
        if c.lower() in _CLASS_ESCAPES and c.isupper():
            # Negated: ASCII complement plus any non-ASCII character.
            return Alt((ByteSet(_ASCII_ALL & ~_CLASS_ESCAPES[c.lower()]), AnyMultibyte()))
        if c == "x":
            hexs = self.p[self.i : self.i + 2]
            if len(hexs) != 2 or any(h not in "0123456789abcdefABCDEF" for h in hexs):
                self.error("\\x needs two hex digits")
            self.i += 2
            b = int(hexs, 16)
            if b > 0x7F and not in_class:
                self.error("\\x beyond ASCII outside a class is ambiguous; use the literal character")
            return ByteSet(_mask_of(b))
        if c in _CHAR_ESCAPES and c != "b":
            return ByteSet(_mask_of(_CHAR_ESCAPES[c]))
        if c == "b" and in_class:
            return ByteSet(_mask_of(0x08))
        if c == "b":
            self.error("word-boundary \\b is not a DFA-expressible single-byte constraint")
        if c.isalnum():
            self.error(f"unsupported escape \\{c}")
        return self._literal_char(c)

    def _char_class(self):
        negate = False
        if self.peek() == "^":
            self.next()
            negate = True
        mask = 0
        first = True
        while True:
            c = self.peek()
            if c is None:
                self.error("unterminated character class")
            if c == "]" and not first:
                self.next()
                break
            first = False
            lo_node = self._class_single()
            if isinstance(lo_node, int):
                lo = lo_node
                if self.peek() == "-" and self.p[self.i + 1 : self.i + 2] not in ("]", ""):
                    self.next()
                    hi_node = self._class_single()
                    if not isinstance(hi_node, int) or hi_node < lo:
                        self.error("bad class range")
                    mask |= _range_mask(lo, hi_node)
                else:
                    mask |= _mask_of(lo)
            else:  # a \d/\w/\s mask inside the class
                mask |= lo_node.mask
        if negate:
            # Complement within ASCII, plus all non-ASCII characters.
            return Alt((ByteSet(_ASCII_ALL & ~mask), AnyMultibyte()))
        return ByteSet(mask)

    def _class_single(self):
        c = self.next()
        if c == "\\":
            node = self._escape(in_class=True)
            if isinstance(node, ByteSet):
                m = node.mask
                # single byte -> return the code; multi-bit -> return the set
                if m & (m - 1) == 0:
                    return m.bit_length() - 1
                return node
            self.error("unsupported escape in class")
        b = c.encode("utf-8")
        if len(b) != 1:
            self.error("non-ASCII characters in classes are not supported")
        return b[0]


# ---------------------------------------------------------------------------
# Thompson NFA -> subset-construction DFA over an alphabet partition.
# ---------------------------------------------------------------------------

_MB_LEAD2 = _range_mask(0xC2, 0xDF)
_MB_LEAD3 = _range_mask(0xE0, 0xEF)
_MB_LEAD4 = _range_mask(0xF0, 0xF4)
_MB_CONT = _range_mask(0x80, 0xBF)


class _NFA:
    def __init__(self):
        self.n = 0
        self.edges: list[tuple[int, int, int]] = []  # (src, mask, dst)
        self.eps: list[tuple[int, int]] = []

    def state(self) -> int:
        self.n += 1
        return self.n - 1

    def add(self, src: int, mask: int, dst: int):
        self.edges.append((src, mask, dst))

    def frag(self, node) -> tuple[int, int]:
        """Build the fragment for ``node``; returns (start, accept)."""
        if isinstance(node, ByteSet):
            s, a = self.state(), self.state()
            if node.mask:
                self.add(s, node.mask, a)
            # empty mask = matches nothing (e.g. [^\x00-\x7f] ASCII part)
            return s, a
        if isinstance(node, AnyMultibyte):
            s, a = self.state(), self.state()
            c1, c2, c3 = self.state(), self.state(), self.state()
            self.add(s, _MB_LEAD2, c1)
            self.add(s, _MB_LEAD3, c2)
            self.add(s, _MB_LEAD4, c3)
            self.add(c3, _MB_CONT, c2)
            self.add(c2, _MB_CONT, c1)
            self.add(c1, _MB_CONT, a)
            return s, a
        if isinstance(node, Seq):
            if not node.parts:
                s = self.state()
                return s, s
            s, a = self.frag(node.parts[0])
            for part in node.parts[1:]:
                s2, a2 = self.frag(part)
                self.eps.append((a, s2))
                a = a2
            return s, a
        if isinstance(node, Alt):
            s, a = self.state(), self.state()
            for opt in node.options:
                os, oa = self.frag(opt)
                self.eps.append((s, os))
                self.eps.append((oa, a))
            return s, a
        if isinstance(node, Repeat):
            s = self.state()
            cur = s
            for _ in range(node.min):
                fs, fa = self.frag(node.node)
                self.eps.append((cur, fs))
                cur = fa
            if node.max is None:
                fs, fa = self.frag(node.node)
                self.eps.append((cur, fs))
                self.eps.append((fa, fs))
                a = self.state()
                self.eps.append((cur, a))
                self.eps.append((fa, a))
                return s, a
            a = self.state()
            self.eps.append((cur, a))
            for _ in range(node.max - node.min):
                fs, fa = self.frag(node.node)
                self.eps.append((cur, fs))
                self.eps.append((fa, a))
                cur = fa
            return s, a
        if isinstance(node, OrderFree):
            n = len(node.pairs)
            s, a = self.state(), self.state()
            hubs = [self.state() for _ in range(1 << n)]
            self.eps.append((s, hubs[0]))
            for S in range(1 << n):
                if S & node.required_mask == node.required_mask:
                    self.eps.append((hubs[S], a))
                for i in range(n):
                    if S & (1 << i):
                        continue
                    pair = (node.pairs[i] if S == 0
                            else Seq((node.sep, node.pairs[i])))
                    ps, pa = self.frag(pair)
                    self.eps.append((hubs[S], ps))
                    self.eps.append((pa, hubs[S | (1 << i)]))
            return s, a
        raise TypeError(f"unknown AST node {node!r}")


def _nfa_to_dfa(nfa: _NFA, start: int, accept: int, max_states: int,
                *, minimize: bool = False):
    """Subset construction. Returns (next (S, 256) int32 with -1 = dead,
    accept (S,) bool). The alphabet is partitioned into byte-equivalence
    classes (bytes indistinguishable by every edge mask) so the per-state
    work is O(n_classes), not O(256)."""
    # Alphabet partition: class signature = which distinct masks contain b.
    masks = sorted({m for (_, m, _) in nfa.edges})
    sig = np.zeros(256, np.int64)
    for idx, m in enumerate(masks):
        arr = np.array([(m >> b) & 1 for b in range(256)], np.int64)
        sig = sig * 2 + arr  # cheap running signature
        # Re-compress before int64 can overflow: after a compression the
        # values are < 256 distinct indices, and 48 doublings keeps
        # 2^8 * 2^48 well inside int64.
        if idx and idx % 48 == 0:
            _, sig = np.unique(sig, return_inverse=True)
    _, class_of = np.unique(sig, return_inverse=True)
    n_classes = int(class_of.max()) + 1
    rep_byte = np.zeros(n_classes, np.int64)
    for c in range(n_classes):
        rep_byte[c] = int(np.argmax(class_of == c))

    # Per NFA state: epsilon targets and byte edges.
    eps_out: list[list[int]] = [[] for _ in range(nfa.n)]
    for s, d in nfa.eps:
        eps_out[s].append(d)
    edges_out: list[list[tuple[int, int]]] = [[] for _ in range(nfa.n)]
    for s, m, d in nfa.edges:
        edges_out[s].append((m, d))

    def closure(states: frozenset[int]) -> frozenset[int]:
        stack = list(states)
        seen = set(states)
        while stack:
            s = stack.pop()
            for d in eps_out[s]:
                if d not in seen:
                    seen.add(d)
                    stack.append(d)
        return frozenset(seen)

    start_set = closure(frozenset([start]))
    ids: dict[frozenset[int], int] = {start_set: 0}
    order = [start_set]
    next_cls: list[list[int]] = []
    i = 0
    while i < len(order):
        cur = order[i]
        i += 1
        row = [-1] * n_classes
        for c in range(n_classes):
            b = int(rep_byte[c])
            dst = set()
            for s in cur:
                for m, d in edges_out[s]:
                    if (m >> b) & 1:
                        dst.add(d)
            if dst:
                dset = closure(frozenset(dst))
                if dset not in ids:
                    # With minimization, construction gets headroom:
                    # subset construction overshoots the minimal DFA
                    # (superposed lookahead, duplicated suffixes) and the
                    # binding cap is enforced on the minimized automaton.
                    cap = 4 * max_states if minimize else max_states
                    if len(ids) >= cap:
                        raise RegexError(
                            f"grammar DFA exceeds {cap} states; simplify "
                            "the pattern or raise max_states"
                        )
                    ids[dset] = len(order)
                    order.append(dset)
                row[c] = ids[dset]
        next_cls.append(row)
    n = len(order)
    nxt = np.asarray(next_cls, np.int32)[:, class_of]  # (S, 256)
    acc = np.array([accept in st for st in order], bool)
    if minimize:
        nxt, acc = _minimize_dfa(nxt, acc)
        if nxt.shape[0] > max_states:
            raise RegexError(
                f"grammar DFA needs {nxt.shape[0]} states (> {max_states}); "
                "simplify the pattern or raise max_states"
            )
    return nxt, acc


_MOORE_ROUNDS_CAP = 1000


def _minimize_dfa(nxt: np.ndarray, acc: np.ndarray):
    """Moore partition refinement to the minimal DFA. Subset construction
    leaves plenty of redundancy (superposed lookahead states that converge,
    duplicated suffix chains) and every surviving state costs a row of the
    device token table, so minimizing shrinks real fsm_capacity
    footprints — and lets structurally large grammars (order-free objects)
    fit caps their raw construction would blow. Only run for automata
    containing an ``OrderFree`` body: Moore's round count grows with the
    automaton's distinguishing depth, so chain-shaped grammars (long
    ``maxLength`` strings, wide integer ranges) would pay minutes of
    quadratic refinement for zero shrink — and the rounds cap below bails
    to the UNMINIMIZED (valid, just larger) automaton if a pathological
    mix exceeds it anyway."""
    S = nxt.shape[0]
    # Dead sink as state S so indexing is total; states equivalent to it
    # (no path to acceptance) merge into its block and drop back to -1.
    full = np.vstack([np.where(nxt < 0, S, nxt),
                      np.full((1, nxt.shape[1]), S, nxt.dtype)])
    acc_full = np.concatenate([acc, [False]])
    # Column classes: bytes with identical transition columns refine alike.
    red = full.T[np.sort(np.unique(full.T, axis=0, return_index=True)[1])].T
    block = acc_full.astype(np.int64)
    n_blocks = 2
    rounds = 0
    while True:
        sig = np.column_stack([block[red[:, c]] for c in range(red.shape[1])])
        sig = np.column_stack([block, sig])
        _, block = np.unique(sig, axis=0, return_inverse=True)
        new_n = int(block.max()) + 1
        if new_n == n_blocks:
            break
        n_blocks = new_n
        rounds += 1
        if rounds >= _MOORE_ROUNDS_CAP:
            # A partial refinement would merge NON-equivalent states
            # (wrong language) — return the input unminimized instead.
            return nxt, acc
    # Renumber so the start state's block is 0 and blocks keep first-seen
    # order (the engine convention: state 0 is the grammar start).
    remap = -np.ones(n_blocks, np.int64)
    nxt_id = 0
    for b in [int(block[0])] + [int(b) for b in block[:S]]:
        if remap[b] < 0:
            remap[b] = nxt_id
            nxt_id += 1
    block = remap[block]
    sink_block = int(block[S])  # -1 when no real state is dead
    # Representative = first state of each block (members transition alike).
    reps = np.full(nxt_id, -1, np.int64)
    for s in range(S + 1):
        if block[s] >= 0 and reps[block[s]] < 0:
            reps[block[s]] = s
    new_nxt = block[full[reps]].astype(np.int32)  # (B, 256)
    new_acc = acc_full[reps]
    if block[0] == sink_block:
        # Empty language; keep the 1-state dead table (callers surface the
        # "admits no completion" error at token-table build).
        return (np.full((1, nxt.shape[1]), -1, np.int32),
                np.zeros(1, bool))
    new_nxt = np.where(new_nxt == sink_block, -1, new_nxt)
    keep = np.arange(nxt_id) != sink_block
    if not keep.all():
        # Drop the sink row; renumber the survivors (sink is always last
        # unless it IS a real dead state reached early — compact safely).
        old_ids = np.nonzero(keep)[0]
        renum = -np.ones(nxt_id, np.int64)
        renum[old_ids] = np.arange(old_ids.size)
        new_nxt = np.where(
            new_nxt >= 0, renum[np.clip(new_nxt, 0, None)], -1
        ).astype(np.int32)
        new_nxt = new_nxt[old_ids]
        new_acc = new_acc[old_ids]
    return new_nxt, new_acc


# ---------------------------------------------------------------------------
# Direct bounded-depth JSON DFA (no regex intermediate — the pushdown stack
# is expanded into the state id, so depth 5 stays a few hundred states).
# ---------------------------------------------------------------------------

_WS = b" \t\n\r"
_DIGITS = b"0123456789"
_HEX = b"0123456789abcdefABCDEF"


def _json_dfa(max_depth: int, top: str):
    """Byte-level DFA for JSON with container nesting bounded by
    ``max_depth``. ``top`` is "object" (the OpenAI ``json_object`` contract)
    or "value". States are (mode, stack) pairs, stack a str of 'o'/'a'."""
    if top not in ("object", "value"):
        raise ValueError("top must be 'object' or 'value'")

    def step(state, byte: int):
        """(mode, stack) × byte -> (mode, stack) | None. Modes:
        V value-start; D done (top value complete, ws loop);
        P post-value (ws, then , or close per stack top);
        OO just-opened object (key or }); OC after comma in object (key);
        K in-key; KE key-escape; KU1-4 key-unicode; KC1-2 key utf8 cont;
        PK post-key (ws then :); S/SE/SU1-4/SC1-2 value string;
        N- N0 NI ND NF NE NS NX number; Lt/Lf/Ln literal progress ints."""
        mode, stack = state
        c = byte

        def complete(stk):  # a value just finished under stack stk
            return ("D", "") if not stk else ("P", stk)

        if mode == "D":
            return ("D", "") if c in _WS else None
        if mode == "P":
            if c in _WS:
                return state
            topc = stack[-1]
            if topc == "o":
                if c == ord(","):
                    return ("OC", stack)
                if c == ord("}"):
                    return complete(stack[:-1])
            else:
                if c == ord(","):
                    return ("V", stack)
                if c == ord("]"):
                    return complete(stack[:-1])
            return None
        if mode in ("V", "OO", "OC", "AO"):
            if c in _WS:
                return state
            if mode in ("OO", "OC"):
                if c == ord('"'):
                    return ("K", stack)
                if c == ord("}") and mode == "OO":
                    return complete(stack[:-1])
                return None
            # value start (V), or just-opened array (AO: value or ])
            if mode == "AO" and c == ord("]"):
                return complete(stack[:-1])
            if c == ord('"'):
                return ("S", stack)
            if c == ord("{"):
                if len(stack) >= max_depth:
                    return None
                return ("OO", stack + "o")
            if c == ord("["):
                if len(stack) >= max_depth:
                    return None
                return ("AO", stack + "a")
            if c == ord("-"):
                return ("N-", stack)
            if c == ord("0"):
                return ("N0", stack)
            if c in _DIGITS:
                return ("NI", stack)
            if c == ord("t"):
                return (("L", "true", 1), stack)
            if c == ord("f"):
                return (("L", "false", 1), stack)
            if c == ord("n"):
                return (("L", "null", 1), stack)
            return None
        if isinstance(mode, tuple) and mode[0] == "L":
            _, word, pos = mode
            if c == ord(word[pos]):
                if pos + 1 == len(word):
                    return complete(stack)
                return (("L", word, pos + 1), stack)
            return None
        # Strings (value S* / key K*) share structure.
        if mode in ("S", "K"):
            esc, u1, c1, c2, end = (
                ("SE", "SU1", "SC1", "SC2", None) if mode == "S" else ("KE", "KU1", "KC1", "KC2", None)
            )
            if c == ord('"'):
                return complete(stack) if mode == "S" else ("PK", stack)
            if c == ord("\\"):
                return (esc, stack)
            if 0x20 <= c <= 0x7F:
                return state
            if 0xC2 <= c <= 0xDF:
                return (c1, stack)
            if 0xE0 <= c <= 0xEF:
                return (c2, stack)
            if 0xF0 <= c <= 0xF4:
                return ((("MC3", mode), stack))
            return None
        if isinstance(mode, tuple) and mode[0] == "MC3":
            if 0x80 <= c <= 0xBF:
                return ("SC2" if mode[1] == "S" else "KC2", stack)
            return None
        if mode in ("SC2", "KC2"):
            if 0x80 <= c <= 0xBF:
                return ("SC1" if mode == "SC2" else "KC1", stack)
            return None
        if mode in ("SC1", "KC1"):
            if 0x80 <= c <= 0xBF:
                return ("S" if mode == "SC1" else "K", stack)
            return None
        if mode in ("SE", "KE"):
            base = "S" if mode == "SE" else "K"
            if c in b'"\\/bfnrt':
                return (base, stack)
            if c == ord("u"):
                return (base + "U1", stack)
            return None
        if mode in ("SU1", "SU2", "SU3", "SU4", "KU1", "KU2", "KU3", "KU4"):
            if c in _HEX:
                base, n = mode[0], int(mode[2])
                if n == 4:
                    return ("S" if base == "S" else "K", stack)
                return (f"{base}U{n + 1}", stack)
            return None
        if mode == "PK":
            if c in _WS:
                return state
            if c == ord(":"):
                return ("V", stack)
            return None
        # Numbers. Completion is implicit: delimiter bytes route through P.
        if mode == "N-":
            if c == ord("0"):
                return ("N0", stack)
            if c in _DIGITS:
                return ("NI", stack)
            return None
        if mode in ("N0", "NI", "NF", "NX"):
            if mode == "NI" and c in _DIGITS:
                return state
            if mode in ("N0", "NI") and c == ord("."):
                return ("ND", stack)
            if mode in ("N0", "NI", "NF") and c in b"eE":
                return ("NE", stack)
            if mode in ("NF", "NX") and c in _DIGITS:
                return state
            # number complete; the byte must belong to the follow set
            nxt = complete(stack)
            return step(nxt, c)
        if mode == "ND":
            if c in _DIGITS:
                return ("NF", stack)
            return None
        if mode == "NE":
            if c in b"+-":
                return ("NS", stack)
            if c in _DIGITS:
                return ("NX", stack)
            return None
        if mode == "NS":
            if c in _DIGITS:
                return ("NX", stack)
            return None
        raise AssertionError(f"unhandled mode {mode!r}")

    start = ("OO", "o") if top == "object" else ("V", "")
    if top == "object":
        # top-level object: consume the opening '{' implicitly? No — the
        # model must emit it. Start expects ws then '{'.
        start = ("TOP", "")

    def step_top(state, byte):
        if state[0] == "TOP":
            if byte in _WS:
                return state
            if byte == ord("{"):
                return ("OO", "o")
            return None
        return step(state, byte)

    f = step_top if top == "object" else step

    def is_accept(state):
        mode, stack = state
        if mode == "D":
            return True
        # top-level numbers complete implicitly at end of input
        return not stack and mode in ("N0", "NI", "NF", "NX")

    ids = {start: 0}
    order = [start]
    rows = []
    i = 0
    while i < len(order):
        cur = order[i]
        i += 1
        row = np.full(256, -1, np.int32)
        for b in range(256):
            nxt = f(cur, b)
            if nxt is not None:
                if nxt not in ids:
                    ids[nxt] = len(order)
                    order.append(nxt)
                row[b] = ids[nxt]
        rows.append(row)
    nxt = np.stack(rows)
    acc = np.array([is_accept(s) for s in order], bool)
    return nxt, acc


# ---------------------------------------------------------------------------
# JSON-schema subset -> regex string (closed schemas; nesting comes from the
# schema itself, so the regex stays linear in schema size).
# ---------------------------------------------------------------------------

_JSON_STRING_RE = r'"([^"\\\x00-\x1f]|\\(["\\/bfnrt]|u[0-9a-fA-F]{4}))*"'
_JSON_NUMBER_RE = r"\-?(0|[1-9][0-9]*)(\.[0-9]+)?([eE][\+\-]?[0-9]+)?"
_JSON_INT_RE = r"\-?(0|[1-9][0-9]*)"
_WS_RE = r"[ \t\n\r]*"


def _re_escape(s: str) -> str:
    out = []
    for ch in s:
        if ch in r"\.^$*+?{}[]()|":
            out.append("\\" + ch)
        else:
            out.append(ch)
    return "".join(out)


def _suffix_cmp(s: str, ge: bool) -> str:
    """Digit strings of ``len(s)`` digits (leading zeros fine) that are
    >= s (``ge``) or <= s (not ``ge``)."""
    if not s:
        return ""
    lead = s[0]
    rest = _suffix_cmp(s[1:], ge)
    tail_any = ("[0-9]{%d}" % (len(s) - 1)) if len(s) > 1 else ""
    parts = []
    if ge and lead < "9":
        parts.append(("[%c-9]" % chr(ord(lead) + 1)) + tail_any)
    if not ge and lead > "0":
        parts.append(("[0-%c]" % chr(ord(lead) - 1)) + tail_any)
    parts.append(lead + rest)
    return "(" + "|".join(parts) + ")" if len(parts) > 1 else parts[0]


def _same_len_range(a: str, b: str) -> str:
    """Digit strings of len(a)==len(b) digits in [a, b] (zeros allowed)."""
    if a == b:
        return a
    i = 0
    while a[i] == b[i]:
        i += 1
    if i:
        return a[:i] + _same_len_range(a[i:], b[i:])
    tail_any = ("[0-9]{%d}" % (len(a) - 1)) if len(a) > 1 else ""
    parts = [a[0] + _suffix_cmp(a[1:], True) if len(a) > 1 else a[0]]
    lo_d, hi_d = ord(a[0]) + 1, ord(b[0]) - 1
    if lo_d <= hi_d:
        mid = ("[%c-%c]" % (chr(lo_d), chr(hi_d))) if lo_d != hi_d else chr(lo_d)
        parts.append(mid + tail_any)
    parts.append(b[0] + _suffix_cmp(b[1:], False) if len(b) > 1 else b[0])
    return "(" + "|".join(parts) + ")"


def _nonneg_range_regex(lo: int, hi: int) -> str:
    """Canonical JSON integers (no leading zeros) in [lo, hi], 0 <= lo <= hi."""
    parts = []
    if lo == 0:
        parts.append("0")
        lo = 1
        if hi == 0:
            return "0"
    for nd in range(len(str(lo)), len(str(hi)) + 1):
        lo_d = max(lo, 10 ** (nd - 1))
        hi_d = min(hi, 10**nd - 1)
        if lo_d > hi_d:
            continue
        parts.append(_same_len_range(str(lo_d), str(hi_d)))
    return "(" + "|".join(parts) + ")" if len(parts) > 1 else parts[0]


def _int_range_regex(lo: int, hi: int) -> str:
    """Canonical JSON integers in [lo, hi] (both bounds required)."""
    if lo > hi:
        raise ValueError(f"unsatisfiable integer bounds [{lo}, {hi}]")
    parts = []
    if lo < 0:
        neg_hi = min(hi, -1)
        parts.append("\\-" + _nonneg_range_regex(-neg_hi, -lo))
    if hi >= 0:
        parts.append(_nonneg_range_regex(max(lo, 0), hi))
    return "(" + "|".join(parts) + ")" if len(parts) > 1 else parts[0]


def _reject_unsupported(schema: dict, t: str, keys: tuple) -> None:
    """Reject-don't-drop: an unsupported constraint keyword must raise, not
    silently over-admit — the caller believes the output is constrained."""
    present = [k for k in keys if schema.get(k) is not None]
    if present:
        raise ValueError(
            f"unsupported {t} constraint keywords {present} (this closed "
            "subset would otherwise silently ignore them)"
        )


def _integer_regex(schema: dict) -> str:
    import math

    for k in ("exclusiveMinimum", "exclusiveMaximum"):
        if isinstance(schema.get(k), bool):
            raise ValueError(
                f"draft-4 boolean {k} is not supported; use the draft-6+ "
                "numeric form"
            )
    _reject_unsupported(schema, "integer", ("multipleOf",))
    lo, hi = schema.get("minimum"), schema.get("maximum")
    # ceil/floor, not int(): truncation-toward-zero corrupts negative and
    # fractional bounds (int(-0.5)+1 = 1 would wrongly reject 0).
    lo = None if lo is None else math.ceil(lo)
    hi = None if hi is None else math.floor(hi)
    # Exclusive bounds (pydantic's gt/lt spelling) fold to the tighter
    # inclusive integer bound.
    if schema.get("exclusiveMinimum") is not None:
        xlo = math.floor(schema["exclusiveMinimum"]) + 1
        lo = xlo if lo is None else max(lo, xlo)
    if schema.get("exclusiveMaximum") is not None:
        xhi = math.ceil(schema["exclusiveMaximum"]) - 1
        hi = xhi if hi is None else min(hi, xhi)
    if lo is None and hi is None:
        return _JSON_INT_RE
    if lo is None or hi is None:
        raise ValueError(
            "integer bounds need BOTH a lower and an upper bound (a "
            "one-sided bound has unbounded digit count; give the other "
            "side)"
        )
    return _int_range_regex(lo, hi)


def _strip_illegal_string_bytes(node):
    """Narrow every byte class in a pattern AST to characters legal
    UNESCAPED inside a JSON string (no quote, backslash, or controls —
    the pattern constrains the raw value characters; escape sequences are
    not expressible, documented in compile_json_schema). Keeps ``.`` and
    negated classes sound instead of rejecting them."""
    bad = _mask_of(0x22, 0x5C) | _range_mask(0x00, 0x1F)
    if isinstance(node, ByteSet):
        return ByteSet(node.mask & ~bad)
    if isinstance(node, Seq):
        return Seq(tuple(_strip_illegal_string_bytes(p) for p in node.parts))
    if isinstance(node, Alt):
        return Alt(tuple(_strip_illegal_string_bytes(o) for o in node.options))
    if isinstance(node, Repeat):
        return Repeat(_strip_illegal_string_bytes(node.node), node.min, node.max)
    return node  # AnyMultibyte (>= 0x80: always legal)


def _pattern_string_ast(schema: dict):
    """``{"type": "string", "pattern": ...}`` → AST for the quoted value.

    JSON-Schema ``pattern`` is a SEARCH per spec; a leading ``^`` /
    trailing ``$`` anchor that side (the OpenAI strict-mode idiom is
    ``^...$``), otherwise the side is padded with ``.*`` over legal
    string characters."""
    _reject_unsupported(schema, "string", ("format",))
    for k in ("minLength", "maxLength"):
        if k in schema:
            raise ValueError(
                "pattern cannot be combined with minLength/maxLength "
                "(regex intersection is not supported; fold the length "
                "bound into the pattern itself)"
            )
    core, pre, post = schema["pattern"], ".*", ".*"
    if core.startswith("^"):
        core, pre = core[1:], ""
    if core.endswith("$"):
        # The $ is a real anchor iff it is NOT escaped: an even run of
        # backslashes before it is pairs of escaped backslashes (r"\\$" ends
        # with a literal backslash then a true anchor), an odd run escapes
        # the $ itself (r"\$" is a literal dollar). A single endswith(r"\$")
        # check misreads the even case and feeds _Parser a bare "$".
        stem = core[:-1]
        if (len(stem) - len(stem.rstrip("\\"))) % 2 == 0:
            core, post = stem, ""
    node = _strip_illegal_string_bytes(_ast(pre + "(" + core + ")" + post))
    return Seq((_ast('"'), node, _ast('"')))


def _string_regex(schema: dict) -> str:
    _reject_unsupported(schema, "string", ("format",))
    mn = schema.get("minLength")
    mx = schema.get("maxLength")
    if mn is None and mx is None:
        return _JSON_STRING_RE
    mn = int(mn or 0)
    char = r'([^"\\\x00-\x1f]|\\(["\\/bfnrt]|u[0-9a-fA-F]{4}))'
    if mx is None:
        return '"' + char + ("{%d,}" % mn) + '"'
    mx = int(mx)
    if mx < mn:
        raise ValueError(
            f"unsatisfiable string bounds minLength={mn} > maxLength={mx}"
        )
    return '"' + char + ("{%d,%d}" % (mn, mx)) + '"'


# Order-free compiles as a seen-bitmask NFA (see OrderFree), so the bound
# is no longer factorial — but the determinized DFA is still inherently
# ~n·2^(n-1)·|pair| states (order-freedom itself costs that), so very wide
# objects fall back to declaration order instead of blowing max_states.
_ORDER_FREE_MAX = 8


def _ast(pattern: str):
    """Parse a regex STRING leaf into the AST the NFA builder consumes —
    the schema compiler composes structure with AST combinators (so
    OrderFree nodes can sit anywhere) and only the scalar leaves go
    through regex syntax."""
    return _Parser(pattern).parse()


_WS_AST = None  # parsed lazily (module import order)


def _ws() -> object:
    global _WS_AST
    if _WS_AST is None:
        _WS_AST = _ast(_WS_RE)
    return _WS_AST


def _opt(node) -> Repeat:
    return Repeat(node, 0, 1)


def _object_body(pairs: list, names: list, required: set):
    """AST for an object's property list in the GIVEN order: every
    property optional unless in ``required``, comma placement exact. Built
    from two linear pieces — B(i) (``(, p_i)?`` suffix chain once something
    was emitted) and a union over which property appears FIRST.
    Sub-schemas are compiled by the caller ONCE; AST nodes are shared by
    reference (the NFA builder instantiates per reference)."""
    sep = Seq((_ws(), _ast(","), _ws()))
    n = len(pairs)
    B: list = [Seq(())] * (n + 1)
    for i in range(n - 1, -1, -1):
        frag = Seq((sep, pairs[i]))
        B[i] = Seq(((frag if names[i] in required else _opt(frag)), B[i + 1]))
    # First-present union: property i can open the object only if every
    # earlier property is optional.
    alts = []
    for i in range(n):
        alts.append(Seq((pairs[i], B[i + 1])))
        if names[i] in required:
            break
    body = Alt(tuple(alts)) if len(alts) > 1 else alts[0]
    if not required:
        body = _opt(body)  # {} is valid when nothing is required
    return body


# The hub construction instantiates each pair fragment 2^(n-1) times; past
# this NFA budget the subset construction's eps-closures dominate compile
# time (minutes for nested order-free objects), so such objects fall back
# to declaration order instead — bounded compile, no user-visible error.
_ORDER_FREE_NFA_BUDGET = 100_000


def _order_free_affordable(pairs) -> bool:
    probe = _NFA()
    total = 0
    for p in pairs:
        before = probe.n
        probe.frag(p)
        total += probe.n - before
    n = len(pairs)
    return (1 << max(n - 1, 0)) * total + (1 << n) <= _ORDER_FREE_NFA_BUDGET


def _schema_ast(schema: dict):
    """Schema → regex AST. Structure (objects, arrays, unions) composes at
    the AST level; scalar leaves reuse the regex-string helpers."""
    if not isinstance(schema, dict):
        raise ValueError(f"schema must be a dict, got {type(schema).__name__}")
    if "enum" in schema:
        return _ast(
            "(" + "|".join(_re_escape(json.dumps(v)) for v in schema["enum"]) + ")"
        )
    if "const" in schema:
        return _ast(_re_escape(json.dumps(schema["const"])))
    for key in ("anyOf", "oneOf"):
        subs = schema.get(key)
        if subs:
            # oneOf's exclusivity is not expressible as a regex union; the
            # grammar admits anything matching at least one branch (the
            # anyOf semantics) — documented in compile_json_schema. Sibling
            # constraint keywords would be a CONJUNCTION in JSON Schema;
            # silently dropping them would over-admit, so they reject.
            extras = set(schema) - {
                key, "description", "title", "default", "examples",
                "$schema", "$id",
            }
            if extras:
                raise ValueError(
                    f"{key} cannot be combined with sibling constraint "
                    f"keywords {sorted(extras)} (keyword conjunction is "
                    "not supported; fold the constraints into each branch)"
                )
            return Alt(tuple(_schema_ast(s) for s in subs))
    t = schema.get("type")
    if isinstance(t, list):
        return Alt(tuple(_schema_ast({**schema, "type": x}) for x in t))
    if t == "string":
        if schema.get("pattern") is not None:
            return _pattern_string_ast(schema)
        return _ast(_string_regex(schema))
    if t == "integer":
        return _ast(_integer_regex(schema))
    if t == "number":
        _reject_unsupported(schema, "number", (
            "minimum", "maximum", "exclusiveMinimum", "exclusiveMaximum",
            "multipleOf",
        ))
        return _ast(_JSON_NUMBER_RE)
    if t == "boolean":
        return _ast("(true|false)")
    if t == "null":
        return _ast("null")
    if t == "array":
        items = schema.get("items")
        if items is None:
            raise ValueError("array schemas need 'items' (closed schemas only)")
        item = _schema_ast(items)
        mn = max(int(schema.get("minItems", 0)), 0)
        mx = schema.get("maxItems")
        sep = Seq((_ws(), _ast(","), _ws()))
        rep = Seq((sep, item))
        if mx is not None:
            mx = int(mx)
            if mx < mn:
                raise ValueError(
                    f"unsatisfiable array bounds minItems={mn} > maxItems={mx}"
                )
            if mx == 0:
                body = Seq(())
            elif mn == 0:
                body = _opt(Seq((item, Repeat(rep, 0, mx - 1))))
            else:
                body = Seq((item, Repeat(rep, mn - 1, mx - 1)))
        elif mn > 0:
            body = Seq((item, Repeat(rep, mn - 1, None)))
        else:
            body = _opt(Seq((item, Repeat(rep, 0, None))))
        return Seq((_ast(r"\["), _ws(), body, _ws(), _ast(r"\]")))
    if t == "object":
        props_map = schema.get("properties")
        if not props_map:
            raise ValueError("object schemas need 'properties' (closed schemas only)")
        unknown = set(schema.get("required", ())) - set(props_map)
        if unknown:
            raise ValueError(f"required names not in properties: {unknown}")
        # Standard JSON-Schema semantics: properties are OPTIONAL unless
        # listed in 'required' (the r3 all-required default inverted this;
        # ADVICE r3).
        required = set(schema.get("required", ()))
        # Sub-schemas compile ONCE here; both body shapes share the pair
        # nodes by reference.
        names = list(props_map)
        pairs = [
            Seq((
                _ast(_re_escape(json.dumps(name))), _ws(), _ast(":"), _ws(),
                _schema_ast(sub),
            ))
            for name, sub in props_map.items()
        ]
        if (schema.get("additionalProperties") is False
                and len(pairs) <= _ORDER_FREE_MAX
                and _order_free_affordable(pairs)):
            # Order-free (strict-mode schemas; OpenAI structured outputs):
            # the seen-bitmask construction in OrderFree/frag.
            req_mask = 0
            for i, name in enumerate(names):
                if name in required:
                    req_mask |= 1 << i
            sep = Seq((_ws(), _ast(","), _ws()))
            body = OrderFree(tuple(pairs), sep, req_mask)
        else:
            body = _object_body(pairs, names, required)
        return Seq((_ast(r"\{"), _ws(), body, _ws(), _ast(r"\}")))
    raise ValueError(f"unsupported schema: {schema!r}")


# ---------------------------------------------------------------------------
# Token-level table.
# ---------------------------------------------------------------------------


@dataclass
class CompiledGrammar:
    """A grammar lowered to a token-level transition table.

    ``token_next[s, t]``: local next state if token ``t`` is allowed in local
    state ``s``, else ``-1``. ``accept[s]``: EOS is allowed in ``s``. State 0
    is the start. States are local (0-based); an engine embedding several
    grammars into one device table relocates them by row offset."""

    token_next: np.ndarray  # (S, V) int32
    accept: np.ndarray  # (S,) bool
    source: str  # printable description for stats/debugging
    byte_next: np.ndarray | None = None  # (S, 256) char-level DFA (debug/tests)

    @property
    def n_states(self) -> int:
        return int(self.token_next.shape[0])

    def matches(self, data: bytes) -> bool:
        """Char-level fullmatch — the oracle used by tests."""
        if self.byte_next is None:
            raise ValueError("char-level DFA not retained")
        s = 0
        for b in data:
            s = int(self.byte_next[s, b])
            if s < 0:
                return False
        return bool(self.accept[s])


def _gpt2_unicode_to_byte() -> dict[str, int]:
    """Inverse of GPT-2's public bytes_to_unicode table: byte-level BPEs
    store each raw byte as a printable unicode char; mapping token strings
    back through this table recovers EXACT bytes, including tokens that are
    partial UTF-8 sequences (which ``decode()`` would mangle to U+FFFD)."""
    bs = (
        list(range(ord("!"), ord("~") + 1))
        + list(range(ord("\xa1"), ord("\xac") + 1))
        + list(range(ord("\xae"), ord("\xff") + 1))
    )
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return {chr(c): b for b, c in zip(bs, cs)}


def token_strings(tokenizer) -> list[bytes]:
    """Per-token byte strings. Exact for ByteTokenizer (1 byte/token). For
    HF tokenizers, token vocab strings are mapped back through the GPT-2
    byte alphabet when the vocab uses it (exact for byte-level BPEs, partial
    UTF-8 tokens included) or through SentencePiece's ``▁``-prefix
    convention; otherwise falls back to each token's decoded string. Every
    special token maps to b"" and is handled by column rules (EOS allowed
    via accept states, all other specials disallowed)."""
    off = getattr(tokenizer, "byte_offset", None)
    v = tokenizer.vocab_size
    if off is not None:  # ByteTokenizer fast path
        out = [b""] * v
        for i in range(off, min(off + 256, v)):
            out[i] = bytes([i - off])
        return out
    specials = {tokenizer.pad_id, tokenizer.bos_id, tokenizer.eos_id}
    inner = getattr(tokenizer, "_tok", None)
    if inner is not None:
        specials |= set(getattr(inner, "all_special_ids", ()) or ())
    to_tokens = getattr(inner, "convert_ids_to_tokens", None)
    u2b = _gpt2_unicode_to_byte()
    strings = [
        to_tokens(i) if to_tokens is not None else None for i in range(v)
    ]
    # Byte-level-BPE detection is GLOBAL, not per token: a SentencePiece
    # vocab entry like 'é' is one Latin-1-range char that also happens to
    # sit in the GPT-2 alphabet — a per-token check would map it to byte
    # 0xE9 instead of UTF-8 C3 A9 and guided output could then violate the
    # constraint (ADVICE r3). Two signals combine:
    # - POSITIVE: some token contains a remapped alphabet char
    #   (ord >= 0x100 — Ġ for space, Ċ for newline), which every real
    #   byte-level vocab has in thousands of tokens. A mere absence vote
    #   would let one added token registered as literal text (" ", CJK,
    #   emoji) flip a genuine byte-level vocab onto the decode() path that
    #   mangles partial-UTF-8 tokens.
    # - VETO: any token containing the SentencePiece word marker ▁
    #   (U+2581, outside the alphabet). The remap range U+0100-U+0143
    #   contains real Latin-Extended-A letters (ā, č, ł ...), so a
    #   multilingual SP vocab ('▁český') would otherwise false-positive —
    #   but every SP vocab carries ▁ pieces, and no byte-level vocab
    #   spells one.
    real = [
        s for i, s in enumerate(strings)
        if i not in specials and s is not None
    ]
    byte_level = (
        to_tokens is not None
        and any(any(ord(c) >= 0x100 and c in u2b for c in s) for s in real)
        and not any("▁" in s for s in real)
    )
    import re as _re

    byte_fallback = _re.compile(r"^<0x([0-9A-Fa-f]{2})>$")
    out = []
    for i in range(v):
        if i in specials:
            out.append(b"")
            continue
        s = strings[i]
        if s is not None:
            if byte_level:
                if all(ch in u2b for ch in s):
                    out.append(bytes(u2b[ch] for ch in s))
                else:  # added token registered as literal text (" ",
                    # "\n\n", CJK, emoji): its surface IS the string
                    out.append(s.encode("utf-8"))
                continue
            m = byte_fallback.match(s)
            if m:  # SentencePiece byte-fallback token: ONE raw byte, not
                # the literal 6-char text (ADVICE r3)
                out.append(bytes([int(m.group(1), 16)]))
                continue
            if s.startswith("▁"):  # SentencePiece word-start marker
                out.append((" " + s[1:]).encode("utf-8"))
                continue
            if s.isascii() and s.isprintable():
                # Plain-ASCII vocab strings are their own surface form in
                # every SP-family tokenizer; skip the decode() round trip.
                out.append(s.encode("utf-8"))
                continue
        # Everything else (non-ASCII vocab strings on a non-byte-level
        # vocab — e.g. 'é', which ALSO sits in the GPT-2 alphabet and
        # would mis-map through the byte table) routes through decode():
        # exact for SP-family tokens whose vocab string is not the
        # surface form (ADVICE r3).
        out.append(tokenizer.decode([i]).encode("utf-8"))
    return out


def _token_table(
    byte_next: np.ndarray,
    accept: np.ndarray,
    toks: list[bytes],
    *,
    eos_id: int,
    source: str,
    keep_byte_dfa: bool = True,
) -> CompiledGrammar:
    """Vectorized walk: advance every (state, token) pair through the byte
    DFA in lock-step over byte positions — O(S x V x max_len) numpy ops."""
    from ditl_tpu.native.fsm import token_table_native

    native = token_table_native(byte_next, toks)
    if native is not None:
        tt = native
    else:
        S = byte_next.shape[0]
        V = len(toks)
        lmax = max((len(t) for t in toks), default=1) or 1
        padded = np.zeros((V, lmax), np.uint8)
        lens = np.zeros(V, np.int64)
        for i, t in enumerate(toks):
            padded[i, : len(t)] = np.frombuffer(t, np.uint8)
            lens[i] = len(t)
        tt = np.broadcast_to(np.arange(S, dtype=np.int32)[:, None], (S, V)).copy()
        for l in range(lmax):
            active = (l < lens)[None, :]  # (1, V)
            cur = np.maximum(tt, 0)
            stepped = byte_next[cur, padded[None, :, l]]  # (S, V)
            tt = np.where(active, np.where(tt >= 0, stepped, -1), tt)
        # zero-byte tokens (specials / empty decodes) must not be free
        # no-ops — disallow them everywhere.
        tt[:, lens == 0] = -1
    # EOS: allowed exactly in accepting states; consuming it parks the row
    # in its current state (the engine freezes finished rows anyway).
    tt = tt.astype(np.int32)
    tt[:, eos_id] = np.where(accept, np.arange(byte_next.shape[0], dtype=np.int32), -1)
    # Liveness trim: disallow transitions into states from which no
    # accepting state is TOKEN-reachable. Without this, a constrained row
    # could enter a strandable state (e.g. the grammar needs a byte
    # sequence no token provides) and the decode mask would have no
    # allowed token — generation must instead be steered around such
    # states so every emitted prefix extends to a full match.
    live = accept.copy()
    while True:
        reach = (tt >= 0) & live[np.clip(tt, 0, None)]
        new_live = live | reach.any(axis=1)
        if (new_live == live).all():
            break
        live = new_live
    if not live[0]:
        raise ValueError(
            f"grammar {source!r} admits no completion under this tokenizer "
            "(no token path from the start state reaches an accepting state)"
        )
    tt = np.where((tt >= 0) & live[np.clip(tt, 0, None)], tt, -1).astype(np.int32)
    return CompiledGrammar(
        token_next=tt,
        accept=accept.copy(),
        source=source,
        byte_next=byte_next if keep_byte_dfa else None,
    )


# NFA ceiling: subset construction's eps-closures run over the NFA per
# discovered DFA state, so a huge NFA can stall for minutes before the DFA
# state cap ever fires. Reject it up front (request-path compiles must
# fail fast, not hang).
_NFA_HARD_CAP = 400_000


def _checked_nfa(ast):
    nfa = _NFA()
    s, a = nfa.frag(ast)
    if nfa.n > _NFA_HARD_CAP:
        raise RegexError(
            f"grammar NFA needs {nfa.n} states (> {_NFA_HARD_CAP}); "
            "the pattern/schema is too large to determinize"
        )
    return nfa, s, a


def _contains_order_free(node) -> bool:
    if isinstance(node, OrderFree):
        return True
    if isinstance(node, Seq):
        return any(_contains_order_free(p) for p in node.parts)
    if isinstance(node, Alt):
        return any(_contains_order_free(o) for o in node.options)
    if isinstance(node, Repeat):
        return _contains_order_free(node.node)
    return False


def _compile_ast(ast, tokenizer, max_states: int, source: str,
                 *, minimize: bool = False) -> CompiledGrammar:
    """Shared compile tail: AST → (capped, optionally minimized) byte DFA
    → token table."""
    nfa, s, a = _checked_nfa(ast)
    byte_next, accept = _nfa_to_dfa(nfa, s, a, max_states, minimize=minimize)
    return _token_table(
        byte_next, accept, token_strings(tokenizer),
        eos_id=tokenizer.eos_id, source=source,
    )


def compile_regex(
    pattern: str,
    tokenizer,
    *,
    max_states: int = 20_000,
) -> CompiledGrammar:
    """Compile an anchored (fullmatch) regex into a token-level DFA table."""
    return _compile_ast(
        _Parser(pattern).parse(), tokenizer, max_states, f"regex:{pattern}",
    )


def compile_json(
    tokenizer,
    *,
    max_depth: int = 5,
    top: str = "object",
) -> CompiledGrammar:
    """Any syntactically valid JSON (``top="object"`` = the OpenAI
    ``json_object`` contract) with container nesting up to ``max_depth``."""
    byte_next, accept = _json_dfa(max_depth, top)
    return _token_table(
        byte_next, accept, token_strings(tokenizer),
        eos_id=tokenizer.eos_id, source=f"json:{top}:d{max_depth}",
    )


def compile_json_schema(
    schema: dict,
    tokenizer,
    *,
    max_states: int = 32_768,
) -> CompiledGrammar:
    """Closed JSON-schema subset -> regex -> token DFA.

    Supported: ``type`` (scalar or list), ``enum``/``const``,
    ``anyOf``/``oneOf`` (both compiled as the union — oneOf's exclusivity
    is not regular), objects with ``properties``/``required``, arrays with
    ``items`` + ``minItems``/``maxItems``, integers with
    ``minimum``+``maximum`` (both sides — a one-sided bound is rejected),
    strings with ``minLength``/``maxLength`` OR ``pattern`` (search
    semantics per spec; ``^``/``$`` anchor their side; byte classes are
    narrowed to characters legal UNESCAPED in a JSON string, so a
    pattern cannot demand a quote/backslash/control character — escape
    sequences are not expressible through patterns).

    Object semantics: properties are OPTIONAL unless listed in
    ``required`` (standard JSON-Schema; note OpenAI strict mode requires
    every property listed). Property ORDER is the schema's declaration
    order — except when ``additionalProperties`` is explicitly ``false``
    and the object has <= 8 properties, in which case any order is
    admitted via a seen-property-bitmask DFA (n·2^(n-1) pair fragments,
    not the n! permutation union; the ~2^n state factor is inherent to
    order-freedom, so wider objects fall back to declaration order — and
    order-free objects are the dominant share of a wide schema's states).
    Unknown keys are never admitted (the grammar is closed by
    construction, with or without ``additionalProperties``)."""
    ast = _schema_ast(schema)
    # Minimization only pays (and only tractably) for order-free bodies:
    # their subset DFAs carry real redundancy, while chain-shaped schemas
    # (maxLength strings, wide integer ranges) are already minimal and
    # Moore's refinement rounds would stall the request path for nothing.
    return _compile_ast(
        ast, tokenizer, max_states, f"schema:{json.dumps(schema)[:80]}",
        minimize=_contains_order_free(ast),
    )
