"""Continuous batching: slot-based decoding where requests join and leave
between decode ticks.

The reference's "serving" story is one blocking HTTP call per example to
someone else's server (ref ``src/distributed_inference.py:34-41,69``); the
batch Generator (infer/engine.py) already beats that, but it decodes a fixed
batch in lock-step — a long request stalls the whole batch, and new requests
wait for the batch to drain. This engine removes both limits the TPU way:

- **Fixed-shape slot state**: ``n_slots`` sequences decode together; every
  array (cache, positions, tokens) has a static shape, so exactly TWO
  programs compile — one prefill per prompt-length bucket, one decode tick.
- **Per-slot depth**: each slot sits at its own position; the cache write is
  a per-row scatter (infer/cache.py ``_scatter_rows``) and the attention
  mask is ``slot_index <= pos[row]`` — no re-padding, no re-batching.
- **Prefill into a slot**: a new prompt runs one batched forward over its
  length bucket against a 1-row slice of the shared cache, then the slice is
  written back at the slot index. Other slots' state is untouched, so
  admission never disturbs in-flight decodes.
- **Chunked ticks**: decode runs ``decode_chunk`` steps per program call
  (a ``lax.scan``; zero host round-trips inside), then the host harvests
  finished slots, trims at EOS, and admits queued requests.

The scheduler (``submit``/``step``/``run``) is deliberately host-side and
simple — admission policy is not a TPU problem. Per-request sampling params
are supported for temperature 0/>0 mixtures by keeping sampling greedy when
``temperature == 0`` per-slot (a (B,) vector fed to the tick program).

**Per-tick token budget + SLO classes** (ISSUE 8, the Sarathi-Serve
observation): with ``token_budget > 0`` each tick composes its decode work
(``decode_ready x decode_chunk`` tokens) plus at most ``budget - decode``
prefill tokens, so a long admission's prefill chunks can never monopolize
ticks that decode-ready slots are waiting on — the stall the interference
histogram (``ditl_serving_tpot_interference_seconds``, ISSUE 6) measures.
The first prefill of a tick always runs (at-least-one-chunk progress rule:
a tight budget bounds the stall, it must not starve admission), so the
honest per-tick prefill bound is ``max(one chunk, budget - decode)``.
Requests carry an SLO class (``interactive`` < ``batch`` < ``best_effort``)
— admission orders the queue by class then arrival, prefill chunks advance
in the same order, and under pool pressure the preemption machinery evicts
by class first, youth second, so a best-effort request is always the first
casualty and the highest-priority oldest request always progresses (the
same no-deadlock invariant as before, lifted to (class, age) order).

**Speculative ticks** (``speculative=True``): when every active slot is
greedy, the decode tick can run as ``spec_rounds`` verify rounds instead of
``decode_chunk`` single-token steps. Each round drafts ``spec_k`` tokens per
slot by on-device prompt lookup over a per-slot token-history buffer
(infer/speculative.device_lookup_draft — the history rides the tick carry,
so drafting re-fires after every accepted span with zero host round-trips),
verifies them with ONE (B, K+1)-token forward (per-row scatter cache writes
at each slot's own depth — the ragged-depth machinery chunked prefill
already uses), and emits the accepted prefix plus the verify forward's bonus
token. Rejected draft positions leave stale KV that stays masked and is
overwritten by the next round's write window (same invariant as
infer/speculative.py). Greedy speculative output is token-identical to the
plain tick (exact arithmetic; pinned in f32 by tests). Because acceptance is
a workload property, the engine auto-decides per tick from per-REQUEST
measured acceptance (tokens per verify forward per row, EMA-smoothed, probed
periodically) against the verify/decode cost-ratio threshold — slots whose
requests historically accept keep speculation on; a batch of low-acceptance
requests falls back to plain ticks. Composes with ``cache_mode="paged"``
(accepted tokens land in the deferred-flush tail; the verify runs through a
multi-query paged-attention kernel) and int8 KV.
"""

from __future__ import annotations

import collections
import time
from dataclasses import dataclass, field
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ditl_tpu.annotations import hot_path
from ditl_tpu.chaos import InjectedFault, maybe_inject
from ditl_tpu.config import ModelConfig
from ditl_tpu.data.tokenizer import Tokenizer
from ditl_tpu.infer.cache import init_cache
from ditl_tpu.infer.engine import GenerateConfig, _next_pow2
from ditl_tpu.infer.sampling import sample_logits
from ditl_tpu.models import llama
from ditl_tpu.telemetry.flight import TICK_RING, FlightRecorder
from ditl_tpu.telemetry.serving import ServingMetrics
from ditl_tpu.telemetry.tracing import NULL_TRACER, Tracer
from ditl_tpu.telemetry.usage import sanitize_label
from ditl_tpu.utils.logging import get_logger

logger = get_logger(__name__)

__all__ = ["BadRequestError", "ContinuousEngine", "DeadlineExceededError",
           "QueueFullError", "Request", "SLO_CLASSES", "ThreadedEngine",
           "derive_copy_seed"]

# SLO class -> scheduling rank (lower = served first). Admission orders the
# queue by (rank, arrival), prefill chunks advance in the same order, and
# preemption evicts the highest (rank, req_id) first — so the ranks ARE the
# eviction order reversed. The names ride the HTTP surface (`slo_class`
# payload / `X-SLO-Class` header), so changing them is an API change.
SLO_CLASSES: dict[str, int] = {"interactive": 0, "batch": 1, "best_effort": 2}


def _quantize_pages(chunk: jax.Array) -> tuple[jax.Array, jax.Array]:
    """(L, n, K, ps, D) float -> int8 values + (L, n, K, 1, ps) f32 scales
    — one quantization recipe for both cache modes (infer/cache._quantize,
    symmetric per-position absmax over the last axis)."""
    from ditl_tpu.infer.cache import _quantize

    q, scale = _quantize(chunk)
    return q, scale[:, :, :, None, :]


def _lp_stats(step_logits: jax.Array, tok: jax.Array, k: int):
    """Chosen-token logprob + top-k alternatives from the RAW (B, V)
    distribution — before temperature/top-k/top-p shaping; the same OpenAI
    semantics as engine.Generator's lock-step logprobs."""
    lp = jax.nn.log_softmax(step_logits.astype(jnp.float32), -1)
    chosen = jnp.take_along_axis(lp, tok[:, None], 1)[:, 0]
    top_lp, top_id = jax.lax.top_k(lp, k)
    return chosen, top_id.astype(jnp.int32), top_lp


def _fsm_mask(ftab: jax.Array, fstate: jax.Array, logits: jax.Array) -> jax.Array:
    """Grammar mask: disallowed tokens (table entry < 0) to -inf. One row
    gather per call; FREE/DEAD rows are all-allowed, so unconstrained rows
    pass through bit-identically."""
    return jnp.where(ftab[fstate] >= 0, logits, -jnp.inf)


def _fsm_next(ftab: jax.Array, fstate: jax.Array, tok: jax.Array) -> jax.Array:
    """Advance FSM state(s) on sampled token(s); a disallowed transition
    (only reachable via discarded speculative positions or finished rows)
    clamps to the DEAD trap row 1."""
    nxt = ftab[fstate, tok]
    return jnp.where(nxt >= 0, nxt, 1)


def _flush_tail_into_pools(pools, tk, tv, starts, pos, table, ps, tail_len):
    """Scatter the tick's tail columns into their pages — ONE scatter per
    pool per tick (amortized over the chunk; per-token in-scan page writes
    cost ~7 ms/step on v5e). Valid columns are j < pos - starts (exactly
    the tokens the tick committed; rejected speculative positions and dead
    rows fall outside). Invalid columns aim at sentinel page 0 with
    row-distinct offsets, whose content is never read unmasked. int8 pools:
    the tail is quantized HERE (tokens attend at full precision within
    their own tick, then round once). Shared by the plain and speculative
    paged decode programs."""
    n_b = pos.shape[0]
    b_iota = jnp.arange(n_b, dtype=jnp.int32)
    L, _, K, _, D = pools["kp"].shape
    j = jnp.arange(tail_len, dtype=jnp.int32)
    gpos = starts[:, None] + j[None, :]  # (B, tail_len)
    valid = j[None, :] < (pos - starts)[:, None]
    pidx = jnp.take_along_axis(
        table, jnp.clip(gpos // ps, 0, table.shape[1] - 1), axis=1
    )
    pid = jnp.where(valid, pidx, 0).reshape(-1)
    off = jnp.where(
        valid, gpos % ps,
        (b_iota[:, None] * tail_len + j[None, :]) % ps,
    ).reshape(-1)

    def flush(pool, tail):
        # tail (L, B, K, T, D) -> (B*T, L, K, D); advanced indices
        # on pool dims 1 and 3 put the scatter dim first.
        vals = jnp.transpose(tail, (1, 3, 0, 2, 4)).reshape(
            n_b * tail_len, L, K, D
        )
        return pool.at[:, pid, :, off].set(vals.astype(pool.dtype))

    def flush_scale(spool, scales):
        # scales (L, B, K, T) -> (B*T, L, K, 1); spool (L,P,K,1,ps)
        vals = jnp.transpose(scales, (1, 3, 0, 2)).reshape(
            n_b * tail_len, L, K
        )[..., None]
        return spool.at[:, pid, :, :, off].set(vals)

    out = dict(pools)
    if "ks" in pools:
        from ditl_tpu.infer.cache import _quantize

        qk, sk = _quantize(tk)
        qv, sv = _quantize(tv)
        out["kp"] = flush(pools["kp"], qk)
        out["vp"] = flush(pools["vp"], qv)
        out["ks"] = flush_scale(pools["ks"], sk)
        out["vs"] = flush_scale(pools["vs"], sv)
    else:
        out["kp"] = flush(pools["kp"], tk)
        out["vp"] = flush(pools["vp"], tv)
    return out


def derive_copy_seed(base: int, i: int) -> int:
    """Seed for copy ``i`` of an OpenAI ``n``/``best_of`` fan-out. Copy 0
    keeps the caller's seed untouched (an ``n=2, seed=s`` request reproduces
    the ``n=1, seed=s`` completion as its first candidate); later copies
    stride by a prime and wrap into int31 so no derived seed ever trips the
    pod driver's int32 stage bound. The single source of truth for BOTH
    ThreadedEngine.generate_many and PodContinuousDriver.generate_many —
    pod and solo serving must replay identically for a given seed."""
    return base if i == 0 else (base + 7919 * i) & 0x7FFFFFFF


class BadRequestError(ValueError):
    """Request validation failed — the CLIENT's fault (seed/max_tokens out
    of bounds, prompt too long, unknown adapter, guided-in-pod). Subclasses
    ValueError so existing callers' ``except ValueError`` still matches; the
    HTTP server maps exactly this class to 400, keeping genuine server bugs
    (any other ValueError) on the logged 500 path."""


class QueueFullError(RuntimeError):
    """Raised by ``submit`` when the engine's admission queue is at its
    configured depth cap — callers (the HTTP server) turn this into a 429
    instead of letting waiting requests accumulate without bound."""


class DeadlineExceededError(RuntimeError):
    """A request's deadline expired before it completed: the engine evicted
    it from the queue/slot (its remaining token budget is never decoded)
    and the HTTP layer answers 504. Partial tokens, if any, ride the
    Request object."""


@dataclass
class Request:
    """One in-flight generation request (host bookkeeping)."""

    req_id: int
    prompt: list[int]
    max_new_tokens: int
    temperature: float
    top_p: float
    seed: int
    tokens: list[int] = field(default_factory=list)
    slot: int | None = None
    finished: bool = False
    # Set by cancel(): pipelined (double-buffered) ticks may still hold this
    # request in a pending harvest snapshot — the flag keeps that lagged
    # harvest from appending tokens to (or re-completing) a dead request.
    cancelled: bool = False
    # Chunked prefill progress: next prompt offset to prefill; the request
    # joins decode ticks only once the whole prompt is in the cache.
    prefill_pos: int = 0
    prefilling: bool = False
    # Streaming: when set, every harvest pushes this chunk's new token ids
    # (list[int]); a final ``None`` marks completion.
    stream: Any = None
    # Measured speculative acceptance for THIS request: tokens emitted
    # across its speculative rounds / verify forwards it participated in.
    # Drives the per-tick speculate-or-not decision (see step()).
    spec_tokens: int = 0
    spec_forwards: int = 0
    # OpenAI-style logprobs: None = not requested; N >= 0 = return the
    # chosen token's logprob plus top-N alternatives per generated token
    # (engine computes ``logprobs_k`` alternatives; N only slices).
    logprobs: int | None = None
    # Multi-LoRA: adapter slot in the stacked params tree (0 = base).
    adapter_id: int = 0
    # Optimistic paged admission: True after this request was preempted
    # (pages reclaimed mid-flight); the preempt_* fields carry the device
    # scalars needed for an exact resume — the PENDING sampled token (cur),
    # the per-slot PRNG key (a split chain, not reconstructible from
    # emitted-token count alone), the FSM state, and the pending logprob
    # stats. All stay lazy device values: capture costs no transfer.
    preempted: bool = False
    preempt_cur: Any = None
    preempt_key: Any = None
    preempt_fst: Any = None
    preempt_lp: Any = None
    # Guided decoding: absolute start state in the engine's FSM table
    # (0 = FREE row = unconstrained).
    fsm_start: int = 0
    lp_token: list[float] = field(default_factory=list)
    lp_top_ids: list[list[int]] = field(default_factory=list)
    lp_top: list[list[float]] = field(default_factory=list)
    # Telemetry timestamps (time.monotonic; 0.0 = not yet): submit, slot
    # admission, first harvested token, last harvest. Host wall clocks only
    # — the latency histograms (telemetry/serving.py) are built from these.
    t_submit: float = 0.0
    t_admitted: float = 0.0
    t_first: float = 0.0
    t_last_emit: float = 0.0
    # Deadline (time.monotonic absolute; None = none): past it the request
    # is evicted from the queue/slot at the next scheduler tick instead of
    # burning device time (ISSUE 5). ``expired`` marks that eviction —
    # waiters raise DeadlineExceededError, streams get their terminal None.
    deadline: float | None = None
    expired: bool = False
    # Request tracing (ISSUE 6, telemetry/tracing.py): ``trace`` is the
    # upstream SpanContext (the server's request span) this request's
    # engine-lifecycle spans chain under; request_span/queue_span are the
    # engine's own open spans (None when the engine's tracer is unarmed —
    # tracing is host bookkeeping only and never reaches the scheduler's
    # replicated state).
    trace: Any = None
    request_span: Any = None
    queue_span: Any = None
    # Scheduler-interference attribution (ISSUE 6): wall seconds of OTHER
    # requests' prefill chunks that shared (and lengthened) this request's
    # decode ticks. ``interference_pending`` holds per-tick
    # (culprit_req_id, culprit_prefill_tokens, seconds) entries since the
    # last harvest (drained into the decode span's annotation);
    # ``interference_s`` is the lifetime total.
    interference_pending: list = field(default_factory=list)
    interference_s: float = 0.0
    # SLO class (ISSUE 8): scheduling priority rank key into SLO_CLASSES.
    # Orders admission and prefill advance; picked first for eviction under
    # pool pressure when ranked worse than the needy request.
    slo_class: str = "interactive"
    # Prefix-cache accounting (ISSUE 8): prompt tokens whose KV was reused
    # from the cache at first admission vs tokens actually prefilled.
    # Resume re-prefills after preemption touch NEITHER field — the prompt
    # was already credited once; thrash cost is tracked separately
    # (resume_prefill_tokens).
    cache_hit_tokens: int = 0
    cache_miss_tokens: int = 0
    # Tier split of cache_hit_tokens (ISSUE 13/15): reuse served from the
    # host-RAM tier / a shipped handoff rather than resident HBM pages —
    # stored per request so the usage ledger can bill the split, not just
    # the fleet counters.
    cache_hit_host_tokens: int = 0
    cache_hit_handoff_tokens: int = 0
    # Usage attribution (ISSUE 15): ``tenant`` is the credential-safe
    # label the gateway/server derived (admission digest or configured
    # name — NEVER the raw bearer; sanitized again at submit). The
    # remaining fields are the per-request cost the terminal ledger row
    # carries: an estimated device-seconds share (prefill dispatch wall +
    # this request's share of each decode tick it rode — an estimate by
    # construction, consistent across tenants, documented in
    # docs/design.md), preemptions absorbed, and resume re-prefill thrash.
    tenant: str = "anonymous"
    device_time_est_s: float = 0.0
    # Monotonic stamp of the LAST prefill dispatch's completion: the first
    # decode chunk's device-share interval starts here, not at slot
    # admission — the prefill wall is already billed by _record_prefill,
    # and measuring the first chunk from t_admitted would double-bill it
    # (prefill-heavy tenants would be systematically overbilled, exactly
    # the skew convictions must not have).
    t_prefill_done: float = 0.0
    preempt_count: int = 0
    resume_tokens: int = 0
    # One terminal usage row per request, no matter how many terminal
    # paths race (cancel vs lagged harvest completion).
    usage_noted: bool = False

    @property
    def slo_rank(self) -> tuple[int, int]:
        """Scheduling order key: class rank, then arrival."""
        return (SLO_CLASSES[self.slo_class], self.req_id)


class ContinuousEngine:
    """Slot-based continuous-batching text generation."""

    def __init__(
        self,
        params: llama.Params,
        model_cfg: ModelConfig,
        tokenizer: Tokenizer,
        *,
        n_slots: int = 8,
        decode_chunk: int = 16,
        gen: GenerateConfig | None = None,
        seed: int = 0,
        max_cache_len: int | None = None,
        prefill_chunk: int = 0,
        cache_mode: str = "contiguous",
        page_size: int = 256,
        n_pages: int | None = None,
        max_queue: int | None = None,
        mesh=None,
        rules=None,
        speculative: bool = False,
        spec_k: int = 8,
        spec_ngram: int = 3,
        spec_min_ngram: int = 1,
        spec_rounds: int | None = None,
        spec_threshold: float | None = None,
        spec_probe_every: int = 32,
        spec_ema: float = 0.7,
        logprobs_k: int = 0,
        fsm_capacity: int = 0,
        draft_params: llama.Params | None = None,
        draft_cfg: ModelConfig | None = None,
        pipeline_ticks: bool = False,
        admission: str = "reserve",
        token_budget: int = 0,
        thrash_window: int = 32,
        host_tier_mb: float = 0,
        spill_max_pages_per_tick: int = 32,
        metrics: ServingMetrics | None = None,
        tracer: Tracer | None = None,
        flight: FlightRecorder | None = None,
        anomaly=None,
        usage=None,
        usage_ledger=None,
    ):
        """``max_cache_len`` caps the per-slot KV cache below the model's
        ``max_seq_len`` — essential for long-context models (Llama-3.1's
        131072 would be ~17 GB of cache PER SLOT at 8B scale); requests are
        validated against the cap at submit.

        ``prefill_chunk > 0`` enables chunked prefill: prompts longer than
        the chunk are prefilled one chunk per scheduler tick, interleaved
        with other slots' decode chunks — a 100k-token admission no longer
        stalls every in-flight generation for the whole prefill (and one
        chunk-sized program serves every prompt length, instead of one
        compile per prompt-length bucket).

        ``cache_mode="paged"`` replaces the contiguous per-slot cache with a
        shared page pool (``n_pages`` pages of ``page_size`` tokens;
        default sized to the contiguous capacity ``n_slots x smax``).
        ``page_size`` trades decode speed against sharing granularity: at
        256 (default) paged decode is ~1.5x FASTER than the contiguous
        cache on v5e (the kernel reads only live pages and defers page
        writes to one per-tick flush); 128 costs ~16% over 256, 64 ~40% —
        smaller pages dedup shorter prefixes and waste less tail padding.
        Capacity is then bounded by total resident tokens, not
        ``n_slots x max_context``; every FULL prompt page is content-hashed
        and automatically reused by later prompts sharing the prefix —
        ``register_prefix`` becomes an optimization hint (pre-warm), not a
        requirement (infer/paged_cache.py, ops/paged_attention.py).
        ``admission`` picks the paged admission policy: ``"reserve"``
        (default) reserves a request's worst-case pages up front (prompt +
        max_new) and queues requests the pool can't cover — no mid-flight
        preemption; ``"optimistic"`` reserves only prompt + one tick of
        headroom, feeds pages per tick, and on pool exhaustion preempts the
        youngest request (exact resume: pages published for cheap
        re-prefill, sampling frontier captured device-side) — strictly more
        concurrency at equal pool bytes when requests finish before their
        pessimistic ``max_tokens``. ``kv_cache_dtype="int8"`` composes:
        pools store int8 + per-position scales (halving page bytes =
        doubling resident tokens), the kernel factors the scales out of
        its dots, and the hot tail stays float until the per-tick flush.
        With a mesh, the pools shard kv-heads over the tensor axis (the
        kernel is shard_mapped; heads must divide tp).

        ``max_queue`` caps how many requests may wait for a slot; ``submit``
        raises ``QueueFullError`` beyond it (HTTP layer: 429).

        ``speculative=True`` arms speculative decode ticks (module
        docstring): ``spec_k`` drafted tokens per round via prompt lookup
        with n-gram backoff ``spec_ngram`` → ``spec_min_ngram``,
        ``spec_rounds`` verify rounds per tick (default: enough rounds to
        match ``decode_chunk`` tokens at full acceptance). A tick runs
        speculatively only when every active slot is greedy AND the
        acceptance the engine predicts for the current slots (per-request
        measured tokens/forward, EMA ``spec_ema``, re-probed every
        ``spec_probe_every`` ticks) clears ``spec_threshold`` — the
        verify/decode cost ratio (default from
        ``calibrate_spec_threshold``'s conservative prior, ~2.5 on v5e).

        ``mesh`` shards the engine's programs over a device mesh (same rule
        table as training, parallel/sharding.py): the cache shards batch
        over data/fsdp and kv-heads over tensor, and GSPMD emits the pod
        collectives. Combined with the podserve tick broadcast
        (infer/podserve.PodContinuousDriver) this is pod-wide continuous
        batching: every process runs the identical tick program on its
        shard. In paged mode the kernel is shard_mapped over the tensor
        axis (kv-heads split; page table replicated)."""
        from ditl_tpu.data.tokenizer import check_vocab

        check_vocab(tokenizer, model_cfg.vocab_size, "ContinuousEngine")
        self.params = params
        self.cfg = model_cfg
        self.tokenizer = tokenizer
        # Serving telemetry (telemetry/serving.py): per-request latency
        # histograms + operational counters, recorded on the host scheduler
        # path only (zero device syncs). Pass a shared bundle to aggregate
        # across engines; by default each engine owns its own.
        self.metrics = metrics if metrics is not None else ServingMetrics()
        # Request tracing (telemetry/tracing.py): an armed tracer records
        # each request's engine lifecycle (queue -> prefill chunk(s) ->
        # decode chunks) as spans plus per-tick instants into its journal.
        # Unarmed (the default) every span site is skipped — tracing is
        # host-only bookkeeping and never touches replicated scheduler
        # state, so pod replicas may disagree about it freely.
        self.tracer = tracer if tracer is not None else NULL_TRACER
        # Flight recorder (ISSUE 10): always-on bounded ring of per-tick
        # scheduler snapshots — budget spend, queue-by-class, slot
        # occupancy — recorded as one host dict append per tick and read
        # only when an incident bundle dumps it. ``anomaly`` is an optional
        # telemetry.anomaly.ServingAnomalyMonitor the tick loop consults
        # every ``check_every`` ticks (detectors over signals the metrics
        # bundle already carries; never on the per-request path).
        self.flight = flight if flight is not None else FlightRecorder()
        self.anomaly = anomaly
        # Per-tenant usage metering (ISSUE 15, telemetry/usage.py):
        # ``usage`` (UsageMeter) keeps bounded in-memory rollups + the
        # windowed prefill/device accounting noisy-neighbor convictions
        # read; ``usage_ledger`` (UsageLedger) writes ONE crash-consistent
        # JSONL row per terminal request — both fed from host values the
        # scheduler already holds (zero device syncs), both unarmed by
        # default. The meter binds the engine's own registry so the
        # ditl_usage_* families render on the same /metrics.
        self.usage = usage
        self.usage_ledger = usage_ledger
        if usage is not None:
            usage.bind(self.metrics.registry)
        # Per-tick prefill work [(req_id, tokens, wall_s)] — the
        # interference-attribution input (see step()).
        self._tick_prefills: list[tuple[int, int, float]] = []
        if n_slots < 1:
            raise ValueError(f"n_slots must be >= 1, got {n_slots}")
        if decode_chunk < 1:
            raise ValueError(f"decode_chunk must be >= 1, got {decode_chunk}")
        self.n_slots = n_slots
        self.decode_chunk = decode_chunk
        if prefill_chunk < 0:
            raise ValueError(f"prefill_chunk must be >= 0, got {prefill_chunk}")
        self.prefill_chunk = prefill_chunk
        # Per-tick token budget (ISSUE 8, module docstring): 0 = unbudgeted
        # (the historical scheduler). When armed, each tick's prefill spend
        # is capped at budget - decode_ready*decode_chunk; the floor below
        # guarantees that cap is >= decode_chunk whenever prefill work can
        # exist (a prefilling or free slot means decode_ready < n_slots), so
        # a legal budget can bound stalls but never starve admission.
        if token_budget < 0:
            raise ValueError(f"token_budget must be >= 0, got {token_budget}")
        if token_budget and token_budget < n_slots * decode_chunk:
            raise ValueError(
                f"token_budget {token_budget} must cover a full decode tick "
                f"(n_slots {n_slots} x decode_chunk {decode_chunk} = "
                f"{n_slots * decode_chunk}); smaller budgets would zero the "
                f"prefill allowance forever and starve admission"
            )
        self.token_budget = token_budget
        self._tick_prefill_left: int | None = None  # None = unbudgeted tick
        self._tick_prefill_spent = 0
        # Observability for the budget bound (pinned by the mixed-workload
        # drill): the largest prefill token spend any single tick made, and
        # the largest single interference observation — deterministic and
        # wall-clock views of the same stall.
        self.max_tick_prefill_tokens = 0
        self.interference_max_s = 0.0
        # Per-victim-class split of interference_max_s (ISSUE 9): the
        # disaggregated-fleet drill is graded on the worst stall an
        # INTERACTIVE stream absorbed, not the fleet-wide worst.
        self.interference_max_by_class: dict[str, float] = {}
        self.max_queue = max_queue
        self.mesh = mesh
        self.rules = rules
        self.gen = gen or GenerateConfig()
        self.smax = min(model_cfg.max_seq_len, max_cache_len or model_cfg.max_seq_len)

        if cache_mode not in ("contiguous", "paged"):
            raise ValueError(f"unknown cache_mode {cache_mode!r}")
        self.cache_mode = cache_mode
        self.page_size = page_size
        if cache_mode == "paged":
            if model_cfg.kv_cache_dtype not in ("", "model", "int8"):
                raise ValueError(
                    f"unknown kv_cache_dtype {model_cfg.kv_cache_dtype!r}"
                )
            if page_size < 16 or page_size & (page_size - 1):
                raise ValueError(
                    f"page_size must be a power of two >= 16, got {page_size}"
                )
            if prefill_chunk and prefill_chunk % page_size:
                raise ValueError(
                    f"prefill_chunk {prefill_chunk} must be a multiple of "
                    f"page_size {page_size} (chunk starts must be page-aligned)"
                )
            from ditl_tpu.infer.paged_cache import PageAllocator

            self.maxp = -(-self.smax // page_size)
            # Default pool = the contiguous capacity; page 0 is the sentinel.
            self.n_pages = n_pages or (n_slots * self.maxp + 1)
            # (L, P, K, ps, D): kv-heads before page slots so the Pallas
            # kernel's per-head blocks keep (ps, D) trailing dims.
            shape = (
                model_cfg.num_layers, self.n_pages, model_cfg.num_kv_heads,
                page_size, model_cfg.head_dim,
            )
            dt = jnp.dtype(model_cfg.dtype)
            quantized = model_cfg.kv_cache_dtype == "int8"
            scale_shape = (
                model_cfg.num_layers, self.n_pages, model_cfg.num_kv_heads,
                1, page_size,
            )

            def fresh_pools():
                if quantized:
                    return {
                        "kp": jnp.zeros(shape, jnp.int8),
                        "vp": jnp.zeros(shape, jnp.int8),
                        "ks": jnp.ones(scale_shape, jnp.float32),
                        "vs": jnp.ones(scale_shape, jnp.float32),
                    }
                return {"kp": jnp.zeros(shape, dt), "vp": jnp.zeros(shape, dt)}

            if mesh is not None:
                from ditl_tpu.ops.attention import _mesh_axes_size
                from ditl_tpu.parallel.sharding import (
                    DEFAULT_RULES,
                    named_sharding_tree,
                    seq_shards,
                )

                if seq_shards(mesh, rules) > 1:
                    # Deliberate (BASELINE.md r4 'sequence-sharded x
                    # paged'): page pools shard kv-heads/tensor only and
                    # REPLICATE over the sequence axis — paged capacity
                    # does not scale with it. The sequence axis exists for
                    # contexts that exceed one chip's HBM, where
                    # concurrency is inherently tiny and paged's capacity
                    # sharing buys nothing; use the contiguous cache there
                    # (it context-shards over the axis).
                    logger.warning(
                        "cache_mode='paged' on a sequence-sharded mesh: "
                        "page pools replicate over the sequence axis (no "
                        "context-capacity scaling); long-context serving "
                        "should use the contiguous cache"
                    )
                r = rules if rules is not None else DEFAULT_RULES
                tp = _mesh_axes_size(mesh, r.get("act_kv_heads"))
                if tp > 1 and (model_cfg.num_kv_heads % tp
                               or model_cfg.num_heads % tp):
                    raise ValueError(
                        f"paged cache with a mesh shards kv-heads over the "
                        f"tensor axis: heads {model_cfg.num_heads}/"
                        f"{model_cfg.num_kv_heads} must divide tp={tp}"
                    )
                dp = _mesh_axes_size(mesh, r.get("batch"))
                if dp > 1 and n_slots % dp:
                    # Fail at construction: the kernel would silently fall
                    # back to the unsharded GSPMD path, resharding the whole
                    # page pool every decode step (ADVICE r2).
                    raise ValueError(
                        f"paged cache with a mesh shards slots over the "
                        f"data axes: n_slots={n_slots} must divide dp={dp}"
                    )
                pool_axes = ("layers", None, "act_kv_heads", None, "head_dim")
                axes_tree = {"kp": pool_axes, "vp": pool_axes}
                if quantized:
                    scale_axes = ("layers", None, "act_kv_heads", None, None)
                    axes_tree.update({"ks": scale_axes, "vs": scale_axes})
                shardings = named_sharding_tree(mesh, axes_tree, rules)
                # Allocate sharded-from-birth: materializing the full pool
                # on one device first would OOM exactly the configurations
                # sharding exists for.
                self.cache = jax.jit(fresh_pools, out_shardings=shardings)()
            else:
                self.cache = fresh_pools()
            self.allocator = PageAllocator(
                self.n_pages, on_evict=self._on_pages_evicted,
                # Chain collection costs O(group depth) inside alloc on
                # the admission path — pay it only when something consumes
                # the payload (host-tier spills, handoff-pid attribution).
                group_payload=lambda: (
                    self.host_tier is not None or bool(self._handoff_pids)
                ),
            )
            # Host-RAM prefix-cache tier (ISSUE 13, infer/host_tier.py):
            # LRU-evicted published pages spill their KV bytes to a
            # size-capped host store (one batched device_get per tick,
            # _process_spills) and swap back in on admission miss
            # (_host_swap_in) — the effective shared-prefix working set
            # becomes a config knob instead of a hardware constant.
            per_val = (
                model_cfg.num_layers * model_cfg.num_kv_heads
                * page_size * model_cfg.head_dim
            )
            if quantized:
                scale_vals = (
                    model_cfg.num_layers * model_cfg.num_kv_heads * page_size
                )
                self.page_bytes = 2 * per_val + 2 * scale_vals * 4
            else:
                self.page_bytes = 2 * per_val * dt.itemsize
            if host_tier_mb < 0:
                raise ValueError(
                    f"host_tier_mb must be >= 0, got {host_tier_mb}"
                )
            if spill_max_pages_per_tick < 1:
                raise ValueError(
                    f"spill_max_pages_per_tick must be >= 1, got "
                    f"{spill_max_pages_per_tick}"
                )
            if host_tier_mb:
                from ditl_tpu.infer.host_tier import HostTier

                self.host_tier = HostTier(int(host_tier_mb * 1024 * 1024))
            else:
                self.host_tier = None
            self._spill_max = int(spill_max_pages_per_tick)
            self._pending_spills: list[tuple[int, dict]] = []
            self._pending_spill_ids: set[int] = set()
            self._tier_evictions_seen = 0
            # KV handoff import state (ISSUE 13, infer/kv_transfer.py):
            # physical pages installed by import_kv, so admission can
            # attribute their first reuse to the `handoff` tier label; plus
            # the measured device_put bandwidth the gateway's transfer-cost
            # model reads from /health.
            self._handoff_pids: set[int] = set()
            self.kv_import_bytes = 0
            self.kv_import_seconds = 0.0
            self._install_progs: dict = {}
            self._table = np.zeros((n_slots, self.maxp), np.int32)
            # Device-resident mirror, re-uploaded only when the host table
            # changes (admission / slot free): a per-tick jnp.asarray would
            # add one host->device transfer to EVERY tick's dispatch stream.
            self._table_dirty = True
            self._table_dev: Any = None
            self._slot_pages: list[list[int]] = [[] for _ in range(n_slots)]
            self.limits = jnp.zeros((n_slots,), jnp.int32)
            if admission not in ("reserve", "optimistic"):
                raise ValueError(
                    f"admission must be 'reserve' or 'optimistic', "
                    f"got {admission!r}"
                )
            self.admission = admission
            self.preemptions = 0
            # Anti-thrash hysteresis (VERDICT r4 weak #7): when the pool
            # barely covers the actual working set, optimistic admission
            # preempt-thrashes — resume prefills burn more device time
            # than the decode they enable (the honest −45% row in
            # BASELINE.md). Per WINDOW of ticks the engine compares
            # resume-prefilled tokens against generated tokens; past the
            # engage ratio NEW admissions reserve worst-case pages
            # (degrade toward reserve mode, in-flight footprints keep
            # topping up), releasing only when a full window stays below
            # the release ratio. Both counters are deterministic functions
            # of replicated scheduler state, so pod replicas flip the
            # switch on the same tick — no freeze needed (unlike the
            # timing-derived speculation threshold).
            if thrash_window < 1:
                raise ValueError(
                    f"thrash_window must be >= 1, got {thrash_window}"
                )
            self._thrash_window = int(thrash_window)  # ticks per window
            self._thrash_engage = 0.5  # resume-prefill / generated tokens
            self._thrash_release = 0.1
            self._win_ticks = 0
            self._win_resume_tokens = 0
            self._win_gen_tokens = 0
            self._degraded = False
            self.admission_degrades = 0  # windows that ENGAGED the guard
            self.resume_prefill_tokens = 0  # lifetime thrash cost
        else:
            if admission != "reserve":
                raise ValueError(
                    "admission='optimistic' requires cache_mode='paged' "
                    "(the contiguous cache has no pages to reclaim)"
                )
            if host_tier_mb:
                raise ValueError(
                    "host_tier_mb requires cache_mode='paged' (the host "
                    "tier spills and swaps KV pages)"
                )
            self.host_tier = None
            self.admission = admission
            self.preemptions = 0
            self.cache = init_cache(model_cfg, n_slots, self.smax)
            if mesh is not None:
                from ditl_tpu.infer.cache import cache_logical_axes
                from ditl_tpu.parallel.sharding import (
                    named_sharding_tree,
                    seq_shards,
                )

                seq_n = seq_shards(mesh, rules)
                if seq_n > 1 and self.smax % seq_n:
                    raise ValueError(
                        f"sequence-sharded serving needs max context "
                        f"{self.smax} divisible by the sequence axis {seq_n}"
                    )
                self.cache = jax.device_put(
                    self.cache,
                    named_sharding_tree(
                        mesh,
                        cache_logical_axes(model_cfg, seq_sharded=seq_n > 1),
                        rules,
                    ),
                )
        # Measured prefill throughput (ISSUE 13): accumulated over
        # page-warming prefills only (register_prefix / export_kv), which
        # run off the serving hot path and are SYNCED before the clock
        # closes — ordinary admission prefills are async-dispatched, and
        # their dispatch time is not device time. /health exposes the
        # derived tok/s as the re-prefill side of the gateway's KV-handoff
        # transfer-cost model (absent until something warmed; the model's
        # floors cover that).
        self.prefill_tokens_total = 0
        self.prefill_seconds_total = 0.0
        self.cur = jnp.full((n_slots,), tokenizer.pad_id, jnp.int32)
        self.pos = jnp.zeros((n_slots,), jnp.int32)
        self.temps = jnp.zeros((n_slots,), jnp.float32)
        self.top_ps = jnp.ones((n_slots,), jnp.float32)
        # Multi-LoRA serving: when the params tree is an adapter STACK
        # (models/lora.stack_adapters; leaves (L, n_adapters, d, r)), each
        # slot carries its adapter id — a per-row gather inside every
        # program, so requests with different adapters share decode ticks
        # (slot 0 convention: the base model).
        lora = params.get("layers", {}).get("lora") or {}
        self.multi_lora = bool(lora) and next(iter(lora.values()))["a"].ndim == 4
        self.n_adapters = (
            next(iter(lora.values()))["a"].shape[1] if self.multi_lora else 0
        )
        self.adapters = jnp.zeros((n_slots,), jnp.int32)
        # Adapter lifecycle plane (ISSUE 16, infer/adapters.py): attached
        # by AdapterRegistry.bind_engine; annotates terminal usage rows
        # with the adapter name and bills the gather cost to the OWNING
        # tenant. None = static stack (or no stack) — zero overhead.
        self.adapter_registry = None
        # One PRNG stream per slot: per-request seeds stay reproducible no
        # matter which other requests share the batch.
        self.keys = jax.vmap(jax.random.key)(jnp.arange(n_slots, dtype=jnp.uint32))
        self._base_seed = seed

        self._slots: list[Request | None] = [None] * n_slots
        # Admission queue, kept sorted by (SLO class rank, req_id) — FIFO
        # within a class, interactive ahead of batch ahead of best_effort.
        # A preempted request re-enters with its ORIGINAL req_id, so it
        # lands at the front of its class (the old appendleft semantics,
        # scoped to the class). Plain list: depths are bounded by max_queue
        # and every consumer below indexes/pops the head.
        self._queue: list[Request] = []
        self._completed: dict[int, Request] = {}
        # Double-buffered (pipelined) ticks: dispatch tick N+1 before
        # fetching tick N's outputs, so the host→device dispatch and
        # device→host fetch round trips (the dominant per-tick cost on
        # remote-transport devices, and real on local TPU-VMs too) overlap
        # with device compute instead of serializing with it. Harvest and
        # admission lag one tick; outputs are token-identical (per-slot RNG
        # derives from the request seed, never from tick alignment).
        self.pipeline_ticks = bool(pipeline_ticks)
        self._pending_fetch: tuple | None = None
        self._next_id = 0
        self.tick_count = 0  # scheduler ticks (the chaos seam's step index)
        self._prefill_cache: dict[int, Any] = {}
        self._decode_cache: dict[tuple[bool, bool], Any] = {}
        # Prefix cache: prompt-prefix tokens -> (1-row KV slice over P slots,
        # last-token logits, real length). Explicit registration, not
        # automatic block hashing: slots are contiguous (not paged), so
        # sharing is prefix-granular by design (see register_prefix).
        self._prefixes: dict[tuple[int, ...], tuple[Any, Any, int]] = {}
        self._prefix_prefill: dict[int, Any] = {}
        self._seed_cache: dict[int, Any] = {}
        self._suffix_prefill: dict[int, Any] = {}  # keyed by suffix bucket
        self._first_sampler: Any = None
        import collections as _collections

        # (s_bucket, ctx_pages) -> compiled prefill program, LRU-bounded
        self._paged_prefill: _collections.OrderedDict = _collections.OrderedDict()
        self._paged_decode: dict[tuple[bool, bool], Any] = {}

        # -- speculative decode ticks -----------------------------------
        self.speculative = speculative
        if speculative:
            if spec_k < 1:
                raise ValueError(f"spec_k must be >= 1, got {spec_k}")
            if not (1 <= spec_min_ngram <= spec_ngram):
                raise ValueError(
                    f"spec_min_ngram must be in [1, spec_ngram], got "
                    f"{spec_min_ngram}"
                )
            self.spec_k = spec_k
            self.spec_ngram = spec_ngram
            self.spec_min_ngram = spec_min_ngram
            # Default rounds-per-tick matches the PLAIN tick's device cost,
            # not its token count: a verify round costs ~2.5 decode steps
            # (the threshold prior), so decode_chunk/2.5 rounds keep tick
            # latency comparable while emitting up to (k+1)x more tokens
            # per tick — which is also what amortizes the per-tick host
            # dispatch on remote-transport setups. Rows that finish
            # mid-tick wait for the tick end, same as the plain chunk.
            self.spec_rounds = spec_rounds or max(
                1, round(decode_chunk / 2.5)
            )
            if self.spec_rounds < 1:
                raise ValueError(f"spec_rounds must be >= 1, got {spec_rounds}")
            # None => self-calibrating threshold: the engine measures the
            # real per-round verify cost and per-step decode cost from its
            # own tick timings (compile calls excluded) and uses their
            # ratio — the breakeven tokens-per-verify-forward — instead of
            # a hardcoded chip-specific constant (VERDICT r2 weak #4).
            self._spec_threshold_cfg = spec_threshold
            self._plain_step_ms: float | None = None
            self._spec_round_ms: float | None = None
            self._timed_plain_keys: set = set()
            self._timed_spec = False
            # Pipelined serving self-calibrates through bounded SERIAL
            # probe ticks (see step): lagged pipelined intervals measure
            # the pipeline period, not device cost, so the first ticks run
            # dispatch+fetch back-to-back to time both paths, then
            # double-buffering takes over with the measured threshold
            # (VERDICT r4 weak #3). The budget caps the warmup when one
            # path never runs (e.g. acceptance so high no plain tick is
            # ever chosen — the threshold is moot there anyway).
            self._probe_ticks_left = 16 if pipeline_ticks else 0
            self._probe_timing = False
            self.spec_probe_every = spec_probe_every
            self._spec_ema_w = spec_ema
            self.spec_acceptance_ema: float | None = None
            self.spec_ticks = 0
            self._tick_no = 0
            self._spec_decode: dict[tuple, Any] = {}  # key: (paged?, sampled?)
        # -- model-based drafting (draft_params + draft_cfg) -------------
        # A small DRAFT model supplies speculative tokens instead of prompt
        # lookup: k sequential draft-model decode steps inside the spec
        # tick (the drafter is small, so k tiny forwards cost less than the
        # big model's k+1-wide verify), verified by the target exactly as
        # lookup drafts are — exactness never depends on the drafter. The
        # draft model keeps its own contiguous per-slot KV cache: feeding
        # the pending ``cur`` at ``pos`` each round writes the KV the
        # previous round's bonus token never got (self-healing), and
        # rejected positions' stale KV stays masked by position, so
        # rollback is free. Acceptance on natural text comes from the
        # drafter's quality (train one on your data), not the workload's
        # self-similarity — the lever prompt-lookup cannot reach.
        self.spec_draft = "lookup"
        if draft_params is not None or draft_cfg is not None:
            if draft_params is None or draft_cfg is None:
                raise ValueError(
                    "draft_params and draft_cfg must be given together"
                )
            if not speculative:
                raise ValueError(
                    "a draft model needs speculative=True (it drafts for "
                    "speculative ticks)"
                )
            if draft_cfg.vocab_size != model_cfg.vocab_size:
                raise ValueError(
                    f"draft vocab {draft_cfg.vocab_size} must match the "
                    f"target's {model_cfg.vocab_size} (same token space)"
                )
            if draft_cfg.max_seq_len < self.smax:
                raise ValueError(
                    f"draft max_seq_len {draft_cfg.max_seq_len} is below "
                    f"the serving context cap {self.smax}"
                )
            self.spec_draft = "model"
            self.draft_params = draft_params
            self.draft_cfg = draft_cfg
            self.draft_cache = init_cache(draft_cfg, n_slots, self.smax)
            if mesh is not None:
                from ditl_tpu.infer.cache import cache_logical_axes
                from ditl_tpu.parallel.sharding import named_sharding_tree

                self.draft_cache = jax.device_put(
                    self.draft_cache,
                    named_sharding_tree(
                        mesh, cache_logical_axes(draft_cfg), rules
                    ),
                )
            self._draft_prefill_cache: dict[int, Any] = {}
            self._draft_suffix_cache: dict[int, Any] = {}

        # Per-slot token history (prompt + generated incl. the pending
        # ``cur``) — the draft source for speculative ticks. Rides the tick
        # carry; host writes it only at admission. 1-wide dummy when
        # speculation is off or drafting is model-based (the programs take
        # it either way; XLA drops the dead argument).
        self.hist = jnp.zeros(
            (n_slots,
             self.smax if speculative and self.spec_draft == "lookup" else 1),
            jnp.int32,
        )

        # -- per-token logprobs (OpenAI semantics) -----------------------
        # ``logprobs_k > 0`` arms per-token logprob tracking: every prefill
        # and decode program additionally computes the chosen token's
        # logprob and the top-k alternatives FROM THE RAW distribution
        # (before temperature/top-k/top-p shaping — the same semantics as
        # engine.Generator's lock-step logprobs). The stats of the pending
        # ``cur`` ride engine state between ticks, exactly like ``cur``
        # itself. Costs one (B, V) log-softmax + top-k per decode step when
        # armed; requests that don't ask for logprobs simply don't consume
        # the outputs. Speculative ticks carry the stats too (the verify
        # logits already score every emitted token — _spec_lp_round), so
        # logprobs and speculation compose.
        if logprobs_k < 0:
            raise ValueError(f"logprobs_k must be >= 0, got {logprobs_k}")
        self.logprobs_k = logprobs_k
        if logprobs_k > 0:
            self.lp_chosen = jnp.zeros((n_slots,), jnp.float32)
            self.lp_ids = jnp.zeros((n_slots, logprobs_k), jnp.int32)
            self.lp_top = jnp.zeros((n_slots, logprobs_k), jnp.float32)

        # -- grammar-constrained decoding (infer/grammar.py) -------------
        # ``fsm_capacity > 0`` arms guided decoding: a device-resident
        # (capacity, vocab) transition table holds every registered
        # grammar's token-level DFA; each slot carries one int32 FSM state.
        # Every sample site then costs ONE row gather + a ``where`` mask,
        # and the transition is one scalar gather — no host round-trips,
        # and unconstrained rows ride the FREE row (all-allowed identity,
        # so their sampled tokens are bit-identical to a guided-off
        # engine). Row conventions: table[s, t] >= 0 = allowed, value =
        # next state; -1 = masked (transition clamps to DEAD). Row 0 =
        # FREE (everything allowed, parks), row 1 = DEAD (permissive
        # trap — reached only by finished rows and discarded speculative
        # positions, and deliberately all-allowed so a masked row can
        # never be all -inf, which would NaN the sampling softmax).
        if fsm_capacity < 0:
            raise ValueError(f"fsm_capacity must be >= 0, got {fsm_capacity}")
        self.fsm_capacity = fsm_capacity
        self.guided = fsm_capacity > 0
        if self.guided:
            if fsm_capacity < 2:
                raise ValueError("fsm_capacity must be >= 2 (FREE + DEAD rows)")
            import threading as _threading

            v = model_cfg.vocab_size
            self._fsm_host = np.full((fsm_capacity, v), -1, np.int32)
            self._fsm_host[0, :] = 0  # FREE
            self._fsm_host[1, :] = 1  # DEAD
            self._fsm_used = 2
            self._fsm_dirty = True
            self._fsm_dev: Any = None
            self._grammars: dict[str, int] = {}
            # Registration may come from HTTP handler threads while the
            # driver thread is mid-tick (ThreadedEngine): the lock pairs
            # every host-table mutation with the dirty-check-and-upload so
            # a tick can never capture a half-installed grammar.
            self._fsm_lock = _threading.Lock()
            self.fstates = jnp.zeros((n_slots,), jnp.int32)

    # -- compiled programs --------------------------------------------------

    def _build_prefill(self, p_bucket: int):
        cfg, smax = self.cfg, self.smax

        def run(params, cache, ids, length, slot, temp, top_p, rng, aid,
                *fsm):
            # 1-row view of the shared cache: prefill never touches other slots.
            row = jax.tree.map(
                lambda c: jax.lax.dynamic_slice_in_dim(c, slot, 1, axis=1), cache
            )
            q_pos = jnp.arange(p_bucket, dtype=jnp.int32)
            # Empty-cache full prefill == causal self-attention over the
            # chunk: flash-kernel path (validity via segment ids).
            seg = (q_pos[None, :] < length).astype(jnp.int32)
            logits, row = llama.forward(
                params,
                ids,
                cfg,
                positions=q_pos[None],
                segment_ids=seg,
                cache=row,
                cache_index=jnp.int32(0),
                mesh=self.mesh,
                rules=self.rules,
                prefill_causal=True,
                adapter_ids=aid if self.multi_lora else None,
            )
            cache = jax.tree.map(
                lambda c, r: jax.lax.dynamic_update_slice_in_dim(c, r, slot, axis=1),
                cache,
                row,
            )
            last = logits[0, length - 1]
            masked = _fsm_mask(fsm[0], fsm[1], last) if self.guided else last
            first = sample_logits(
                masked[None], rng, temperature=temp,
                top_k=self.gen.top_k, top_p=top_p,
            )[0]
            fs = (_fsm_next(fsm[0], fsm[1], first),) if self.guided else ()
            if self.logprobs_k:
                c, i, t = _lp_stats(last[None], first[None], self.logprobs_k)
                return (cache, first, c[0], i[0], t[0], *fs)
            return (cache, first, *fs)

        return jax.jit(run, donate_argnums=(1,))

    def _build_decode(self, sampled: bool, topp: bool):
        """One decode program per (any-slot-sampled, any-top-p) combination:
        all-greedy ticks compile to pure argmax — no per-step vocab sort,
        softmax, or categorical that a ``where`` would discard. With
        ``speculative`` armed, the per-slot token history rides the carry so
        a later speculative tick drafts from fresh context."""
        cfg, smax, pad, eos = self.cfg, self.smax, self.tokenizer.pad_id, self.tokenizer.eos_id
        slots_iota = jnp.arange(smax, dtype=jnp.int32)
        chunk = self.decode_chunk
        track = self.speculative
        n_lp = self.logprobs_k

        guided = self.guided

        def run(params, cache, cur, pos, alive, temps, top_ps, keys, hist,
                adapters, *extra):
            ftab, fstates = (extra[0], extra[1]) if guided else (None, None)
            lp0 = extra[2:] if guided else extra

            def body(carry, _):
                cache, cur, pos, done, keys, hist, fst, lp = carry
                split = jax.vmap(lambda k: jax.random.split(k, 2))(keys)
                keys, subs = split[:, 0], split[:, 1]
                mask = (slots_iota[None, :] <= pos[:, None])[:, None, :]  # (B,1,Smax)
                logits, cache = llama.forward(
                    params,
                    cur[:, None],
                    cfg,
                    positions=pos[:, None],
                    cache=cache,
                    cache_index=pos,
                    attn_mask=mask,
                    mesh=self.mesh,
                    rules=self.rules,
                    adapter_ids=adapters if self.multi_lora else None,
                )
                step_logits = logits[:, 0]
                nxt = sample_logits(
                    _fsm_mask(ftab, fst, step_logits) if guided else step_logits,
                    subs,
                    temperature=temps if sampled else 0.0,
                    top_k=self.gen.top_k,
                    top_p=top_ps if topp else 1.0,
                )
                step_alive = ~done
                emit = jnp.where(step_alive, cur, pad)
                # The emitted stats are the PENDING ones — computed when
                # ``cur`` was sampled (previous step / prefill) — then the
                # pending slot is refilled with ``nxt``'s stats.
                ys = (emit, *lp) if n_lp else emit
                if n_lp:
                    lp = _lp_stats(step_logits, nxt, n_lp)
                done = done | (cur == eos)
                if guided:
                    # ``nxt`` is real only for rows still live after the
                    # EOS check — mirror the ``cur`` update exactly.
                    fst = jnp.where(done, fst, _fsm_next(ftab, fst, nxt))
                pos = jnp.where(step_alive, jnp.minimum(pos + 1, smax - 1), pos)
                cur = jnp.where(done, pad, nxt)
                if track:
                    from ditl_tpu.infer.speculative import _emit_rows

                    grow = (~done).astype(jnp.int32)
                    hist = _emit_rows(hist, cur[:, None], pos, grow)
                return (cache, cur, pos, done, keys, hist, fst, lp), ys

            fst0 = fstates if guided else jnp.zeros((), jnp.int32)
            (cache, cur, pos, done, keys, hist, fst, lp), ys = jax.lax.scan(
                body, (cache, cur, pos, ~alive, keys, hist, fst0, tuple(lp0)),
                None, length=chunk,
            )
            fs = (fst,) if guided else ()
            if n_lp:
                toks, c, i, t = ys
                return (cache, cur, pos, keys, hist, *fs, lp, toks.T,
                        c.T, jnp.swapaxes(i, 0, 1), jnp.swapaxes(t, 0, 1))
            return (cache, cur, pos, keys, hist, *fs, ys.T)  # ys: (chunk, B)

        return jax.jit(run, donate_argnums=(1,))

    def _build_draft_prefill(self, p_bucket: int):
        """Prefill one slot of the DRAFT model's cache with the prompt.
        No sampling: the drafter's first prediction happens inside the spec
        tick (feeding the pending ``cur`` at ``pos``). Always a full-prompt
        prefill — the drafter is small, and prefix seams (main-cache prefix
        reuse, chunked main prefill) don't apply to its private cache."""
        dcfg = self.draft_cfg

        def run(dparams, dcache, ids, length, slot):
            row = jax.tree.map(
                lambda c: jax.lax.dynamic_slice_in_dim(c, slot, 1, axis=1),
                dcache,
            )
            q_pos = jnp.arange(p_bucket, dtype=jnp.int32)
            seg = (q_pos[None, :] < length).astype(jnp.int32)
            _, row = llama.forward(
                dparams, ids, dcfg, positions=q_pos[None], segment_ids=seg,
                cache=row, cache_index=jnp.int32(0),
                mesh=self.mesh, rules=self.rules, prefill_causal=True,
            )
            return jax.tree.map(
                lambda c, r: jax.lax.dynamic_update_slice_in_dim(
                    c, r, slot, axis=1
                ),
                dcache,
                row,
            )

        return jax.jit(run, donate_argnums=(1,))

    def _build_draft_suffix_prefill(self, s_bucket: int):
        """Suffix continuation of the draft cache at an offset — the
        chunked form of ``_build_draft_prefill`` (same shape as the target
        model's suffix prefill: the bucket tail past the chunk's real
        tokens writes garbage that the draft scan overwrites before
        attending it, so no valid-length masking is needed)."""
        dcfg = self.draft_cfg
        slots_iota = jnp.arange(self.smax, dtype=jnp.int32)

        def run(dparams, dcache, ids, offset, slot):
            row = jax.tree.map(
                lambda c: jax.lax.dynamic_slice_in_dim(c, slot, 1, axis=1),
                dcache,
            )
            q_pos = offset + jnp.arange(s_bucket, dtype=jnp.int32)
            mask = slots_iota[None, None, :] <= q_pos[None, :, None]
            _, row = llama.forward(
                dparams, ids, dcfg, positions=q_pos[None],
                cache=row, cache_index=offset, attn_mask=mask,
                mesh=self.mesh, rules=self.rules,
            )
            return jax.tree.map(
                lambda c, r: jax.lax.dynamic_update_slice_in_dim(
                    c, r, slot, axis=1
                ),
                dcache,
                row,
            )

        return jax.jit(run, donate_argnums=(1,))

    def _draft_prefill(self, req: Request, slot: int,
                       ctx: list[int] | None = None) -> None:
        """Admission hook (model drafting only): load the context into the
        draft model's cache for ``slot``. ``ctx`` defaults to the prompt;
        preemption resume passes ``prompt + tokens`` — the draft cache has
        no device-captured frontier, so every position up to the resumed
        ``pos`` must be re-fed or the drafter would attend the prior
        occupant's stale KV (ADVICE r4). Long contexts honor
        ``prefill_chunk`` (resume contexts reach buckets no prompt does;
        one fixed chunk program beats a pow2 ladder of mid-serving
        compiles)."""
        if self.spec_draft != "model":
            return
        if ctx is None:
            ctx = req.prompt
        if self.prefill_chunk and len(ctx) > self.prefill_chunk:
            d, step = 0, self.prefill_chunk
            while d < len(ctx):
                s = min(step, len(ctx) - d)
                s_bucket = self._chunk_bucket(d, s)
                if s_bucket not in self._draft_suffix_cache:
                    logger.info(
                        "compiling draft suffix prefill for bucket %d",
                        s_bucket,
                    )
                    self._draft_suffix_cache[s_bucket] = (
                        self._build_draft_suffix_prefill(s_bucket)
                    )
                ids = np.full((1, s_bucket), self.tokenizer.pad_id, np.int32)
                ids[0, :s] = ctx[d: d + s]
                self.draft_cache = self._draft_suffix_cache[s_bucket](
                    self.draft_params, self.draft_cache, jnp.asarray(ids),
                    jnp.int32(d), jnp.int32(slot),
                )
                d += s
            return
        p_bucket = min(_next_pow2(len(ctx), floor=16), self.smax)
        if p_bucket not in self._draft_prefill_cache:
            logger.info("compiling draft prefill for bucket %d", p_bucket)
            self._draft_prefill_cache[p_bucket] = self._build_draft_prefill(
                p_bucket
            )
        ids = np.full((1, p_bucket), self.tokenizer.pad_id, np.int32)
        ids[0, : len(ctx)] = ctx
        self.draft_cache = self._draft_prefill_cache[p_bucket](
            self.draft_params, self.draft_cache, jnp.asarray(ids),
            jnp.int32(len(ctx)), jnp.int32(slot),
        )

    def _draft_scan(self, dparams, dcache, cur, pos, smax):
        """k greedy draft-model decode steps from the pending ``cur``:
        returns (new dcache, (B, k) drafts). The scan runs k+1 feeds —
        ``cur`` then ALL k drafts — so every drafted token's KV is written
        (feeding only k would leave the last draft's position unwritten
        forever on a full-accept round, and the next scan's mask would
        attend the hole); the final output token is discarded. Feeding
        ``cur`` at ``pos`` also writes the KV the previous round's bonus
        token never got, and stale KV beyond a row's position stays masked
        until the position is re-fed — so rejected drafts need no
        rollback. ``dparams`` is a program ARGUMENT (a closure constant
        would bake the draft weights into the executable)."""
        dcfg, k = self.draft_cfg, self.spec_k
        slots_iota = jnp.arange(smax, dtype=jnp.int32)

        def step(carry, _):
            dcache, tok, p = carry
            mask = (slots_iota[None, :] <= p[:, None])[:, None, :]
            lg, dcache = llama.forward(
                dparams, tok[:, None], dcfg, positions=p[:, None],
                cache=dcache, cache_index=p, attn_mask=mask,
                mesh=self.mesh, rules=self.rules,
            )
            nxt = jnp.argmax(lg[:, 0], axis=-1).astype(jnp.int32)
            return (dcache, nxt, jnp.minimum(p + 1, smax - 1)), nxt

        (dcache, _, _), drafts = jax.lax.scan(
            step, (dcache, cur, pos), None, length=k + 1
        )
        return dcache, drafts.T[:, :k]  # (B, k); the k+1-th is discarded

    def _fsm_spec_path(self, ftab, fstates, draft):
        """Grammar states along the speculative draft path: ``path[:, 0]``
        is the row's current state, ``path[:, j+1]`` the state after
        consuming ``draft[:, j]``. A disallowed draft token clamps to the
        DEAD trap — its own position was already masked -inf under the
        PRE-transition state, so acceptance rejects there and every
        DEAD-masked later position is discarded; k is small (static), so
        the walk unrolls into k scalar-gather steps."""
        states = [fstates]
        for j in range(draft.shape[1]):
            states.append(_fsm_next(ftab, states[-1], draft[:, j]))
        return jnp.stack(states, axis=1)  # (B, k+1)

    def _spec_accept(self, logits, tokens_in, subs, temps, top_ps,
                     sampled: bool):
        """Shared acceptance step for spec ticks: returns ``(n_acc,
        nxt_tok)`` — accepted-draft count and the pending token. Greedy
        programs compile the pure exact-match/argmax rule; sampled programs
        use point-mass rejection sampling (speculative.spec_sample_tokens),
        whose greedy-row limit is bit-identical to the exact-match rule."""
        k = self.spec_k
        if sampled:
            from ditl_tpu.infer.speculative import spec_sample_tokens

            return spec_sample_tokens(
                logits, tokens_in[:, 1:], subs, temps, top_ps,
                top_k=self.gen.top_k,
            )
        cand = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # (B, K+1)
        eq = tokens_in[:, 1:] == cand[:, :k]
        n_acc = jnp.sum(jnp.cumprod(eq.astype(jnp.int32), axis=-1), axis=-1)
        nxt = jnp.take_along_axis(cand, n_acc[:, None], axis=1)[:, 0]
        return n_acc, nxt

    def _spec_lp_round(self, logits, draft, n_acc, nxt_tok, lp, bufs, n_out,
                       e):
        """Per-round logprob bookkeeping for spec ticks (``logprobs_k > 0``):
        emit-index j's stats are the PENDING ones for j=0 (``cur``, scored
        when it was chosen) and, for j >= 1, ``draft[j-1]`` scored by the
        verify logits at position j-1 — the raw distribution, identical
        semantics to the plain tick. The new pending stats score
        ``nxt_tok`` under the distribution that chose it
        (``logits[:, n_acc]``)."""
        from ditl_tpu.infer.speculative import _emit_rows

        n_lp = self.logprobs_k
        pc, pi, pt = lp
        bc, bi, bt = bufs
        k = logits.shape[1] - 1
        lp_all = jax.nn.log_softmax(logits[:, :k].astype(jnp.float32), -1)
        chosen_d = jnp.take_along_axis(lp_all, draft[..., None], 2)[..., 0]
        top_t, top_i = jax.lax.top_k(lp_all, n_lp)  # (B, k, N)
        seq_c = jnp.concatenate([pc[:, None], chosen_d], axis=1)
        seq_i = jnp.concatenate([pi[:, None, :], top_i.astype(jnp.int32)],
                                axis=1)
        seq_t = jnp.concatenate([pt[:, None, :], top_t], axis=1)
        bc = _emit_rows(bc, seq_c, n_out, e)
        bi = _emit_rows(bi, seq_i, n_out, e)
        bt = _emit_rows(bt, seq_t, n_out, e)
        sel = jnp.take_along_axis(logits, n_acc[:, None, None], axis=1)[:, 0]
        return _lp_stats(sel, nxt_tok, n_lp), (bc, bi, bt)

    def _build_spec_decode(self, sampled: bool = False):
        """Speculative decode tick, contiguous cache (module docstring):
        ``spec_rounds`` rounds of draft → (B, K+1) verify forward → accept.
        ``sampled=False`` compiles the pure greedy exact-match program;
        ``sampled=True`` accepts by point-mass rejection sampling (exact in
        distribution under each row's temperature/top-k/top-p; greedy rows
        in the batch still take the argmax rule bit-exactly). Emissions are
        compacted per row (prefix of the output buffer) with a per-row
        count, because a round emits 1..K+1 tokens — harvest consumes
        ``toks[b, :counts[b]]`` instead of pad-scanning."""
        cfg, smax = self.cfg, self.smax
        pad, eos = self.tokenizer.pad_id, self.tokenizer.eos_id
        k, rounds = self.spec_k, self.spec_rounds
        ngram, min_ngram = self.spec_ngram, self.spec_min_ngram
        out_len = rounds * (k + 1)
        slots_iota = jnp.arange(smax, dtype=jnp.int32)
        q_idx = jnp.arange(k + 1, dtype=jnp.int32)

        from ditl_tpu.infer.speculative import _emit_rows, device_lookup_draft

        n_lp = self.logprobs_k

        guided = self.guided
        model_draft = self.spec_draft == "model"

        def run(params, cache, cur, pos, alive, hist, temps, top_ps, keys,
                adapters, *extra):
            i = 0
            dparams = dcache0 = None
            if model_draft:
                dparams, dcache0 = extra[0], extra[1]
                i = 2
            ftab, fstates = (
                (extra[i], extra[i + 1]) if guided else (None, None)
            )
            lp0 = extra[i + 2 :] if guided else extra[i:]
            n_b = pos.shape[0]
            out0 = jnp.full((n_b, out_len), pad, jnp.int32)
            zeros = jnp.zeros((n_b,), jnp.int32)
            bufs0 = (
                (jnp.zeros((n_b, out_len), jnp.float32),
                 jnp.zeros((n_b, out_len, n_lp), jnp.int32),
                 jnp.zeros((n_b, out_len, n_lp), jnp.float32))
                if n_lp else ()
            )

            def body(carry, _):
                (cache, dcache, cur, pos, done, hist, out, n_out, rr, keys,
                 fst, lp, bufs) = carry
                live = ~done
                split = jax.vmap(lambda kk: jax.random.split(kk, 2))(keys)
                keys, subs = split[:, 0], split[:, 1]
                if model_draft:
                    dcache, draft = self._draft_scan(
                        dparams, dcache, cur, pos, smax
                    )
                else:
                    # ctx_len = pos + 1: hist[pos] holds the pending ``cur``.
                    draft = device_lookup_draft(
                        hist, jnp.minimum(pos + 1, smax), k=k, ngram=ngram,
                        min_ngram=min_ngram,
                    )  # (B, k)
                tokens_in = jnp.concatenate([cur[:, None], draft], axis=1)
                positions = pos[:, None] + q_idx[None, :]  # (B, K+1)
                mask = slots_iota[None, None, :] <= positions[:, :, None]
                logits, cache = llama.forward(
                    params, tokens_in, cfg, positions=positions,
                    cache=cache, cache_index=pos, attn_mask=mask,
                    mesh=self.mesh, rules=self.rules,
                    adapter_ids=adapters if self.multi_lora else None,
                )
                if guided:
                    # Mask every verify position under its path state: a
                    # disallowed draft token rejects at its own position
                    # (p=0 / argmax mismatch), so constrained rows accept
                    # only grammar-legal prefixes — and the bonus token is
                    # sampled under the post-acceptance state's mask.
                    path = self._fsm_spec_path(ftab, fst, draft)
                    ver_logits = _fsm_mask(ftab, path, logits)
                else:
                    ver_logits = logits
                n_acc, nxt_tok = self._spec_accept(
                    ver_logits, tokens_in, subs, temps, top_ps, sampled
                )
                # Emission sequence: [cur, accepted drafts...] — index j
                # emits the token at global position pos + j. The pending
                # token (``nxt_tok``) becomes the next round's ``cur`` and
                # is NOT emitted (same convention as the plain tick).
                in_span = q_idx[None, :] <= n_acc[:, None]
                is_term = (tokens_in == eos) | (tokens_in == pad)
                term_before = (
                    jnp.cumsum(is_term.astype(jnp.int32), axis=1)
                    - is_term.astype(jnp.int32)
                ) > 0
                emit = in_span & ~term_before & live[:, None]
                e = jnp.sum(emit.astype(jnp.int32), axis=1)  # (B,)
                hit_term = jnp.any(emit & is_term, axis=1)
                out = _emit_rows(out, tokens_in, n_out, e)
                if n_lp:
                    # Buffers share ``out``'s PRE-advance offsets (column-
                    # aligned with the emitted tokens).
                    lp, bufs = self._spec_lp_round(
                        logits, draft, n_acc, nxt_tok, lp, bufs, n_out, e
                    )
                n_out = n_out + e
                # History gains positions pos+1 .. pos+e: the accepted
                # drafts, with the pending token at index n_acc.
                append_seq = jnp.where(
                    q_idx[None, :] == n_acc[:, None],
                    nxt_tok[:, None],
                    jnp.concatenate([draft, zeros[:, None]], axis=1),
                )
                grow = jnp.where(hit_term, 0, e)
                if not model_draft:
                    hist = _emit_rows(
                        hist, append_seq, jnp.minimum(pos + 1, smax), grow
                    )
                pos = jnp.where(
                    live, jnp.minimum(pos + e, smax - 1), pos
                )
                done = done | hit_term
                if guided:
                    s_at = jnp.take_along_axis(path, n_acc[:, None], 1)[:, 0]
                    fst = jnp.where(done, fst, _fsm_next(ftab, s_at, nxt_tok))
                cur = jnp.where(done, pad, nxt_tok)
                rr = rr + live.astype(jnp.int32)
                return (cache, dcache, cur, pos, done, hist, out, n_out, rr,
                        keys, fst, lp, bufs), None

            fst0 = fstates if guided else jnp.zeros((), jnp.int32)
            dc0 = dcache0 if model_draft else jnp.zeros((), jnp.int32)
            (cache, dcache, cur, pos, done, hist, out, n_out, rr, keys, fst,
             lp, bufs), _ = jax.lax.scan(
                body,
                (cache, dc0, cur, pos, ~alive, hist, out0, zeros, zeros,
                 keys, fst0, tuple(lp0), bufs0),
                None, length=rounds,
            )
            fs = (fst,) if guided else ()
            dc = (dcache,) if model_draft else ()
            return (cache, *dc, cur, pos, hist, keys, *fs, out, n_out, rr,
                    lp, bufs)

        donate = (1, 11) if model_draft else (1,)
        return jax.jit(run, donate_argnums=donate)

    # -- prefix caching ------------------------------------------------------

    def _build_prefix_prefill(self, p_bucket: int):
        """Prefill a standalone 1-row cache of ``p_bucket`` slots; returns the
        KV slice plus the last real token's logits (for prompts that are
        exactly the prefix)."""
        cfg = self.cfg

        def run(params, ids, length):
            row = init_cache(cfg, 1, p_bucket)
            q_pos = jnp.arange(p_bucket, dtype=jnp.int32)
            slots = jnp.arange(p_bucket, dtype=jnp.int32)
            mask = (slots[None, None, :] <= q_pos[None, :, None]) & (
                slots[None, None, :] < length
            )
            logits, row = llama.forward(
                params, ids, cfg, positions=q_pos[None],
                cache=row, cache_index=jnp.int32(0), attn_mask=mask,
                mesh=self.mesh, rules=self.rules,
            )
            return row, logits[0, length - 1]

        return jax.jit(run)

    def _build_seed(self, p_bucket: int):
        """Copy a registered prefix's KV slice into one slot of the shared
        cache (slots 0..p_bucket of the slot's sequence axis)."""

        def run(cache, row, slot):
            return jax.tree.map(
                lambda c, r: jax.lax.dynamic_update_slice(
                    c, r.astype(c.dtype), (0, slot, 0) + (0,) * (c.ndim - 3)
                ),
                cache,
                row,
            )

        return jax.jit(run, donate_argnums=(0,))

    def _build_suffix_prefill(self, s_bucket: int):
        """Prefill only the suffix of a prompt whose first ``offset`` tokens
        are already seeded in the slot's cache; same write-then-unmask
        invariant as full prefill (garbage beyond the suffix is overwritten
        by decode writes before ``pos`` unmasks it)."""
        cfg, smax = self.cfg, self.smax
        slots_iota = jnp.arange(smax, dtype=jnp.int32)

        def run(params, cache, ids, offset, s_len, slot, temp, top_p, rng,
                aid, *fsm):
            row = jax.tree.map(
                lambda c: jax.lax.dynamic_slice_in_dim(c, slot, 1, axis=1), cache
            )
            q_pos = offset + jnp.arange(s_bucket, dtype=jnp.int32)
            mask = slots_iota[None, None, :] <= q_pos[None, :, None]
            logits, row = llama.forward(
                params, ids, cfg, positions=q_pos[None],
                cache=row, cache_index=offset, attn_mask=mask,
                mesh=self.mesh, rules=self.rules,
                adapter_ids=aid if self.multi_lora else None,
            )
            cache = jax.tree.map(
                lambda c, r: jax.lax.dynamic_update_slice_in_dim(c, r, slot, axis=1),
                cache,
                row,
            )
            last = logits[0, s_len - 1]
            masked = _fsm_mask(fsm[0], fsm[1], last) if self.guided else last
            first = sample_logits(
                masked[None], rng, temperature=temp, top_k=self.gen.top_k,
                top_p=top_p,
            )[0]
            fs = (_fsm_next(fsm[0], fsm[1], first),) if self.guided else ()
            if self.logprobs_k:
                c, i, t = _lp_stats(last[None], first[None], self.logprobs_k)
                return (cache, first, c[0], i[0], t[0], *fs)
            return (cache, first, *fs)

        return jax.jit(run, donate_argnums=(1,))

    # -- paged programs ------------------------------------------------------

    def _build_paged_prefill(self, s_bucket: int, ctx_pages: int):
        """Prefill ``s_bucket`` prompt tokens of one slot in paged mode.

        The slot's resident pages are gathered into a transient contiguous
        row (prefill is compute-bound; one context-sized copy is noise), the
        ordinary cached forward runs against it, and the chunk's K/V pages
        are scattered back into the pool at ``write_pids``. ``ctx_pages``
        bounds the gather to a bucket of the pages actually holding context
        (gathering the full worst-case table made long chunked prefills
        quadratic in max context). Chunk starts are page-aligned by
        construction (prefill_chunk and prefix matches are multiples of
        page_size), so the chunk covers whole pages; bucket tail beyond
        ``s_len`` writes garbage that stays masked until decode overwrites
        it (the same write-then-unmask invariant as the contiguous suffix
        prefill)."""
        cfg, ps = self.cfg, self.page_size
        maxp = ctx_pages
        n_wp = s_bucket // ps
        buf = maxp * ps + s_bucket
        buf_iota = jnp.arange(buf, dtype=jnp.int32)

        cd = jnp.dtype(cfg.dtype)
        quantized = cfg.kv_cache_dtype == "int8"

        def run(params, pools, table_row, ids, offset, s_len, temp, top_p,
                rng, write_pids, aid, *fsm):
            kp, vp = pools["kp"], pools["vp"]
            L, _, K, _, D = kp.shape

            def to_row(pool, scales=None):
                # (L, ctx_pages, K, ps, D) [+ scales] -> (L, 1, ctx*ps, K, D)
                if maxp == 0:
                    return jnp.zeros((L, 1, 0, K, D), cd)
                g = pool[:, table_row]
                if scales is not None:
                    sc = scales[:, table_row][:, :, :, 0, :]  # (L, maxp, K, ps)
                    g = (g.astype(jnp.float32) * sc[..., None]).astype(cd)
                g = jnp.swapaxes(g, 2, 3)
                return g.reshape(L, 1, maxp * ps, K, D)

            ctx_k = to_row(kp, pools.get("ks"))
            ctx_v = to_row(vp, pools.get("vs"))
            zeros = jnp.zeros((L, 1, s_bucket, K, D), ctx_k.dtype)
            row = {
                "k": jnp.concatenate([ctx_k, zeros], axis=2),
                "v": jnp.concatenate([ctx_v, zeros], axis=2),
            }
            q_pos = offset + jnp.arange(s_bucket, dtype=jnp.int32)
            if maxp == 0:
                # No context pages (offset 0): pure causal self-attention
                # over the chunk — flash-kernel path.
                seg = (jnp.arange(s_bucket, dtype=jnp.int32)[None, :]
                       < s_len).astype(jnp.int32)
                logits, row = llama.forward(
                    params, ids, cfg, positions=q_pos[None], segment_ids=seg,
                    cache=row, cache_index=offset,
                    mesh=self.mesh, rules=self.rules, prefill_causal=True,
                    adapter_ids=aid if self.multi_lora else None,
                )
            else:
                mask = buf_iota[None, None, :] <= q_pos[None, :, None]
                logits, row = llama.forward(
                    params, ids, cfg, positions=q_pos[None],
                    cache=row, cache_index=offset, attn_mask=mask,
                    mesh=self.mesh, rules=self.rules,
                    adapter_ids=aid if self.multi_lora else None,
                )
            def to_pages(r):  # (L, 1, s_bucket, K, D) -> (L, n_wp, K, ps, D)
                chunk = jax.lax.dynamic_slice_in_dim(r, offset, s_bucket, axis=2)
                return jnp.swapaxes(chunk.reshape(L, n_wp, ps, K, D), 2, 3)

            chunk_k, chunk_v = to_pages(row["k"]), to_pages(row["v"])
            out = dict(pools)
            if quantized:
                for name, sname, chunk in (("kp", "ks", chunk_k),
                                           ("vp", "vs", chunk_v)):
                    vals, sc = _quantize_pages(chunk)
                    pool, spool = out[name], out[sname]
                    for j in range(n_wp):
                        pool = jax.lax.dynamic_update_slice(
                            pool, vals[:, j:j + 1], (0, write_pids[j], 0, 0, 0)
                        )
                        spool = jax.lax.dynamic_update_slice(
                            spool, sc[:, j:j + 1], (0, write_pids[j], 0, 0, 0)
                        )
                    out[name], out[sname] = pool, spool
            else:
                for name, chunk in (("kp", chunk_k), ("vp", chunk_v)):
                    pool = out[name]
                    for j in range(n_wp):
                        pool = jax.lax.dynamic_update_slice(
                            pool, chunk[:, j:j + 1], (0, write_pids[j], 0, 0, 0)
                        )
                    out[name] = pool
            last = logits[0, s_len - 1]
            masked = _fsm_mask(fsm[0], fsm[1], last) if self.guided else last
            first = sample_logits(
                masked[None], rng, temperature=temp, top_k=self.gen.top_k,
                top_p=top_p,
            )[0]
            fs = (_fsm_next(fsm[0], fsm[1], first),) if self.guided else ()
            if self.logprobs_k:
                c, i, t = _lp_stats(last[None], first[None], self.logprobs_k)
                return (out, first, c[0], i[0], t[0], *fs)
            return (out, first, *fs)

        return jax.jit(run, donate_argnums=(1,))

    def _build_paged_decode(self, sampled: bool, topp: bool):
        """Paged decode tick with DEFERRED page writes: the chunk's K/V
        accumulate in small per-layer tail buffers carried through the scan
        (the kernel reads pages + tail; per-token writes into the pooled
        buffers inside the scan cost ~7 ms/step on v5e), then ONE scatter
        per pool flushes the tail after the scan. ``limits`` ends a row
        exactly at its token budget, so flushed positions never pass the
        pages reserved at admission."""
        cfg, ps = self.cfg, self.page_size
        pad, eos = self.tokenizer.pad_id, self.tokenizer.eos_id
        chunk = self.decode_chunk
        tail_len = max(chunk, 8)  # Mosaic sublane floor for the tail block
        L, K, D = cfg.num_layers, cfg.num_kv_heads, cfg.head_dim
        dt = jnp.dtype(cfg.dtype)

        track = self.speculative
        n_lp = self.logprobs_k

        guided = self.guided

        def run(params, pools, cur, pos, alive, temps, top_ps, keys, table,
                limits, hist, adapters, *extra):
            ftab, fstates = (extra[0], extra[1]) if guided else (None, None)
            lp0 = extra[2:] if guided else extra
            n_b = pos.shape[0]
            # starts = pos (not where(alive, pos, 0)): dead rows then have
            # pos - starts == 0 live tail columns, so the flush writes
            # nothing for them regardless of table-row state — no reliance
            # on freed slots having zeroed rows.
            starts = pos
            tk0 = jnp.zeros((L, n_b, K, tail_len, D), dt)
            tv0 = jnp.zeros((L, n_b, K, tail_len, D), dt)
            cache_const = dict(pools)  # pools are read-only during the scan

            def body(carry, t):
                tk, tv, cur, pos, done, keys, hist, fst, lp = carry
                split = jax.vmap(lambda k: jax.random.split(k, 2))(keys)
                keys, subs = split[:, 0], split[:, 1]
                done = done | (pos >= limits)
                step_alive = ~done
                lengths = jnp.where(step_alive, pos + 1, 0)
                paged_meta = {
                    "table": table, "lengths": lengths, "starts": starts,
                    "t": t,
                }
                logits, tails = llama.forward(
                    params,
                    cur[:, None],
                    cfg,
                    positions=pos[:, None],
                    cache={**cache_const, "tk": tk, "tv": tv},
                    paged=paged_meta,
                    mesh=self.mesh,
                    rules=self.rules,
                    adapter_ids=adapters if self.multi_lora else None,
                )
                tk, tv = tails["tk"], tails["tv"]
                step_logits = logits[:, 0]
                nxt = sample_logits(
                    _fsm_mask(ftab, fst, step_logits) if guided else step_logits,
                    subs,
                    temperature=temps if sampled else 0.0,
                    top_k=self.gen.top_k,
                    top_p=top_ps if topp else 1.0,
                )
                emit = jnp.where(step_alive, cur, pad)
                # Emitted stats are the pending ones (aligned with ``cur``);
                # the pending slot then refills with ``nxt``'s stats.
                ys = (emit, *lp) if n_lp else emit
                if n_lp:
                    lp = _lp_stats(step_logits, nxt, n_lp)
                done = done | (cur == eos)
                if guided:
                    fst = jnp.where(done, fst, _fsm_next(ftab, fst, nxt))
                pos = jnp.where(step_alive, pos + 1, pos)
                cur = jnp.where(done, pad, nxt)
                if track:
                    from ditl_tpu.infer.speculative import _emit_rows

                    grow = (~done).astype(jnp.int32)
                    hist = _emit_rows(hist, cur[:, None], pos, grow)
                return (tk, tv, cur, pos, done, keys, hist, fst, lp), ys

            fst0 = fstates if guided else jnp.zeros((), jnp.int32)
            (tk, tv, cur, pos, done, keys, hist, fst, lp), ys = jax.lax.scan(
                body, (tk0, tv0, cur, pos, ~alive, keys, hist, fst0,
                       tuple(lp0)),
                jnp.arange(chunk, dtype=jnp.int32),
            )

            out = _flush_tail_into_pools(
                pools, tk, tv, starts, pos, table, ps, tail_len
            )
            fs = (fst,) if guided else ()
            if n_lp:
                toks, c, i, t = ys
                return (out, cur, pos, keys, hist, *fs, lp, toks.T,
                        c.T, jnp.swapaxes(i, 0, 1), jnp.swapaxes(t, 0, 1))
            return (out, cur, pos, keys, hist, *fs, ys.T)

        return jax.jit(run, donate_argnums=(1,))

    def _build_spec_paged_decode(self, sampled: bool = False):
        """Speculative decode tick, paged cache: same round structure as the
        contiguous spec tick, but the verify chunk's K/V land in the
        deferred-flush TAIL buffer at per-row offsets (cache.scatter_tail)
        and the verify attention runs through the multi-query paged kernel
        (Q queries share every page fetch; per-query causal limits apply to
        the tail block only). Accepted columns are contiguous from each
        round's offset, so the per-tick flush is IDENTICAL to the plain
        tick's (valid = j < pos - starts). ``limits`` caps emission on
        device so flushed positions never pass the pages reserved at
        admission. ``sampled``: see ``_build_spec_decode``."""
        cfg, ps, smax = self.cfg, self.page_size, self.smax
        pad, eos = self.tokenizer.pad_id, self.tokenizer.eos_id
        k, rounds = self.spec_k, self.spec_rounds
        ngram, min_ngram = self.spec_ngram, self.spec_min_ngram
        out_len = rounds * (k + 1)
        tail_len = max(rounds * (k + 1), 8)
        L, K, D = cfg.num_layers, cfg.num_kv_heads, cfg.head_dim
        dt = jnp.dtype(cfg.dtype)
        q_idx = jnp.arange(k + 1, dtype=jnp.int32)

        from ditl_tpu.infer.speculative import _emit_rows, device_lookup_draft

        n_lp = self.logprobs_k

        guided = self.guided
        model_draft = self.spec_draft == "model"

        def run(params, pools, cur, pos, alive, table, limits, hist, temps,
                top_ps, keys, adapters, *extra):
            i = 0
            dparams = dcache0 = None
            if model_draft:
                dparams, dcache0 = extra[0], extra[1]
                i = 2
            ftab, fstates = (
                (extra[i], extra[i + 1]) if guided else (None, None)
            )
            lp0 = extra[i + 2 :] if guided else extra[i:]
            n_b = pos.shape[0]
            starts = pos
            tk0 = jnp.zeros((L, n_b, K, tail_len, D), dt)
            tv0 = jnp.zeros((L, n_b, K, tail_len, D), dt)
            cache_const = dict(pools)  # pools are read-only during the scan
            out0 = jnp.full((n_b, out_len), pad, jnp.int32)
            zeros = jnp.zeros((n_b,), jnp.int32)
            bufs0 = (
                (jnp.zeros((n_b, out_len), jnp.float32),
                 jnp.zeros((n_b, out_len, n_lp), jnp.int32),
                 jnp.zeros((n_b, out_len, n_lp), jnp.float32))
                if n_lp else ()
            )

            def body(carry, _):
                (tk, tv, dcache, cur, pos, done, hist, out, n_out, rr, keys,
                 fst, lp, bufs) = carry
                done = done | (pos >= limits)
                live = ~done
                split = jax.vmap(lambda kk: jax.random.split(kk, 2))(keys)
                keys, subs = split[:, 0], split[:, 1]
                if model_draft:
                    # The DRAFT cache stays contiguous even under a paged
                    # target: it is per-slot small, and page-granular
                    # sharing buys nothing for a private scratch model.
                    dcache, draft = self._draft_scan(
                        dparams, dcache, cur, pos, smax
                    )
                else:
                    draft = device_lookup_draft(
                        hist, jnp.minimum(pos + 1, smax), k=k, ngram=ngram,
                        min_ngram=min_ngram,
                    )
                tokens_in = jnp.concatenate([cur[:, None], draft], axis=1)
                positions = pos[:, None] + q_idx[None, :]
                lengths = jnp.where(live, pos + 1, 0)
                paged_meta = {
                    "table": table, "lengths": lengths, "starts": starts,
                    "off": pos - starts,
                }
                logits, tails = llama.forward(
                    params, tokens_in, cfg, positions=positions,
                    cache={**cache_const, "tk": tk, "tv": tv},
                    paged=paged_meta, mesh=self.mesh, rules=self.rules,
                    adapter_ids=adapters if self.multi_lora else None,
                )
                tk, tv = tails["tk"], tails["tv"]
                if guided:
                    # See _build_spec_decode: per-position path-state masks.
                    path = self._fsm_spec_path(ftab, fst, draft)
                    ver_logits = _fsm_mask(ftab, path, logits)
                else:
                    ver_logits = logits
                n_acc, nxt_tok = self._spec_accept(
                    ver_logits, tokens_in, subs, temps, top_ps, sampled
                )
                in_span = q_idx[None, :] <= n_acc[:, None]
                is_term = (tokens_in == eos) | (tokens_in == pad)
                term_before = (
                    jnp.cumsum(is_term.astype(jnp.int32), axis=1)
                    - is_term.astype(jnp.int32)
                ) > 0
                budget_ok = (pos[:, None] + q_idx[None, :]) < limits[:, None]
                emit = in_span & ~term_before & budget_ok & live[:, None]
                e = jnp.sum(emit.astype(jnp.int32), axis=1)
                hit_term = jnp.any(emit & is_term, axis=1)
                out = _emit_rows(out, tokens_in, n_out, e)
                if n_lp:
                    lp, bufs = self._spec_lp_round(
                        logits, draft, n_acc, nxt_tok, lp, bufs, n_out, e
                    )
                n_out = n_out + e
                append_seq = jnp.where(
                    q_idx[None, :] == n_acc[:, None],
                    nxt_tok[:, None],
                    jnp.concatenate([draft, zeros[:, None]], axis=1),
                )
                grow = jnp.where(hit_term, 0, e)
                if not model_draft:
                    hist = _emit_rows(
                        hist, append_seq, jnp.minimum(pos + 1, smax), grow
                    )
                pos = jnp.where(live, pos + e, pos)
                done = done | hit_term
                if guided:
                    s_at = jnp.take_along_axis(path, n_acc[:, None], 1)[:, 0]
                    fst = jnp.where(done, fst, _fsm_next(ftab, s_at, nxt_tok))
                cur = jnp.where(done, pad, nxt_tok)
                rr = rr + live.astype(jnp.int32)
                return (tk, tv, dcache, cur, pos, done, hist, out, n_out,
                        rr, keys, fst, lp, bufs), None

            fst0 = fstates if guided else jnp.zeros((), jnp.int32)
            dc0 = dcache0 if model_draft else jnp.zeros((), jnp.int32)
            (tk, tv, dcache, cur, pos, done, hist, out, n_out, rr, keys,
             fst, lp, bufs), _ = jax.lax.scan(
                body,
                (tk0, tv0, dc0, cur, pos, ~alive, hist, out0, zeros, zeros,
                 keys, fst0, tuple(lp0), bufs0),
                None, length=rounds,
            )
            pools_out = _flush_tail_into_pools(
                pools, tk, tv, starts, pos, table, ps, tail_len
            )
            fs = (fst,) if guided else ()
            dc = (dcache,) if model_draft else ()
            return (pools_out, *dc, cur, pos, hist, keys, *fs, out, n_out,
                    rr, lp, bufs)

        donate = (1, 13) if model_draft else (1,)
        return jax.jit(run, donate_argnums=donate)

    def register_prefix(self, prefix_tokens: list[int]) -> None:
        """Prefill ``prefix_tokens`` once and reuse the KV for every future
        request whose prompt starts with them (longest registered match wins).
        The natural use is a shared system prompt. Sharing is whole-prefix
        (contiguous slot cache, no paging), and the prefix slice lives in
        device memory until ``clear_prefixes``."""
        if not prefix_tokens:
            raise ValueError("prefix must be non-empty")
        if self.multi_lora:
            raise ValueError(
                "register_prefix with a multi-adapter stack is unsupported "
                "(the prefix KV is adapter-specific); paged-mode automatic "
                "prefix reuse is adapter-isolated instead"
            )
        if len(prefix_tokens) + 1 > self.smax:
            raise ValueError(
                f"prefix {len(prefix_tokens)} leaves no room in cache {self.smax}"
            )
        if self.cache_mode == "paged":
            # Paged mode: prefix reuse is automatic (content-hashed pages);
            # registration is just a pre-warm of the page cache.
            self._warm_pages(prefix_tokens)
            return
        key = tuple(prefix_tokens)
        if key in self._prefixes:
            return
        p_bucket = min(_next_pow2(len(prefix_tokens), floor=16), self.smax)
        if p_bucket not in self._prefix_prefill:
            logger.info("compiling prefix prefill for bucket %d", p_bucket)
            self._prefix_prefill[p_bucket] = self._build_prefix_prefill(p_bucket)
        ids = np.full((1, p_bucket), self.tokenizer.pad_id, np.int32)
        ids[0, : len(prefix_tokens)] = prefix_tokens
        row, last_logits = self._prefix_prefill[p_bucket](
            self.params, jnp.asarray(ids), jnp.int32(len(prefix_tokens))
        )
        self._prefixes[key] = (row, last_logits, len(prefix_tokens))
        logger.info(
            "registered prefix of %d tokens (bucket %d)", len(prefix_tokens), p_bucket
        )

    def _warm_pages(self, tokens: list[int]) -> None:
        """Prefill and publish the FULL pages of ``tokens`` into the page
        cache so later prompts reuse them without prefilling (paged-mode
        ``register_prefix``). No slot is occupied; the pages are held only
        by the content cache (evictable under pool pressure)."""
        ps = self.page_size
        n_full = len(tokens) // ps
        if n_full == 0:
            return
        matched: list[int] = []
        parent = 0
        for i in range(n_full):
            block = tuple(tokens[i * ps:(i + 1) * ps])
            pid = self.allocator.lookup((parent, block))
            if pid is None:
                break
            self.allocator.retain(pid)
            matched.append(pid)
            parent = pid
        n_fresh = n_full - len(matched)
        if n_fresh == 0:
            for pid in matched:
                self.allocator.release(pid)
            return
        try:
            fresh = self.allocator.alloc(n_fresh)
        except MemoryError:
            # A warm hint must not raise or leak: drop the matched retains
            # and leave the cache as-is.
            for pid in matched:
                self.allocator.release(pid)
            logger.warning(
                "register_prefix: pool cannot hold %d fresh pages; skipping "
                "warm-up", n_fresh,
            )
            return
        pages = matched + fresh
        d = len(matched) * ps
        s = n_full * ps - d
        m0 = time.monotonic()
        self._run_paged_prefill(
            tokens[d: d + s], d, s, s,
            ctx_row=np.asarray(pages, np.int32),  # pages[:ctx] = the context
            write_pids=np.asarray(pages[len(matched):], np.int32),
            temp=0.0, top_p=1.0, rng=jax.random.key(0),
        )
        # The measured prefill tok/s (ISSUE 13) comes from page-warming
        # prefills ONLY, synced before the clock closes: ordinary
        # admissions are async-dispatched (pipelining is the point) and
        # timing their dispatch would feed the cost model a dispatch
        # rate, not device time — the warm path is off the serving hot
        # path and IS the work the handoff trades against.
        jax.block_until_ready(self.cache)
        self.prefill_tokens_total += s
        self.prefill_seconds_total += time.monotonic() - m0
        self.allocator.publish_chain(tokens[: n_full * ps], ps, pages)
        for pid in pages:
            self.allocator.release(pid)
        logger.info(
            "warmed %d pages (%d reused) for a %d-token prefix",
            n_fresh, len(matched), len(tokens),
        )

    def clear_prefixes(self) -> None:
        """Drop all registered prefixes (frees their device memory)."""
        self._prefixes.clear()

    def _match_prefix(self, prompt: list[int]):
        """Longest registered prefix that prefixes ``prompt``, or None."""
        best = None
        for key, entry in self._prefixes.items():
            d = entry[2]
            if d <= len(prompt) and tuple(prompt[:d]) == key:
                if best is None or d > best[2]:
                    best = entry
        return best

    # -- scheduler ----------------------------------------------------------

    def submit(
        self,
        prompt_tokens: list[int],
        *,
        max_new_tokens: int | None = None,
        temperature: float | None = None,
        top_p: float | None = None,
        seed: int | None = None,
        stream: Any = None,
        logprobs: int | None = None,
        adapter_id: int | None = None,
        grammar: Any = None,
        deadline_s: float | None = None,
        slo_class: str | None = None,
        trace: Any = None,
        tenant: str | None = None,
    ) -> int:
        """Queue a request; returns its id (see ``results``/``run``).
        ``stream``: optional ``queue.Queue`` receiving per-chunk token lists
        and a final ``None``. ``logprobs``: top-N alternatives per generated
        token (None = off; 0 = chosen-token logprob only); requires the
        engine constructed with ``logprobs_k >= N``. ``adapter_id`` selects
        the request's LoRA adapter when params are a multi-adapter stack
        (0 = base). ``grammar`` constrains the COMPLETION (not the prompt)
        to a compiled grammar — an ``infer.grammar.CompiledGrammar`` (auto-
        registered) or an int start state from ``register_grammar``;
        requires the engine constructed with ``fsm_capacity > 0``.
        ``deadline_s``: relative deadline — past it the request is evicted
        from the queue/slot (DeadlineExceededError for waiters) instead of
        decoding work nobody will read. Solo serving only: the pod tick
        broadcast never carries deadlines (per-process wall clocks would
        desync the replicated scheduler). ``slo_class``: scheduling
        priority class (``interactive`` | ``batch`` | ``best_effort``,
        default interactive) — orders admission/prefill and picks eviction
        victims under pool pressure (module docstring); never changes a
        request's RESULT, only when it runs. ``trace``: upstream span/
        SpanContext (telemetry/tracing.py) the engine's lifecycle spans
        chain under when the engine's tracer is armed; ignored otherwise.
        ``tenant``: credential-safe tenant label (ISSUE 15 — the admission
        digest or a configured public name, NEVER a raw bearer; sanitized
        again here) the request's usage accounting attributes to."""
        gen = self.gen
        tenant = sanitize_label(tenant or "anonymous")
        if self.max_queue is not None and len(self._queue) >= self.max_queue:
            self.metrics.queue_full.inc()
            # A 429 is a terminal outcome the tenant's bill must carry
            # (the request consumed admission capacity even though it
            # never reached a slot) — ledgered here because the engine is
            # the only place that knows the queue said no.
            self._note_usage_row({
                "tenant": tenant, "outcome": "429",
                "slo_class": slo_class or "interactive",
                "prompt_tokens": len(prompt_tokens or ()),
                "generated_tokens": 0,
            })
            raise QueueFullError(
                f"admission queue full ({self.max_queue} waiting requests)"
            )
        if adapter_id:
            if not self.multi_lora:
                raise BadRequestError(
                    "adapter_id given but params are not a multi-adapter "
                    "stack (models/lora.stack_adapters)"
                )
            if not 0 <= adapter_id < self.n_adapters:
                # JAX gathers clamp out-of-range indices under jit, which
                # would silently serve the wrong adapter.
                raise BadRequestError(
                    f"adapter_id {adapter_id} out of range "
                    f"[0, {self.n_adapters})"
                )
        if logprobs is not None:
            if self.logprobs_k == 0:
                raise BadRequestError(
                    "logprobs requested but the engine was built with "
                    "logprobs_k=0"
                )
            if not 0 <= logprobs <= self.logprobs_k:
                raise BadRequestError(
                    f"logprobs={logprobs} out of range [0, {self.logprobs_k}]"
                )
        if seed is not None and not (-2**31 <= int(seed) < 2**31):
            # Same bound the pod stage enforces: the per-slot PRNG key is
            # folded from an int32 lane; numpy would raise OverflowError at
            # dispatch time otherwise — surface it as request validation.
            # Checked BEFORE grammar registration: fsm rows are never
            # evicted, so a rejected request must not consume one.
            raise BadRequestError("seed must fit in int32")
        if deadline_s is not None and not (
            isinstance(deadline_s, (int, float))
            and deadline_s == deadline_s  # NaN would poison every sweep
        ):
            # Also BEFORE grammar registration, for the same reason.
            raise BadRequestError("deadline_s must be a number")
        if slo_class is None:
            slo_class = "interactive"
        elif slo_class not in SLO_CLASSES:
            # Also BEFORE grammar registration (FSM rows are never evicted).
            raise BadRequestError(
                f"unknown slo_class {slo_class!r} "
                f"(one of {sorted(SLO_CLASSES)})"
            )
        max_new = max_new_tokens if max_new_tokens is not None else gen.max_new_tokens
        prompt = prompt_tokens or [self.tokenizer.bos_id]
        self.validate_request(prompt, max_new)
        fsm_start = 0
        if grammar is not None:
            if not self.guided:
                raise BadRequestError(
                    "grammar requested but the engine was built with "
                    "fsm_capacity=0"
                )
            if isinstance(grammar, int):
                with self._fsm_lock:  # register_grammar appends from HTTP
                    # threads; an unlocked read could reject a state that
                    # was just registered (ADVICE r3)
                    used = self._fsm_used
                if not 0 <= grammar < used:
                    raise BadRequestError(
                        f"grammar start state {grammar} not in the installed "
                        f"table (rows [0, {used}))"
                    )
                fsm_start = grammar
            else:
                fsm_start = self.register_grammar(grammar)
        req = Request(
            req_id=self._next_id,
            prompt=list(prompt),
            max_new_tokens=max_new,
            temperature=gen.temperature if temperature is None else temperature,
            top_p=gen.top_p if top_p is None else top_p,
            seed=(self._base_seed + self._next_id) if seed is None else seed,
            stream=stream,
            logprobs=logprobs,
            adapter_id=adapter_id or 0,
            fsm_start=fsm_start,
            t_submit=time.monotonic(),
            deadline=(
                time.monotonic() + float(deadline_s)
                if deadline_s is not None else None
            ),
            slo_class=slo_class,
            tenant=tenant,
        )
        self._next_id += 1
        if self.tracer.armed:
            # The whole-lifecycle span stays open until completion/expiry/
            # cancel; the queue span closes at slot admission. Both chain
            # under the caller's (server's) span so the merged trace nests
            # across the HTTP boundary.
            req.trace = trace
            req.request_span = self.tracer.start_span(
                "engine.request", parent=trace, req=req.req_id,
                prompt_tokens=len(req.prompt),
                max_new_tokens=req.max_new_tokens,
            )
            req.queue_span = self.tracer.start_span(
                "engine.queue", parent=req.request_span, req=req.req_id,
            )
        self.metrics.requests.inc()
        self._enqueue(req)
        return req.req_id

    def _enqueue(self, req: Request) -> None:
        """Insert by (class rank, req_id): FIFO within a class, classes in
        priority order. Monotonic req_ids make this a stable sort; a
        requeued (preempted) request's old id puts it ahead of everything
        newer in its class — the old queue-head semantics, class-scoped.
        Deterministic, so pod replicas order identically."""
        import bisect

        keys = [r.slo_rank for r in self._queue]
        self._queue.insert(bisect.bisect_right(keys, req.slo_rank), req)

    def validate_request(self, prompt: list[int], max_new: int) -> None:
        """Per-request shape validation, raising ``ValueError`` on requests
        that could never run. Exposed so pod staging (podserve) can reject
        a bad request on its own HTTP thread instead of failing the whole
        broadcast tick it would have shared with innocent requests."""
        if len(prompt) + max_new > self.smax:
            raise BadRequestError(
                f"prompt {len(prompt)} + max_new {max_new} exceeds max_seq_len "
                f"/ cache cap {self.smax}"
            )
        if self.cache_mode == "paged":
            need = -(-(len(prompt) + max_new) // self.page_size)
            if need > self.n_pages - 1:  # page 0 is the reserved sentinel
                # Reject now: admission could never reserve this many pages,
                # and a forever-unadmittable request would spin run()/the
                # server driver without progress.
                raise BadRequestError(
                    f"request needs {need} pages but the pool only has "
                    f"{self.n_pages - 1} (n_pages={self.n_pages}, "
                    f"page_size={self.page_size})"
                )

    def _prefill_into_slot(self, req: Request, slot: int, rng,
                           prefix) -> jax.Array | None:
        """Fill the slot's cache for ``req``'s prompt and return the first
        sampled token. ``prefix`` is the caller's ``_match_prefix`` result
        (``_admit`` already computed it for the token-budget gate — one
        scan per admission, not two). Uses the matched prefix's KV when
        present (seed copy + suffix-only prefill), else the full prefill
        program. Returns ``None`` when chunked prefill takes over (the
        request finishes prefilling across subsequent ticks, see
        ``_advance_prefill``)."""
        d0 = 0 if prefix is None else prefix[2]
        self._note_prefix_cache(req, d0)
        if self.prefill_chunk and len(req.prompt) - d0 > self.prefill_chunk:
            if prefix is not None:
                row, _, _ = prefix
                p_bucket = row["k"].shape[2]
                if p_bucket not in self._seed_cache:
                    self._seed_cache[p_bucket] = self._build_seed(p_bucket)
                self.cache = self._seed_cache[p_bucket](
                    self.cache, row, jnp.int32(slot)
                )
            req.prefill_pos = d0
            req.prefilling = True
            return None
        if prefix is None:
            p_bucket = min(_next_pow2(len(req.prompt), floor=16), self.smax)
            if p_bucket not in self._prefill_cache:
                logger.info("compiling prefill program for bucket %d", p_bucket)
                self._prefill_cache[p_bucket] = self._build_prefill(p_bucket)
            ids = np.full((1, p_bucket), self.tokenizer.pad_id, np.int32)
            ids[0, : len(req.prompt)] = req.prompt
            return self._take_prefill(self._prefill_cache[p_bucket](
                self.params, self.cache, jnp.asarray(ids),
                jnp.int32(len(req.prompt)), jnp.int32(slot),
                jnp.float32(req.temperature), jnp.float32(req.top_p), rng,
                jnp.asarray([req.adapter_id], jnp.int32),
                *self._fsm_args(req.fsm_start),
            ), slot)
        row, last_logits, d = prefix
        p_bucket = row["k"].shape[2]
        if p_bucket not in self._seed_cache:
            self._seed_cache[p_bucket] = self._build_seed(p_bucket)
        self.cache = self._seed_cache[p_bucket](self.cache, row, jnp.int32(slot))
        s = len(req.prompt) - d
        if s == 0:
            # Prompt == prefix: first token comes from the stored logits.
            if self._first_sampler is None:
                n_lp = self.logprobs_k
                guided = self.guided

                def first_sample(lg, key, t, p, *fsm):
                    masked = _fsm_mask(fsm[0], fsm[1], lg) if guided else lg
                    first = sample_logits(
                        masked[None], key, temperature=t,
                        top_k=self.gen.top_k, top_p=p,
                    )[0]
                    fs = (
                        (_fsm_next(fsm[0], fsm[1], first),) if guided else ()
                    )
                    if n_lp:
                        c, i, tt = _lp_stats(lg[None], first[None], n_lp)
                        return (first, c[0], i[0], tt[0], *fs)
                    return (first, *fs) if guided else first

                self._first_sampler = jax.jit(first_sample)
            out = self._first_sampler(
                last_logits, rng, jnp.float32(req.temperature),
                jnp.float32(req.top_p), *self._fsm_args(req.fsm_start),
            )
            if self.guided:
                *out, fst = out
                self.fstates = self.fstates.at[slot].set(fst)
            if self.logprobs_k:
                first, c, i, t = out
                self._store_lp(slot, c, i, t)
                return first
            return out[0] if self.guided else out
        s_bucket = min(_next_pow2(s, floor=16), self.smax - d)
        if s_bucket not in self._suffix_prefill:
            logger.info("compiling suffix prefill for bucket %d", s_bucket)
            self._suffix_prefill[s_bucket] = self._build_suffix_prefill(s_bucket)
        ids = np.full((1, s_bucket), self.tokenizer.pad_id, np.int32)
        ids[0, :s] = req.prompt[d:]
        return self._take_prefill(self._suffix_prefill[s_bucket](
            self.params, self.cache, jnp.asarray(ids), jnp.int32(d),
            jnp.int32(s), jnp.int32(slot), jnp.float32(req.temperature),
            jnp.float32(req.top_p), rng,
            jnp.asarray([req.adapter_id], jnp.int32),
            *self._fsm_args(req.fsm_start),
        ), slot)

    def _chunk_bucket(self, d: int, s: int) -> int:
        """Write-window bucket for a prefill chunk of ``s`` tokens at offset
        ``d``: the fixed ``prefill_chunk`` program, except tail chunks near
        the cache end, which take a smaller bucket — the window must fit
        (a clamped dynamic_update_slice would silently shift the chunk)."""
        if d + self.prefill_chunk <= self.smax:
            return self.prefill_chunk
        return min(_next_pow2(s, floor=16), self.smax - d)

    def _advance_prefill(self, req: Request) -> None:
        """One chunk of a chunked prefill (reuses the suffix-prefill program —
        a chunk IS a suffix continuation at offset ``prefill_pos``). The
        final chunk's sample becomes the request's first token, and the slot
        key is (re)derived from the request seed so sampling stays
        reproducible no matter how many decode ticks ran while parked."""
        if self.cache_mode == "paged":
            d = req.prefill_pos
            s = min(self.prefill_chunk, len(req.prompt) - d)
            slot_key, sub = jax.random.split(jax.random.key(req.seed))
            first = self._paged_prefill_chunk(
                req, req.slot, d, s, self.prefill_chunk, sub
            )
            req.prefill_pos += s
            if req.prefill_pos >= len(req.prompt):
                req.prefilling = False
                self._publish_prompt_pages(req, req.slot)
                self.cur = self.cur.at[req.slot].set(first)
                self.pos = self.pos.at[req.slot].set(len(req.prompt))
                self.keys = self.keys.at[req.slot].set(slot_key)
                self._set_hist(req.slot, req.prompt, first)
                self._draft_prefill(req, req.slot)
            return
        d = req.prefill_pos
        s = min(self.prefill_chunk, len(req.prompt) - d)
        s_bucket = self._chunk_bucket(d, s)
        if s_bucket not in self._suffix_prefill:
            logger.info("compiling suffix prefill for bucket %d", s_bucket)
            self._suffix_prefill[s_bucket] = self._build_suffix_prefill(s_bucket)
        ids = np.full((1, s_bucket), self.tokenizer.pad_id, np.int32)
        ids[0, :s] = req.prompt[d: d + s]
        slot_key, sub = jax.random.split(jax.random.key(req.seed))
        first = self._take_prefill(self._suffix_prefill[s_bucket](
            self.params, self.cache, jnp.asarray(ids), jnp.int32(d),
            jnp.int32(s), jnp.int32(req.slot), jnp.float32(req.temperature),
            jnp.float32(req.top_p), sub,
            jnp.asarray([req.adapter_id], jnp.int32),
            *self._fsm_args(req.fsm_start),
        ), req.slot)
        req.prefill_pos += s
        if req.prefill_pos >= len(req.prompt):
            req.prefilling = False
            self.cur = self.cur.at[req.slot].set(first)
            self.pos = self.pos.at[req.slot].set(len(req.prompt))
            self.keys = self.keys.at[req.slot].set(slot_key)
            self._set_hist(req.slot, req.prompt, first)
            self._draft_prefill(req, req.slot)

    def _take_prefill(self, out, slot: int | None):
        """Unpack a prefill program's outputs: store the new cache and —
        when logprobs are armed — the first token's pending stats for
        ``slot`` (``None``: discard, e.g. page warming); return ``first``.
        Guided engines also carry the post-first-token FSM state; like the
        pending logprob stats, a chunked prefill's intermediate stores are
        junk that the final chunk overwrites before the slot goes live."""
        if self.guided:
            *out, fst = out
            if slot is not None:
                self.fstates = self.fstates.at[slot].set(fst)
        if self.logprobs_k:
            self.cache, first, c, i, t = out
            if slot is not None:
                self._store_lp(slot, c, i, t)
        else:
            self.cache, first = out
        return first

    def _fsm_args(self, fsm_start: int) -> tuple:
        """Per-call FSM program arguments (device table + start state), or
        () on unguided engines — splatted after the fixed prefill args."""
        if not self.guided:
            return ()
        return (self._fsm_device(), jnp.int32(fsm_start))

    def _store_lp(self, slot: int, c, i, t) -> None:
        self.lp_chosen = self.lp_chosen.at[slot].set(c)
        self.lp_ids = self.lp_ids.at[slot].set(i)
        self.lp_top = self.lp_top.at[slot].set(t)

    def _set_hist(self, slot: int, prompt: list[int], first) -> None:
        """Seed the slot's draft history: prompt tokens plus the pending
        first sampled token (``hist[pos] == cur`` is the tick invariant).
        ``first`` stays a device scalar — no host sync on admission."""
        if not self.speculative or self.spec_draft != "lookup":
            return
        row = np.zeros((self.smax,), np.int32)
        n = min(len(prompt), self.smax - 1)
        row[:n] = prompt[:n]
        self.hist = (
            self.hist.at[slot].set(jnp.asarray(row)).at[slot, n].set(first)
        )

    # -- paged admission / prefill -------------------------------------------

    def _free_slot_pages(self, slot: int) -> None:
        for pid in self._slot_pages[slot]:
            self.allocator.release(pid)
        self._slot_pages[slot] = []
        self._table[slot, :] = 0
        self._table_dirty = True

    def _publish_prompt_pages(self, req: Request, slot: int) -> None:
        """Make the prompt's FULL pages content-addressable so later prompts
        sharing the prefix reuse them without prefilling. Full prompt pages
        are immutable (decode writes only past the prompt), so sharing is
        read-only by construction."""
        self._publish_tokens(req.prompt, slot, req.adapter_id)

    def _publish_tokens(self, tokens: list[int], slot: int,
                        adapter_id: int = 0) -> None:
        ps = self.page_size
        n_full = len(tokens) // ps
        self.allocator.publish_chain(
            tokens[: n_full * ps], ps,
            [int(p) for p in self._table[slot, :n_full]],
            root=-adapter_id,
        )

    def _publish_generated_pages(self, req: Request, slot: int) -> None:
        """On natural completion, publish the pages covering prompt AND
        generated tokens: a multi-turn follow-up whose prompt embeds this
        turn's output (chat history) then reuses the whole conversation's
        KV and prefills only the new user turn. Generated pages become
        immutable the moment the slot stops decoding, and their content key
        — (parent page, exact tokens) — verifies exactly like prompt pages."""
        self._publish_tokens(req.prompt + req.tokens, slot, req.adapter_id)

    # -- host-RAM prefix-cache tier + KV handoff (ISSUE 13) ------------------

    def _on_pages_evicted(self, group) -> None:
        """Allocator ``on_evict`` hook: count the reclaim (one claimed page
        per call — the ISSUE 8 eviction-counter semantics are unchanged)
        and queue the WHOLE evicted group — claimed page plus cascaded
        descendants — for the host-tier spill. Only lazy device-array
        slices are captured here (async gather dispatch, no host sync, so
        the ``@hot_path`` tick stays free of blocking transfers); the one
        real ``device_get`` happens per tick in ``_process_spills``. The
        slice must be taken NOW: ``alloc`` hands the claimed page to a
        prefill that overwrites it this very tick."""
        self.metrics.prefix_cache_evictions.inc()
        if self._handoff_pids:
            # An evicted page's physical id may be recycled for unrelated
            # content — it must never attribute a later hit to the handoff
            # tier (the unpublish group is the only path out of the
            # published set, so this discard is exhaustive).
            self._handoff_pids.difference_update(p for p, _, _ in group)
        tier = self.host_tier
        if tier is None:
            return
        for pid, root, blocks in group:
            nid = tier.intern(root, list(blocks))
            if tier.has_entry(nid) or nid in self._pending_spill_ids:
                continue
            self._pending_spill_ids.add(nid)
            self._pending_spills.append(
                (nid, {k: v[:, pid] for k, v in self.cache.items()})
            )

    def _process_spills(self) -> None:
        """End-of-tick spill batch: ONE ``jax.device_get`` over every page
        this tick's evictions queued, stored into the host tier under
        never-recycled chain-node ids. Bounded by
        ``spill_max_pages_per_tick`` (the remainder carries over to the
        next tick). Chaos site ``kvtier.spill``: ``delay`` stalls the
        batch, ``error`` drops it (counted — correctness never depends on
        a spill landing; the pages simply re-prefill on their next miss),
        ``kill`` is a real process death mid-spill."""
        if not self._pending_spills:
            return
        batch = self._pending_spills[: self._spill_max]
        del self._pending_spills[: len(batch)]
        for nid, _ in batch:
            self._pending_spill_ids.discard(nid)
        m = self.metrics
        try:
            maybe_inject("kvtier.spill")
        except InjectedFault:
            m.host_tier_dropped_pages.inc(len(batch))
            return
        fetched = jax.device_get([parts for _, parts in batch])
        stored = 0
        for (nid, _), parts in zip(batch, fetched):
            if self.host_tier.put(
                nid, {k: np.asarray(v) for k, v in parts.items()}
            ):
                stored += 1
        m.host_tier_spilled_pages.inc(stored)
        if stored < len(batch):
            m.host_tier_dropped_pages.inc(len(batch) - stored)
        ev = self.host_tier.evictions
        if ev > self._tier_evictions_seen:
            m.host_tier_evictions.inc(ev - self._tier_evictions_seen)
            self._tier_evictions_seen = ev

    def _install_pages(self, pids: list[int], entries: list[dict]) -> None:
        """Scatter host KV arrays into pool pages — one donated, jitted
        scatter per pool per pow2 batch bucket (a bare ``.at[].set``
        outside jit copies the whole pool). Padding rows aim at sentinel
        page 0, whose content is never read unmasked (the same invariant
        the per-tick tail flush relies on)."""
        n = len(pids)
        bucket = _next_pow2(n, floor=1)
        idx = np.zeros((bucket,), np.int32)
        idx[:n] = pids
        for name in list(self.cache):
            vals = np.stack([np.asarray(e[name]) for e in entries])
            if bucket > n:
                pad = np.zeros((bucket - n,) + vals.shape[1:], vals.dtype)
                vals = np.concatenate([vals, pad])
            vals = np.moveaxis(vals, 0, 1)  # (L, bucket, K, ...)
            key = (name, bucket)
            prog = self._install_progs.get(key)
            if prog is None:
                prog = jax.jit(
                    lambda pool, i, v: pool.at[:, i].set(v),
                    donate_argnums=(0,),
                )
                self._install_progs[key] = prog
            self.cache[name] = prog(
                self.cache[name], jnp.asarray(idx), jnp.asarray(vals)
            )

    def _host_swap_in(self, req: Request,
                      matched: list[int]) -> tuple[list[int], int]:
        """Admission-miss host-tier lookup: extend the HBM ``matched`` run
        by swapping spilled pages back in (device_put + republish +
        refcount) instead of re-prefilling them. Returns ``(pages,
        host-hit tokens)`` — the tokens land under the ``host`` tier label
        in ``_note_prefix_cache``, never conflated with HBM hits, and the
        whole operation is timed into the swap-in-latency histogram.
        Swapped pages end in exactly the state a prefilled-then-published
        page holds (caller ref + cache ref), so every downstream invariant
        — publish chains, LRU eviction, re-spill — is untouched. A corrupt
        entry (crc mismatch) is dropped and counted; the chain cannot
        extend past it and the remainder re-prefills."""
        tier = self.host_tier
        ps = self.page_size
        prompt = req.prompt
        usable = (len(prompt) - 1) // ps
        if tier is None or usable <= len(matched):
            return matched, 0
        blocks = [tuple(prompt[i * ps:(i + 1) * ps]) for i in range(usable)]
        nids = tier.walk(-req.adapter_id, blocks)
        take: list[tuple[int, int]] = []
        for i in range(len(matched), usable):
            nid = nids[i]
            if nid is None or not tier.has_entry(nid):
                break
            take.append((i, nid))
        if not take:
            return matched, 0
        try:
            fault = maybe_inject("kvtier.swap_in")
        except InjectedFault:
            return matched, 0  # injected miss: admission just prefills
        if fault is not None and fault.action == "corrupt":
            # The drill's bit flip: the crc check below must catch it.
            tier.corrupt(take[0][1])
        t0 = time.monotonic()
        entries: list[dict] = []
        for i, nid in take:
            arrs = tier.fetch(nid)
            if arrs is None:
                # crc caught a corrupt entry: dropped + counted, never
                # served — and the chain past it cannot verify either.
                self.metrics.host_tier_corrupt_entries.inc()
                break
            entries.append(arrs)
        if not entries:
            return matched, 0
        try:
            pids = self.allocator.alloc(len(entries))
        except MemoryError:
            return matched, 0
        self._install_pages(pids, entries)
        parent = matched[-1] if matched else -req.adapter_id
        for pid, (i, _) in zip(pids, take):
            self.allocator.publish((parent, blocks[i]), pid)
            parent = pid
        jax.block_until_ready(self.cache)  # honest swap-in latency
        self._table_dirty = True
        self.metrics.host_tier_swap_in.observe(time.monotonic() - t0)
        self.metrics.host_tier_swapped_pages.inc(len(pids))
        return matched + pids, len(pids) * ps

    def export_kv(self, prompt: list[int],
                  adapter_id: int = 0) -> tuple[bytes, int]:
        """Serialize the FULL pages of ``prompt`` for a prefill->decode
        handoff (infer/kv_transfer.py): prefill whatever isn't already
        cached (page warming — no slot is occupied), then ship the page
        KV with per-page crc32s and the exact token blocks the importer
        republishes under. Returns ``(blob, shipped_tokens)``. Ships at
        most the pages ``match_prefix`` would reuse (the always-leave-one-
        token rule), so the importer-side hit accounting equals the
        shipped tokens exactly. Must run on the engine driver thread
        (``ThreadedEngine.call``)."""
        if self.cache_mode != "paged":
            raise BadRequestError("KV handoff requires cache_mode='paged'")
        if adapter_id:
            raise BadRequestError("KV handoff serves the base adapter only")
        ps = self.page_size
        n = (len(prompt) - 1) // ps
        if n < 1:
            raise BadRequestError(
                f"prompt too short to ship ({len(prompt)} tokens, "
                f"page size {ps})"
            )
        self._warm_pages(prompt[: n * ps])
        matched = self.allocator.match_prefix(prompt, ps)
        if not matched:
            raise MemoryError(
                "page pool cannot hold the prompt's pages (nothing to ship)"
            )
        pid_arr = jnp.asarray(np.asarray(matched, np.int32))
        parts = jax.device_get(
            {k: v[:, pid_arr] for k, v in self.cache.items()}
        )
        for pid in matched:
            self.allocator.release(pid)
        tokens = prompt[: len(matched) * ps]
        meta = {
            "page_size": ps,
            "num_layers": self.cfg.num_layers,
            "num_kv_heads": self.cfg.num_kv_heads,
            "head_dim": self.cfg.head_dim,
            "quantized": "ks" in self.cache,
            "adapter_id": adapter_id,
            "blocks": [
                list(tokens[i * ps:(i + 1) * ps])
                for i in range(len(matched))
            ],
        }
        from ditl_tpu.infer.kv_transfer import serialize_pages

        pages = [
            {k: np.asarray(v[:, i]) for k, v in parts.items()}
            for i in range(len(matched))
        ]
        return serialize_pages(meta, pages), len(matched) * ps

    def import_kv(self, blob: bytes) -> dict:
        """Install a shipped prefill's pages into this engine's pool and
        publish them, so the relayed request's admission prefix-matches
        them instead of re-prefilling — the decode half of the handoff.
        Torn/short/crc-failing blobs raise
        :exc:`~ditl_tpu.infer.kv_transfer.KVTransferError` (reject whole,
        never partial-install); geometry mismatches are
        :class:`BadRequestError`. A full pool installs nothing (the relay
        re-prefills; zero client-visible failure). Must run on the engine
        driver thread (``ThreadedEngine.call``)."""
        from ditl_tpu.infer.kv_transfer import deserialize_pages

        if self.cache_mode != "paged":
            raise BadRequestError("KV handoff requires cache_mode='paged'")
        meta, pages = deserialize_pages(blob)
        want = {
            "page_size": self.page_size,
            "num_layers": self.cfg.num_layers,
            "num_kv_heads": self.cfg.num_kv_heads,
            "head_dim": self.cfg.head_dim,
            "quantized": "ks" in self.cache,
        }
        for k, v in want.items():
            if meta.get(k) != v:
                raise BadRequestError(
                    f"KV blob {k}={meta.get(k)!r} does not match this "
                    f"engine ({v!r})"
                )
        if sorted(meta["parts"]) != sorted(self.cache):
            raise BadRequestError(
                f"KV blob pools {meta['parts']} do not match this "
                f"engine's {sorted(self.cache)}"
            )
        for name, pool in self.cache.items():
            # Pool DTYPE is geometry too: the install scatter would
            # silently cast a mismatched blob (f32 pages into a bf16
            # pool) instead of rejecting — outputs would stop being
            # token-identical to a local prefill with no error signal.
            got = meta["part_dtypes"].get(name)
            if got != pool.dtype.name:
                raise BadRequestError(
                    f"KV blob pool {name} dtype {got!r} does not match "
                    f"this engine's {pool.dtype.name!r}"
                )
        ps = self.page_size
        blocks = [tuple(int(t) for t in b) for b in meta["blocks"]]
        if any(len(b) != ps for b in blocks):
            raise BadRequestError("KV blob blocks are not page-sized")
        root = -int(meta.get("adapter_id", 0))
        # RETAIN the matched prefix chain before any alloc: the walk's
        # pages may be cache-only (ref 1), and alloc's LRU eviction could
        # otherwise reclaim — and even hand back as an install target —
        # the very parent pid the publish chain below runs through,
        # recording shipped pages under a recycled physical id (the
        # cross-request corruption the chain keys exist to prevent).
        matched_pids: list[int] = []
        parent, idx = root, 0
        for b in blocks:
            pid = self.allocator.lookup((parent, b))
            if pid is None:
                break
            self.allocator.retain(pid)
            matched_pids.append(pid)
            parent, idx = pid, idx + 1
        todo = list(range(idx, len(blocks)))
        installed = 0
        dt = 0.0
        if todo:
            try:
                pids = self.allocator.alloc(len(todo))
            except MemoryError:
                pids = []
            if pids:
                t0 = time.monotonic()
                self._install_pages(pids, [pages[i] for i in todo])
                for pid, i in zip(pids, todo):
                    self.allocator.publish((parent, blocks[i]), pid)
                    parent = pid
                    # The cache's own reference keeps the page resident
                    # (and LRU-evictable); the importer holds none.
                    self.allocator.release(pid)
                jax.block_until_ready(self.cache)
                dt = max(time.monotonic() - t0, 1e-9)
                self._handoff_pids.update(pids)
                installed = len(pids)
                # Bandwidth accounting ONLY over real installs, timed over
                # the device_put region alone: a no-op import (full pool,
                # all matched) clocking the blob's bytes over microseconds
                # would inflate the measured kv_put_mbps the gateway's
                # cost model trusts — and keep shipping prefills into the
                # very replica that cannot install them.
                self.kv_import_bytes += installed * self.page_bytes
                self.kv_import_seconds += dt
        for pid in matched_pids:
            self.allocator.release(pid)
        self.metrics.kv_handoff_imports.inc()
        self.metrics.kv_handoff_tokens.inc(installed * ps)
        return {
            "installed_pages": installed,
            "matched_pages": idx,
            "tokens": installed * ps,
            "shipped_tokens": len(blocks) * ps,
            "seconds": round(dt, 6),
        }

    def _ctx_pages_bucket(self, d: int) -> int:
        """Gather-bucket (in pages) covering a context of ``d`` tokens."""
        if d <= 0:
            return 0
        need = -(-d // self.page_size)
        return min(_next_pow2(need, floor=1), self.maxp)

    def _run_paged_prefill(self, tokens, d: int, s: int, s_bucket: int,
                           ctx_row, write_pids, temp: float, top_p: float,
                           rng, slot: int | None = None, adapter: int = 0,
                           fsm_start: int = 0):
        """Compile-on-miss + call of the (s_bucket, ctx_pages) prefill
        program — the one shared path for slot prefills and page warming."""
        ps, maxp = self.page_size, self.maxp
        s_bucket = min(_next_pow2(max(s_bucket, ps), floor=ps), maxp * ps)
        ctx = self._ctx_pages_bucket(d)
        from ditl_tpu.infer.engine import lru_program

        key = (s_bucket, ctx)

        def build():
            logger.info(
                "compiling paged prefill for bucket %d (ctx %d pages)",
                s_bucket, ctx,
            )
            return self._build_paged_prefill(s_bucket, ctx)

        program = lru_program(self._paged_prefill, key, build)
        ids = np.full((1, s_bucket), self.tokenizer.pad_id, np.int32)
        ids[0, :s] = tokens
        n_wp = s_bucket // ps
        pids = np.zeros((n_wp,), np.int32)
        pids[: min(len(write_pids), n_wp)] = write_pids[:n_wp]
        row = np.zeros((max(ctx, 1),), np.int32)
        row[: min(len(ctx_row), ctx)] = ctx_row[:ctx]
        return self._take_prefill(program(
            self.params, self.cache,
            jnp.asarray(row), jnp.asarray(ids), jnp.int32(d),
            jnp.int32(s), jnp.float32(temp), jnp.float32(top_p), rng,
            jnp.asarray(pids), jnp.asarray([adapter], jnp.int32),
            *self._fsm_args(fsm_start),
        ), slot)

    def _paged_prefill_chunk(self, req: Request, slot: int, d: int, s: int,
                             s_bucket: int, rng):
        """Run one paged prefill program call over prompt[d:d+s]."""
        ps = self.page_size
        return self._run_paged_prefill(
            req.prompt[d: d + s], d, s, s_bucket,
            ctx_row=self._table[slot],
            write_pids=self._table[slot, d // ps:],
            temp=req.temperature, top_p=req.top_p, rng=rng, slot=slot,
            adapter=req.adapter_id, fsm_start=req.fsm_start,
        )

    def _tick_advance_bound(self) -> int:
        """Worst-case KV-write-position advance of one decode tick — how far
        ahead optimistic page top-up must cover. Speculative ticks write the
        whole (k+1)-token verify window every round even when little is
        accepted, hence the extra ``spec_k + 1`` over the emission bound."""
        if self.speculative:
            return self.spec_rounds * (self.spec_k + 1) + self.spec_k + 1
        return self.decode_chunk

    def _admit_paged_slot(self, slot: int) -> bool:
        """Admit the queue head into ``slot`` (paged mode).

        ``admission="reserve"`` (default): reserve the request's worst-case
        pages (prompt + max_new) up front — admission fails (request stays
        queued, False returned) when the pool cannot cover it, so decode
        never faults mid-flight.

        ``admission="optimistic"``: reserve only prompt + one tick of
        headroom; further pages are allocated per tick (``_topup_pages``),
        and pool exhaustion preempts the youngest request instead of
        blocking admission — strictly more concurrency at equal pool bytes
        when requests finish before their pessimistic ``max_tokens``."""
        while True:
            req = self._queue[0]
            if not (req.finished or req.cancelled):
                break
            # A preempted request can complete (or be cancelled) while
            # queued — its pending tick's lagged harvest delivered the
            # final chunk and already recorded it in _completed. Nothing
            # to admit; drop it and try the next head.
            self._queue.pop(0)
            if not self._queue:
                return False
        if req.preempted:
            return self._resume_paged_slot(slot, req)
        ps = self.page_size
        matched = self.allocator.match_prefix(
            req.prompt, ps, root=-req.adapter_id
        )  # retained
        # Host-tier swap-in (ISSUE 13): extend the HBM run from the host
        # store before deciding how much prefill this admission costs. If
        # admission then defers (budget/pool), the swapped pages stay
        # published — the retry rematches them in HBM for free.
        matched, host_tokens = self._host_swap_in(req, matched)
        d0 = len(matched) * ps
        # Token-budget gate (ISSUE 8): an unchunked admission prefills its
        # whole unmatched prompt THIS tick; defer it when that would bust
        # the tick's prefill allowance (a chunked admission costs nothing
        # now — its chunks draw the allowance as they run).
        s = len(req.prompt) - d0
        cost = 0 if (self.prefill_chunk and s > self.prefill_chunk) else s
        if not self._budget_allows(cost):
            for pid in matched:
                self.allocator.release(pid)
            return False
        worst = -(-(len(req.prompt) + req.max_new_tokens) // ps)
        if self.admission == "optimistic" and not self._degraded:
            want = -(-(len(req.prompt) + self._tick_advance_bound()) // ps)
            n_total = min(max(want, len(matched)), worst)
        else:
            n_total = worst
        n_fresh = n_total - len(matched)
        try:
            fresh = self.allocator.alloc(n_fresh)
        except MemoryError:
            for pid in matched:
                self.allocator.release(pid)
            return False
        self._queue.pop(0)
        self._note_admitted(req)
        # Handoff attribution (ISSUE 13): matched pages installed by
        # import_kv count under the `handoff` tier label on their first
        # reuse — the counter the handoff drill pins reused == shipped on.
        handoff_tokens = 0
        if self._handoff_pids:
            hand = [p for p in matched if p in self._handoff_pids]
            if hand:
                self._handoff_pids.difference_update(hand)
                handoff_tokens = len(hand) * ps
        self._note_prefix_cache(req, d0, host_tokens=host_tokens,
                                handoff_tokens=handoff_tokens)
        pages = matched + fresh
        self._slot_pages[slot] = pages
        self._table[slot, :] = 0
        self._table[slot, : len(pages)] = pages
        self._table_dirty = True
        d0 = len(matched) * ps
        slot_key, sub = jax.random.split(jax.random.key(req.seed))
        req.slot = slot
        self._slots[slot] = req
        s = len(req.prompt) - d0
        if self.prefill_chunk and s > self.prefill_chunk:
            req.prefill_pos = d0
            req.prefilling = True
            self.cur = self.cur.at[slot].set(self.tokenizer.pad_id)
            self.pos = self.pos.at[slot].set(0)
        else:
            w0, m0 = time.time(), time.monotonic()
            first = self._paged_prefill_chunk(req, slot, d0, s, s, sub)
            self._record_prefill(req, s, d0, w0,
                                 time.monotonic() - m0, "prompt")
            self._publish_prompt_pages(req, slot)
            self.cur = self.cur.at[slot].set(first)
            self.pos = self.pos.at[slot].set(len(req.prompt))
            self._set_hist(slot, req.prompt, first)
            self._draft_prefill(req, slot)
        self.temps = self.temps.at[slot].set(req.temperature)
        self.top_ps = self.top_ps.at[slot].set(req.top_p)
        self.keys = self.keys.at[slot].set(slot_key)
        self.adapters = self.adapters.at[slot].set(req.adapter_id)
        self.limits = self.limits.at[slot].set(
            len(req.prompt) + req.max_new_tokens
        )
        return True

    def _resume_paged_slot(self, slot: int, req: Request) -> bool:
        """Re-admit a preempted request with its exact mid-flight state.

        The KV for ``prompt + tokens`` is re-prefilled (one shot — resume
        skips chunked prefill; the preemption publish below usually makes
        this a near-full prefix match), then the captured device scalars
        restore the sampling frontier: ``cur`` = the PENDING sampled token
        (one ahead of ``tokens[-1]``), ``pos`` = its write position, the
        per-slot PRNG key (a split chain — not derivable from token count),
        the FSM state, and the pending logprob stats. Decode then continues
        bit-exactly where it left off."""
        ps = self.page_size
        ctx = req.prompt + req.tokens
        pos = len(ctx)  # cur's write position
        cap = len(req.prompt) + req.max_new_tokens
        matched = self.allocator.match_prefix(ctx, ps, root=-req.adapter_id)
        # Budget gate: the resume's chunks run back-to-back inside THIS
        # admission (they never interleave across ticks — see below), so
        # the whole unmatched remainder is this tick's prefill cost.
        if not self._budget_allows(pos - len(matched) * ps):
            for pid in matched:
                self.allocator.release(pid)
            return False
        worst = -(-cap // ps)
        if self.admission == "optimistic" and not self._degraded:
            n_total = min(-(-(pos + self._tick_advance_bound()) // ps), worst)
        else:
            n_total = worst
        n_total = max(n_total, len(matched))
        try:
            fresh = self.allocator.alloc(n_total - len(matched))
        except MemoryError:
            for pid in matched:
                self.allocator.release(pid)
            return False
        self._queue.pop(0)
        self._note_admitted(req)  # no-op for an already-admitted resume
        pages = matched + fresh
        self._slot_pages[slot] = pages
        self._table[slot, :] = 0
        self._table[slot, : len(pages)] = pages
        self._table_dirty = True
        d0 = len(matched) * ps
        s = pos - d0
        req.slot = slot
        self._slots[slot] = req
        # The prefill programs' sampled tokens are discarded — the real
        # pending token was captured at preemption; rng is irrelevant for
        # the same reason (keys restored below). When the engine is
        # configured for chunked prefill, the resume honors the bound: a
        # published-pages eviction under pressure can make the unmatched
        # remainder the FULL context, and a one-shot next_pow2(s) program
        # would be exactly the compile/memory blowup prefill_chunk exists
        # to prevent. (The chunks run back-to-back within this admission —
        # resume does not interleave them across ticks.)
        self._win_resume_tokens += pos - d0  # thrash-guard accounting
        self.resume_prefill_tokens += pos - d0
        req.resume_tokens += pos - d0  # per-request thrash for the ledger
        step = self.prefill_chunk or s
        d = d0
        w0, m0 = time.time(), time.monotonic()
        while d < pos:
            n = min(step, pos - d)
            self._run_paged_prefill(
                ctx[d: d + n], d, n, n,
                ctx_row=self._table[slot],
                write_pids=self._table[slot, d // ps:],
                temp=req.temperature, top_p=req.top_p,
                rng=jax.random.key(req.seed), slot=slot,
                adapter=req.adapter_id, fsm_start=req.fsm_start,
            )
            d += n
        if pos > d0:
            # Resume prefills monopolize ticks exactly like fresh ones —
            # they must show up in the interference attribution too.
            self._record_prefill(req, pos - d0, d0, w0,
                                 time.monotonic() - m0, "resume")
        self.cur = self.cur.at[slot].set(req.preempt_cur)
        self.pos = self.pos.at[slot].set(pos)
        self.keys = self.keys.at[slot].set(req.preempt_key)
        if self.guided and req.preempt_fst is not None:
            self.fstates = self.fstates.at[slot].set(req.preempt_fst)
        if self.logprobs_k and req.preempt_lp is not None:
            self._store_lp(slot, *req.preempt_lp)
        self._set_hist(slot, ctx, req.preempt_cur)
        self._draft_prefill(req, slot, ctx=ctx)
        self.temps = self.temps.at[slot].set(req.temperature)
        self.top_ps = self.top_ps.at[slot].set(req.top_p)
        self.adapters = self.adapters.at[slot].set(req.adapter_id)
        self.limits = self.limits.at[slot].set(cap)
        req.preempted = False
        req.preempt_cur = req.preempt_key = None
        req.preempt_fst = req.preempt_lp = None
        return True

    def _pick_victim(self, needy: Request) -> int | None:
        """The in-flight request ranked STRICTLY worse than ``needy`` in
        (SLO class, age) order, worst first — so under pressure best-effort
        work is always the first casualty, batch next, and within a class
        the youngest goes first (the pre-SLO rule). The request with the
        minimal (class, req_id) key is never preempted and always
        progresses — the same no-deadlock invariant as the age-only rule,
        lifted to the lexicographic (class, age) order; cross-class
        ping-pong is impossible because a lower class can never evict a
        higher one. Prefilling slots are eligible victims too (ADVICE r4:
        skipping them let the needy request preempt ITSELF when every
        younger request was still prefilling, transiently breaking the
        invariant); a mid-prefill victim has no sampling frontier yet and
        is simply requeued as fresh. None when ``needy`` itself holds the
        worst rank."""
        best: int | None = None
        for slot, req in enumerate(self._slots):
            if (req is None or req.finished
                    or req.cancelled or req.slo_rank <= needy.slo_rank):
                continue
            if best is None or req.slo_rank > self._slots[best].slo_rank:
                best = slot
        return best

    def _preempt_slot(self, slot: int) -> None:
        """Reclaim a slot's pages mid-flight and requeue its request at the
        queue head. The full pages of ``prompt + tokens`` are PUBLISHED
        before release, so they stay resident (LRU-evictable under real
        pressure) and the resume prefill is a near-full prefix match —
        re-admission costs roughly one partial-page prefill. Capture of the
        sampling frontier stays device-lazy (no transfer)."""
        req = self._slots[slot]
        if req.prefilling:
            # Mid-prefill: nothing sampled yet, no frontier to capture —
            # requeue as a FRESH request. The chunks already written are
            # published (whole pages only) so re-admission prefix-matches
            # them and the lost work is at most one partial page.
            self._publish_tokens(
                req.prompt[: req.prefill_pos], slot, req.adapter_id
            )
            req.prefilling = False
            req.prefill_pos = 0
            self._slots[slot] = None
            self._free_slot_pages(slot)
            self._enqueue(req)  # old req_id => front of its class
            self.preemptions += 1
            req.preempt_count += 1
            self.metrics.preemptions.inc()
            logger.info(
                "preempted mid-prefill request %d; requeued fresh", req.req_id
            )
            return
        req.preempted = True
        req.preempt_cur = self.cur[slot]
        req.preempt_key = self.keys[slot]
        if self.guided:
            req.preempt_fst = self.fstates[slot]
        if self.logprobs_k:
            req.preempt_lp = (
                self.lp_chosen[slot], self.lp_ids[slot], self.lp_top[slot]
            )
        self._publish_tokens(req.prompt + req.tokens, slot, req.adapter_id)
        self._slots[slot] = None
        self._free_slot_pages(slot)
        self._enqueue(req)  # old req_id => front of its class
        self.preemptions += 1
        req.preempt_count += 1
        self.metrics.preemptions.inc()
        logger.info(
            "preempted request %d (%d tokens in); pages reclaimed",
            req.req_id, len(req.tokens),
        )

    def _topup_pages(self) -> None:
        """Optimistic admission's per-tick page feed: before dispatch, every
        decoding slot's table must cover this tick's worst-case writes
        (``_tick_advance_bound``). On pool exhaustion, preempt the youngest
        younger-than-needy request and retry; when the needy request IS the
        youngest, preempt it instead — older requests keep their pages and
        the oldest always progresses (no deadlock, no preemption ping-pong)."""
        if self.cache_mode != "paged" or self.admission != "optimistic":
            return
        self._win_ticks += 1
        if self._win_ticks >= self._thrash_window:
            ratio = self._win_resume_tokens / max(1, self._win_gen_tokens)
            # Release needs the BACKLOG drained, not just a quiet window:
            # while degraded, worst-case reservations suppress preemption,
            # so the ratio alone always looks quiet and the guard would
            # oscillate (optimism burst -> thrash -> degrade) every
            # window. An empty admission queue is the causal signal that
            # the pressure the thrash came from has cleared. (Pool slack
            # is not usable here: in the thrash regime the "evictable"
            # pages ARE the preempted requests' published working sets.)
            drained = not self._queue
            if not self._degraded and ratio > self._thrash_engage:
                self._degraded = True
                self.admission_degrades += 1
                self.metrics.admission_degrades.inc()
                logger.info(
                    "optimistic admission degraded to worst-case reservation"
                    " (resume-prefill/generated = %.2f over %d ticks)",
                    ratio, self._thrash_window,
                )
            elif self._degraded and ratio < self._thrash_release and drained:
                self._degraded = False
                logger.info(
                    "optimistic admission re-engaged (thrash ratio %.2f, "
                    "backlog drained)", ratio,
                )
            self._win_ticks = 0
            self._win_resume_tokens = 0
            self._win_gen_tokens = 0
        ps, adv = self.page_size, self._tick_advance_bound()
        # One pending (unharvested) tick in pipelined mode can have advanced
        # the device frontier past the harvested token count.
        lag = 2 if self.pipeline_ticks else 1
        for slot in range(self.n_slots):
            req = self._slots[slot]
            if req is None or req.prefilling or req.finished or req.cancelled:
                continue
            cap = len(req.prompt) + req.max_new_tokens
            # Resync to the ACTUAL frontier (prompt + harvested tokens) each
            # tick rather than accumulating the worst-case bound — under
            # speculative ticks the bound is pessimistic (the verify window
            # is written every round but only accepted tokens advance), and
            # accumulation would degenerate to reserve-mode footprint.
            target = min(len(req.prompt) + len(req.tokens) + lag * adv, cap)
            need = -(-target // ps)
            while True:
                have = len(self._slot_pages[slot])
                if need <= have:
                    break
                try:
                    fresh = self.allocator.alloc(need - have)
                except MemoryError:
                    victim = self._pick_victim(req)
                    if victim is None:
                        self._preempt_slot(slot)
                        break
                    self._preempt_slot(victim)
                    continue
                self._table[slot, have: have + len(fresh)] = fresh
                self._slot_pages[slot].extend(fresh)
                self._table_dirty = True

    def _close_spans(self, req: Request, **attrs) -> None:
        """End the request's open tracing spans (idempotent). Every
        terminal path — completion, deadline expiry, cancellation — funnels
        here so an armed tracer never leaks an unclosed request span."""
        if req.queue_span is not None:
            req.queue_span.end(**attrs)
            req.queue_span = None
        if req.request_span is not None:
            req.request_span.end(tokens=len(req.tokens), **attrs)
            req.request_span = None

    def _note_usage_row(self, row: dict) -> None:
        """One usage-accounting row into both sinks (meter + ledger),
        whichever is armed. Never raises into the scheduler: billing must
        not take down serving (the anomaly-plane rule)."""
        if self.usage is None and self.usage_ledger is None:
            return
        try:
            if self.usage is not None:
                self.usage.note_terminal(row)
            if self.usage_ledger is not None:
                self.usage_ledger.record(**row)
        except Exception:  # noqa: BLE001 - metering must not crash serving
            logger.exception("usage metering failed (row dropped)")

    def _note_usage_terminal(self, req: Request, outcome: str) -> None:
        """Build and record the ONE terminal usage row for ``req`` — the
        per-request accounting the engine already computed, attributed to
        the request's tenant (ISSUE 15 tentpole). Written once at end like
        spans (crash-consistent: a SIGKILL loses at most this row), from
        every terminal path: completion (200), deadline eviction (504),
        and cancellation; submit-time 429s write their own thin row.
        Idempotent via ``usage_noted`` — cancel racing a lagged pipelined
        harvest must not bill twice."""
        if req.usage_noted or (self.usage is None
                               and self.usage_ledger is None
                               and self.adapter_registry is None):
            # With ONLY the adapter plane armed the row still gets built:
            # the owner's gather bill accrues in the registry even when
            # this replica writes no per-request ledger of its own.
            return
        req.usage_noted = True
        t_now = time.monotonic()
        row = {
            # req.tenant was sanitized at submit; sanitize again so a
            # directly-constructed Request (tests, embedders) can never
            # leak an unsanitized identifier into the ledger.
            "tenant": sanitize_label(req.tenant),
            "outcome": outcome,
            "slo_class": req.slo_class,
            "req_id": req.req_id,
            "prompt_tokens": len(req.prompt),
            "generated_tokens": len(req.tokens),
            "cache_hit_tokens": req.cache_hit_tokens,
            "cache_hit_host_tokens": req.cache_hit_host_tokens,
            "cache_hit_handoff_tokens": req.cache_hit_handoff_tokens,
            "prefilled_tokens": req.cache_miss_tokens,
            "queue_wait_s": round(req.t_admitted - req.t_submit, 6)
            if req.t_admitted and req.t_submit else 0.0,
            "device_time_est_s": round(req.device_time_est_s, 6),
            "interference_absorbed_s": round(req.interference_s, 6),
            "preemptions": req.preempt_count,
            "resume_prefill_tokens": req.resume_tokens,
            "e2e_s": round(t_now - req.t_submit, 6) if req.t_submit
            else 0.0,
        }
        if req.adapter_id and self.adapter_registry is not None:
            # Adapter attribution (ISSUE 16): stamp the serving adapter's
            # name/generation on the requester's row and accumulate the
            # per-request gather cost against the adapter's OWNER (flushed
            # as the owner's own ledger rows by the registry) — the
            # requester pays for tokens, the owner pays for the gather.
            try:
                self.adapter_registry.bill_request(req.adapter_id, row)
            except Exception:  # noqa: BLE001 - billing must not kill serving
                logger.exception("adapter billing failed (annotation lost)")
        self._note_usage_row(row)

    # -- adapter hot load/evict seams (ISSUE 16, infer/adapters.py) ----------
    # Driver-thread-only, like every other mutation of engine/device state:
    # the registry reaches them through ThreadedEngine.call, so a row swap
    # lands BETWEEN ticks — an in-flight request never samples a
    # half-swapped adapter (its slot's adapter id keeps pointing at the
    # old, still-intact row until the registry's drain frees it).

    def install_adapter(self, row: int, tree: dict) -> None:
        """Overwrite pool row ``row`` of the stacked adapter leaves with
        ``tree`` (a single-adapter {target: {a, b}} host tree). Purely a
        functional ``.at[:, row].set`` per leaf — params are never donated
        to the compiled programs, so the next tick simply reads the new
        arrays; no recompile (shapes unchanged), no restart."""
        if not self.multi_lora:
            raise ValueError("engine does not serve a multi-adapter stack")
        if not 1 <= row < self.n_adapters:
            raise ValueError(
                f"adapter row {row} out of range [1, {self.n_adapters})"
                " (row 0 is the base model)")
        lora = self.params["layers"]["lora"]
        new = {}
        for target, leaves in lora.items():
            if target not in tree:
                raise ValueError(f"adapter tree missing target {target!r}")
            new[target] = {}
            for leaf, stacked in leaves.items():
                arr = jnp.asarray(tree[target][leaf], stacked.dtype)
                if arr.shape != stacked.shape[:1] + stacked.shape[2:]:
                    raise ValueError(
                        f"adapter leaf {target}.{leaf} shape {arr.shape} "
                        f"!= pool row shape "
                        f"{stacked.shape[:1] + stacked.shape[2:]}")
                new[target][leaf] = stacked.at[:, row].set(arr)
        self.params["layers"]["lora"] = new

    def clear_adapter(self, row: int) -> None:
        """Zero pool row ``row`` (== the base model's delta): an evicted
        row must not keep serving stale weights if a future bug ever lets
        an id reach it without an install."""
        self.install_adapter(row, {
            target: {leaf: jnp.zeros(
                stacked.shape[:1] + stacked.shape[2:], stacked.dtype)
                for leaf, stacked in leaves.items()}
            for target, leaves in self.params["layers"]["lora"].items()
        })

    def adapter_row_in_use(self, row: int) -> int:
        """How many in-flight requests (slots + admission queue) reference
        pool row ``row`` — the registry's drain predicate before a row is
        freed or reused."""
        n = sum(1 for r in self._slots
                if r is not None and r.adapter_id == row
                and not (r.finished or r.cancelled))
        n += sum(1 for r in self._queue if r.adapter_id == row)
        return n

    def purge_adapter_pages(self, row: int) -> int:
        """Drop every published prefix-cache page namespaced under pool
        row ``row`` (paged mode publishes under ``root=-adapter_id``):
        after an evict/reinstall, stale KV computed under the old weights
        must never prefix-match a request on the row's next occupant."""
        if self.cache_mode == "paged":
            return self.allocator.purge_root(-row)
        return 0

    def _expire(self, req: Request) -> None:
        """Terminal bookkeeping for a deadline eviction: the request
        completes (with whatever tokens it already produced), waiters see
        ``expired``, streams get their terminal None, and the dedicated
        counter moves — distinguishable from completion AND from client
        cancellation on /metrics."""
        req.expired = True
        req.finished = True
        req.cancelled = True  # lagged pipelined harvests must skip it
        self.metrics.deadline_expired.inc()
        self._note_usage_terminal(req, "504")
        self._close_spans(req, expired=True)
        if req.stream is not None:
            req.stream.put(None)
        self._completed[req.req_id] = req

    def _expire_deadlines(self) -> None:
        """Evict every queued/slotted request whose deadline passed — run
        once per scheduler tick BEFORE admission and dispatch, so expired
        work never costs a prefill or decode chunk it no longer needs. A
        request mid-chunk when its deadline passes finishes that one chunk
        (the program is already dispatched) and is evicted at the next
        tick: at most one chunk of overrun, pinned by test_chaos."""
        now = time.monotonic()
        for req in list(self._queue):
            if req.finished or req.cancelled:
                # Preempted request that COMPLETED via its pending tick's
                # lagged harvest while queued: its stream already got its
                # terminal None and the result sits in _completed —
                # re-expiring it would double-count the metric and turn a
                # full result into a 504 (same state cancel() handles).
                continue
            if req.deadline is not None and now >= req.deadline:
                self._queue.remove(req)
                self._expire(req)
        for slot, req in enumerate(self._slots):
            if (
                req is not None and not req.finished and not req.cancelled
                and req.deadline is not None
                and now >= req.deadline
            ):
                self._slots[slot] = None
                if self.cache_mode == "paged":
                    self._free_slot_pages(slot)
                self._expire(req)

    def _note_admitted(self, req: Request) -> None:
        """Telemetry at queue -> slot admission. A preemption-resume is not
        a second admission (queue wait is measured once, submit -> first
        slot)."""
        if req.t_admitted:
            return
        req.t_admitted = time.monotonic()
        self.metrics.admitted.inc()
        if req.t_submit:  # directly-constructed Requests carry no stamp
            self.metrics.queue_wait.observe(req.t_admitted - req.t_submit)
        if req.queue_span is not None:
            req.queue_span.end(
                queue_wait_s=round(req.t_admitted - req.t_submit, 6)
                if req.t_submit else 0.0,
            )
            req.queue_span = None

    def _budget_allows(self, cost: int) -> bool:
        """Does this tick's prefill allowance cover ``cost`` more tokens?
        The tick's FIRST prefill always passes (at-least-one-chunk progress
        rule — a tight budget bounds the stall, it must not starve
        admission forever), so the honest per-tick bound is
        ``max(one chunk, budget - decode_ready*decode_chunk)``."""
        if self._tick_prefill_left is None or cost <= 0:
            return True
        return self._tick_prefill_spent == 0 or cost <= self._tick_prefill_left

    def _note_prefix_cache(self, req: Request, hit_tokens: int,
                           host_tokens: int = 0,
                           handoff_tokens: int = 0) -> None:
        """Record a FIRST admission's reused-vs-prefilled prompt split
        (prefix-cache accounting, ISSUE 8). Resume re-prefills never come
        here — their cost is thrash (resume_prefill_tokens), not a cache
        verdict on the prompt. Idempotent: a mid-prefill preemption victim
        is requeued as FRESH (no sampling frontier to capture), and its
        re-admission would otherwise count the prompt twice — with its own
        just-published pages masquerading as hits. ``host_tokens`` /
        ``handoff_tokens`` (ISSUE 13) split the hit under its tier label —
        a host swap-in or a shipped handoff page is a real reuse but NOT
        an HBM hit, and conflating them would hide exactly the churn the
        tier exists to absorb."""
        if req.cache_hit_tokens or req.cache_miss_tokens:
            return  # re-admission after a mid-prefill preemption
        req.cache_hit_tokens = hit_tokens
        req.cache_miss_tokens = len(req.prompt) - hit_tokens
        # Tier split stored per request too (ISSUE 15): the usage ledger
        # bills a host swap-in / shipped handoff differently from an HBM
        # hit, exactly like the fleet counters below do.
        req.cache_hit_host_tokens = host_tokens
        req.cache_hit_handoff_tokens = handoff_tokens
        self.metrics.note_prefix_cache(
            req.cache_hit_tokens, req.cache_miss_tokens,
            host_tokens=host_tokens, handoff_tokens=handoff_tokens,
        )

    def _record_prefill(self, req: Request, tokens: int, offset: int,
                        w0: float, dt: float, kind: str) -> None:
        """Register one prefill dispatch: feeds this tick's interference
        attribution (step()), debits the tick's token-budget allowance,
        and — when tracing — writes the chunk's span under the request's
        lifecycle span."""
        self._tick_prefills.append((req.req_id, tokens, dt))
        self._tick_prefill_spent += tokens
        if self._tick_prefill_left is not None:
            self._tick_prefill_left = max(0, self._tick_prefill_left - tokens)
        self.max_tick_prefill_tokens = max(
            self.max_tick_prefill_tokens, self._tick_prefill_spent
        )
        # Usage attribution (ISSUE 15): the dispatch wall of this prefill
        # is the request's own cost — the prefill half of the
        # device-time estimate, and the LIVE feed the noisy-neighbor
        # conviction window reads (a mid-storm batch job must be visible
        # before it terminates). Host clocks only.
        req.device_time_est_s += dt
        req.t_prefill_done = time.monotonic()
        if self.usage is not None:
            self.usage.note_prefill(req.tenant, tokens)
            self.usage.note_device(req.tenant, dt)
        if req.request_span is not None:
            self.tracer.start_span(
                "engine.prefill", parent=req.request_span, t0=w0,
                req=req.req_id, offset=offset, tokens=tokens, kind=kind,
            ).end(t_end=w0 + dt)

    def _admit(self) -> None:
        for slot in range(self.n_slots):
            if self._slots[slot] is not None or not self._queue:
                continue
            if self.cache_mode == "paged":
                if not self._admit_paged_slot(slot):
                    # Priority FIFO: the head (highest class, oldest)
                    # request doesn't fit the pool or the tick's prefill
                    # allowance right now; don't let smaller or
                    # lower-class requests starve it indefinitely.
                    break
                continue
            req = self._queue[0]
            # Token-budget gate (ISSUE 8): an unchunked admission prefills
            # its whole unmatched prompt this tick — defer when that would
            # bust the allowance (chunked admissions only seed the slot
            # here; their chunks draw the allowance as they run). The
            # match is passed down so _prefill_into_slot never recomputes
            # it.
            prefix = (
                self._match_prefix(req.prompt) if req.adapter_id == 0
                else None
            )
            d0 = 0 if prefix is None else prefix[2]
            s = len(req.prompt) - d0
            if not self._budget_allows(
                0 if (self.prefill_chunk and s > self.prefill_chunk) else s
            ):
                break
            self._queue.pop(0)
            self._note_admitted(req)
            slot_key = jax.random.key(req.seed)
            slot_key, sub = jax.random.split(slot_key)
            req.slot = slot
            w0, m0 = time.time(), time.monotonic()
            first = self._prefill_into_slot(req, slot, sub, prefix)
            if first is not None:
                # Chunked prefill (first is None) records per chunk in
                # step()'s advance loop instead. Tokens = the suffix the
                # program actually prefilled (prefix-matched tokens cost
                # no device work and must not debit the token budget the
                # gate above charged only `s` against).
                self._record_prefill(
                    req, s, d0, w0,
                    time.monotonic() - m0, "prompt",
                )
            self._slots[slot] = req
            if first is None:
                # Chunked prefill in progress: park the row's decode writes
                # on the last cache slot (never attended before it is
                # legitimately overwritten) until the prompt is fully in.
                self.cur = self.cur.at[slot].set(self.tokenizer.pad_id)
                self.pos = self.pos.at[slot].set(self.smax - 1)
            else:
                self.cur = self.cur.at[slot].set(first)
                self.pos = self.pos.at[slot].set(len(req.prompt))
                self._set_hist(slot, req.prompt, first)
                self._draft_prefill(req, slot)
            self.temps = self.temps.at[slot].set(req.temperature)
            self.top_ps = self.top_ps.at[slot].set(req.top_p)
            self.keys = self.keys.at[slot].set(slot_key)
            self.adapters = self.adapters.at[slot].set(req.adapter_id)

    def _advance_prefill_chunks(self, reqs: list) -> None:
        """Advance one prefill chunk per request in SLO order (class rank,
        then age) so a tight allowance feeds interactive prefills before
        batch/best-effort ones; a chunk that would bust the remaining
        allowance parks until a later tick (the slot stays prefilling, its
        decode row parked)."""
        for req in sorted(reqs, key=lambda r: r.slo_rank):
            if not req.prefilling or req.finished or req.cancelled:
                continue
            cost = min(self.prefill_chunk, len(req.prompt) - req.prefill_pos)
            if not self._budget_allows(cost):
                continue
            d_before = req.prefill_pos
            w0, m0 = time.time(), time.monotonic()
            self._advance_prefill(req)
            self._record_prefill(
                req, req.prefill_pos - d_before, d_before, w0,
                time.monotonic() - m0, "chunk",
            )

    def _snapshot_slots(self) -> list[tuple[Request | None, bool]]:
        """(request, was_prefilling) per slot AT DISPATCH TIME — pipelined
        ticks harvest one tick late, by which point admission may have
        refilled a freed slot; the snapshot keeps the lagged harvest bound
        to the requests whose tokens the tick actually computed."""
        return [
            (r, r.prefilling if r is not None else False) for r in self._slots
        ]

    def _harvest(self, emitted: np.ndarray, counts: np.ndarray | None = None,
                 lp=None, snapshot=None) -> None:
        """``counts`` (speculative ticks): per-row valid-emission counts —
        spec rounds emit 1..K+1 tokens, so the row is count-delimited
        instead of pad-delimited (a live row's tick can end without the pad
        filler that marks death in the plain tick's fixed-width output).
        ``lp`` (chosen, top_ids, top_lp arrays, column-aligned with
        ``emitted``): per-token logprob stats, attached to requests that
        asked for them. ``snapshot`` (pipelined ticks): the slot states at
        dispatch time (see ``_snapshot_slots``)."""
        eos, pad = self.tokenizer.eos_id, self.tokenizer.pad_id
        if snapshot is None:
            snapshot = self._snapshot_slots()
        t_now = time.monotonic()  # one clock read per harvest, shared below
        # Decode-tick device-time share (ISSUE 15): the slots of one tick
        # ran ONE device program together, so each live decode slot's
        # harvest interval is attributed 1/n_share to its request — the
        # decode half of the per-request device-time estimate (the prefill
        # half is measured per dispatch in _record_prefill). An estimate
        # by construction (host wall, pipelined ticks overlap dispatch);
        # consistent ACROSS tenants, which is what billing shares and
        # convictions need. Zero device syncs: t_now is already read.
        n_share = sum(
            1 for r, was_p in snapshot
            if r is not None and not was_p
            and not r.finished and not r.cancelled
        )
        for slot, (req, was_prefilling) in enumerate(snapshot):
            if req is None or was_prefilling:
                # A still-prefilling slot is parked: its decode-row output is
                # pad filler, not a finished (empty) generation.
                continue
            if req.finished or req.cancelled:
                # Pipelined ticks: the slot decoded one extra (dead) chunk
                # after the request finished or was cancelled — its row is
                # garbage and the request already completed/streamed.
                continue
            fresh: list[int] = []
            row = emitted[slot] if counts is None else emitted[slot][: counts[slot]]
            for j, tok in enumerate(row):
                tok = int(tok)
                if tok in (eos, pad) or len(req.tokens) >= req.max_new_tokens:
                    req.finished = True
                    break
                req.tokens.append(tok)
                fresh.append(tok)
                if lp is not None and req.logprobs is not None:
                    c, ids, top = lp
                    req.lp_token.append(float(c[slot, j]))
                    req.lp_top_ids.append([int(x) for x in ids[slot, j]])
                    req.lp_top.append([float(x) for x in top[slot, j]])
            if len(req.tokens) >= req.max_new_tokens:
                req.finished = True
            if self.cache_mode == "paged":
                self._win_gen_tokens += len(fresh)  # thrash-guard accounting
            if fresh:
                m = self.metrics
                m.tokens_generated.inc(len(fresh))
                if req.fsm_start > 0:
                    # Every one of these tokens decoded under the FSM mask.
                    m.grammar_masked.inc(len(fresh))
                first_chunk = req.t_first == 0.0
                if first_chunk:
                    req.t_first = t_now
                    if req.t_submit:
                        ttft = t_now - req.t_submit
                        m.ttft.observe(ttft)
                        # Hit/miss split (ISSUE 8): the histogram pair that
                        # answers "does a prefix-cache hit actually buy
                        # TTFT" from /metrics alone.
                        (m.ttft_cache_hit if req.cache_hit_tokens > 0
                         else m.ttft_cache_miss).observe(ttft)
                        # Class split (ISSUE 9): the disagg A/B grades
                        # interactive TTFT specifically.
                        cls_hist = m.ttft_by_class.get(req.slo_class)
                        if cls_hist is not None:
                            cls_hist.observe(ttft)
                elif req.t_last_emit:
                    # TPOT: this harvest interval amortized over the chunk's
                    # tokens, observed once per token. The first chunk is
                    # excluded (its interval is prefill-dominated — that is
                    # TTFT's job).
                    m.decode_token.observe(
                        (t_now - req.t_last_emit) / len(fresh), n=len(fresh)
                    )
                prev_emit = (req.t_last_emit or req.t_prefill_done
                             or req.t_admitted or req.t_submit)
                if prev_emit and n_share:
                    share = max(0.0, t_now - prev_emit) / n_share
                    req.device_time_est_s += share
                    if self.usage is not None:
                        self.usage.note_device(req.tenant, share)
                if req.request_span is not None:
                    # One decode span per harvested chunk, covering the
                    # interval a streaming client actually waited for it;
                    # interference absorbed since the last harvest rides it
                    # as the victim-side annotation (culprit = the tick's
                    # biggest prefill).
                    prev = req.t_last_emit or req.t_admitted or req.t_submit
                    dur = max(0.0, t_now - prev) if prev else 0.0
                    attrs = {"req": req.req_id, "tokens": len(fresh),
                             "first": first_chunk}
                    if req.interference_pending:
                        cid, ctok, _ = max(req.interference_pending,
                                           key=lambda e: e[2])
                        attrs.update(
                            interference_s=round(sum(
                                s for *_, s in req.interference_pending
                            ), 6),
                            interference_culprit=cid,
                            culprit_prefill_tokens=ctok,
                        )
                    w_now = time.time()
                    self.tracer.start_span(
                        "engine.decode", parent=req.request_span,
                        t0=w_now - dur, **attrs,
                    ).end(t_end=w_now)
                req.interference_pending.clear()
                req.t_last_emit = t_now
            if req.stream is not None and fresh:
                if req.logprobs is not None and lp is not None:
                    # Streamed logprobs ride the chunk: the entries for the
                    # tokens just appended (same OpenAI dict layout as the
                    # non-streaming path, sliced to the request's N).
                    n = req.logprobs
                    k = len(fresh)
                    req.stream.put((fresh, {
                        "token_logprobs": req.lp_token[-k:],
                        "top_ids": [r[:n] for r in req.lp_top_ids[-k:]],
                        "top_logprobs": [r[:n] for r in req.lp_top[-k:]],
                    }))
                else:
                    req.stream.put(fresh)
            if req.finished:
                self.metrics.completed.inc()
                if req.t_submit:
                    self.metrics.e2e.observe(t_now - req.t_submit)
                self._note_usage_terminal(req, "200")
                self._close_spans(
                    req,
                    interference_total_s=round(req.interference_s, 6),
                )
                if req.stream is not None:
                    req.stream.put(None)
                self._completed[req.req_id] = req
                if self._slots[slot] is req:  # not cancel-freed meanwhile
                    self._slots[slot] = None
                    if self.cache_mode == "paged":
                        # Publish before releasing: the content cache's own
                        # reference keeps the conversation's pages resident
                        # (and LRU-evictable) for follow-up turns.
                        self._publish_generated_pages(req, slot)
                        self._free_slot_pages(slot)

    def freeze_spec_threshold(self) -> None:
        """Pin the speculation threshold to its current value. REQUIRED for
        pod serving: the self-calibrating threshold derives from per-host
        WALL-CLOCK tick timings, so replicas could disagree on whether a
        tick speculates — different programs, divergent results, and a
        (loud but spurious) fingerprint shutdown. The pod driver and worker
        loop call this so every process decides from identical,
        broadcast-derived state only."""
        if self.speculative and self._spec_threshold_cfg is None:
            self._spec_threshold_cfg = self.spec_threshold
            logger.info(
                "speculation threshold frozen at %.2f for deterministic "
                "pod-wide tick decisions", self._spec_threshold_cfg,
            )

    def _table_device(self):
        if self._table_dirty:
            # .copy() is load-bearing: on the CPU backend jnp.asarray may
            # alias the numpy buffer ZERO-COPY, so a later host mutation
            # (preemption zeroing a row, optimistic top-up appending pages)
            # would race with a still-pending pipelined tick's device read
            # of this table — nondeterministic garbage gathers. The copy is
            # private to the device array; the host never touches it again.
            self._table_dev = jnp.asarray(self._table.copy())
            self._table_dirty = False
        return self._table_dev

    # -- guided decoding -----------------------------------------------------

    def register_grammar(self, g) -> int:
        """Install a compiled grammar (infer/grammar.CompiledGrammar) into
        the engine's device transition table; returns the grammar's START
        state — pass it (or the CompiledGrammar itself) as ``submit``'s
        ``grammar=``. Registration is content-deduplicated, so serving
        layers can call this per-request; the table row budget
        (``fsm_capacity``) is a hard cap — registration raises when a new
        grammar would not fit."""
        import hashlib

        if not self.guided:
            raise ValueError(
                "engine built with fsm_capacity=0; construct with "
                "fsm_capacity >= grammar states + 2 to serve guided requests"
            )
        tn = np.ascontiguousarray(g.token_next, np.int32)
        digest = hashlib.sha1(tn.tobytes()).hexdigest()
        with self._fsm_lock:
            if digest in self._grammars:
                return self._grammars[digest]
            s, vt = tn.shape
            v = self._fsm_host.shape[1]
            if vt > v:
                raise ValueError(
                    f"grammar table vocab {vt} exceeds the model head width {v}"
                )
            if self._fsm_used + s > self.fsm_capacity:
                raise ValueError(
                    f"fsm_capacity exhausted: {self._fsm_used} rows used + "
                    f"{s} needed > {self.fsm_capacity}"
                )
            base = self._fsm_used
            block = np.full((s, v), -1, np.int32)
            block[:, :vt] = np.where(tn >= 0, tn + base, -1)
            self._fsm_host[base : base + s] = block
            self._fsm_used += s
            self._fsm_dirty = True
            self._grammars[digest] = base
        logger.info(
            "registered grammar %s: %d states at rows [%d, %d)",
            getattr(g, "source", "?"), s, base, base + s,
        )
        return base

    def _fsm_device(self):
        with self._fsm_lock:
            if self._fsm_dirty:
                # .copy() for the same reason as _table_device: the host
                # table is appended by register_grammar while ticks may be
                # in flight; a zero-copy alias would race with device reads.
                self._fsm_dev = jnp.asarray(self._fsm_host.copy())
                self._fsm_dirty = False
            return self._fsm_dev

    @property
    def spec_threshold(self) -> float:
        """Breakeven tokens-per-verify-forward for a spec tick to win.
        Explicit construction value wins; otherwise the MEASURED ratio of
        per-round verify cost to per-step decode cost, with a conservative
        2.5 prior until both paths have been timed on this chip. Serial
        engines time every tick; ``pipeline_ticks`` engines self-calibrate
        through the bounded serial probe-tick warmup (``_serial_probe_due``
        — lagged pipelined fetches measure the pipeline period, not device
        cost, so they are never fed into the EMA)."""
        if self._spec_threshold_cfg is not None:
            return self._spec_threshold_cfg
        if self._plain_step_ms and self._spec_round_ms:
            return self._spec_round_ms / self._plain_step_ms
        return 2.5

    def _record_tick_time(self, kind, dt_ms: float) -> None:
        """EMA the per-unit tick cost, excluding each program's first call
        (compile). ``kind``: a plain-decode compile key, or "spec"."""
        if kind == "spec":
            if not self._timed_spec:
                self._timed_spec = True
                return
            per = dt_ms / self.spec_rounds
            self._spec_round_ms = (
                per if self._spec_round_ms is None
                else 0.5 * self._spec_round_ms + 0.5 * per
            )
        else:
            if kind not in self._timed_plain_keys:
                self._timed_plain_keys.add(kind)
                return
            per = dt_ms / self.decode_chunk
            self._plain_step_ms = (
                per if self._plain_step_ms is None
                else 0.5 * self._plain_step_ms + 0.5 * per
            )

    def _use_spec_tick(self, active: list[Request]) -> bool:
        """Speculate this tick? Compares the acceptance predicted for the
        CURRENT slots — each request's measured tokens-per-forward, falling
        back to the engine's workload EMA for unmeasured requests — against
        the verify/decode cost-ratio threshold. Probes (runs one
        speculative tick to re-measure) when nothing is measured yet and
        every ``spec_probe_every`` ticks, so a workload shift back to
        repetitive text is re-detected. Greedy batches take the pure
        argmax-acceptance program; batches with sampled slots take the
        rejection-sampling program (exact in distribution; greedy rows in
        the mix still accept by argmax, bit-exactly)."""
        if not self.speculative:
            return False
        if self.spec_draft == "model":
            # Model-based drafting speculates EVERY tick: the draft cache
            # stays position-synchronized only while spec ticks run (plain
            # ticks would advance the target without the drafter), and a
            # drafter is configured precisely because it pays on the
            # workload. The acceptance EMA still reports quality.
            self._tick_no += 1
            return True
        self._tick_no += 1
        preds = []
        for r in active:
            if r.spec_forwards > 0:
                preds.append(r.spec_tokens / r.spec_forwards)
            elif self.spec_acceptance_ema is not None:
                preds.append(self.spec_acceptance_ema)
            else:
                return True  # nothing measured anywhere yet: probe
        if self._tick_no % self.spec_probe_every == 0:
            return True
        return sum(preds) / len(preds) >= self.spec_threshold

    def _spec_dispatch(self, alive: jax.Array, sampled: bool) -> tuple:
        """Dispatch one speculative tick (async — nothing blocks); returns
        the pending-fetch record ``_spec_finish`` consumes."""
        import time as _time

        paged = self.cache_mode == "paged"
        key = (paged, sampled)
        if key not in self._spec_decode:
            self._spec_decode[key] = (
                self._build_spec_paged_decode(sampled) if paged
                else self._build_spec_decode(sampled)
            )
        lp_args = (
            (self.lp_chosen, self.lp_ids, self.lp_top)
            if self.logprobs_k else ()
        )
        fsm_args = (
            (self._fsm_device(), self.fstates) if self.guided else ()
        )
        draft_args = (
            (self.draft_params, self.draft_cache)
            if self.spec_draft == "model" else ()
        )
        t0 = _time.perf_counter()
        if paged:
            res = self._spec_decode[key](
                self.params, self.cache, self.cur, self.pos, alive,
                self._table_device(), self.limits, self.hist,
                self.temps, self.top_ps, self.keys, self.adapters,
                *draft_args, *fsm_args, *lp_args,
            )
        else:
            res = self._spec_decode[key](
                self.params, self.cache, self.cur, self.pos, alive,
                self.hist, self.temps, self.top_ps, self.keys, self.adapters,
                *draft_args, *fsm_args, *lp_args,
            )
        res = list(res)
        self.cache = res.pop(0)
        if self.spec_draft == "model":
            self.draft_cache = res.pop(0)
        (self.cur, self.pos, self.hist, self.keys, *res) = res
        if self.guided:
            self.fstates = res.pop(0)
        (toks, counts, rr, lp_state, lp_bufs) = res
        if self.logprobs_k:
            (self.lp_chosen, self.lp_ids, self.lp_top) = lp_state
        return ("spec", t0, toks, counts, rr,
                lp_bufs if self.logprobs_k else None, self._snapshot_slots())

    def _spec_finish(self, rec: tuple) -> None:
        """Fetch a dispatched speculative tick's outputs + acceptance
        accounting + harvest."""
        import time as _time

        (_, t0, toks, counts, rr, lp_bufs, snapshot) = rec
        # ONE device_get for every host-consumed output: each separate fetch
        # is a full round trip on remote-device transports (~100 ms here) —
        # three sequential fetches per tick erased the speculative win.
        if lp_bufs is not None:
            counts, rr, toks, lp = jax.device_get(
                (counts, rr, toks, lp_bufs)
            )
            counts, rr, toks = (np.asarray(x) for x in (counts, rr, toks))
            lp = tuple(np.asarray(x) for x in lp)
        else:
            counts, rr, toks = (
                np.asarray(x) for x in jax.device_get((counts, rr, toks))
            )
            lp = None
        if not self.pipeline_ticks or self._probe_timing:
            # Pipelined intervals measure the pipeline period (dispatch to
            # NEXT-step fetch, including foreign host work), not device
            # cost — feeding them into the threshold EMA would collapse
            # spec/plain ratios toward 1. Serial PROBE ticks (back-to-back
            # dispatch+fetch while the pipeline is drained) are the
            # exception: their interval is real device cost.
            self._record_tick_time("spec", (_time.perf_counter() - t0) * 1e3)
        self.spec_ticks += 1
        accs = []
        for slot, (req, was_prefilling) in enumerate(snapshot):
            if req is None or was_prefilling or req.finished or req.cancelled:
                # finished/cancelled: the pipelined dead chunk's counts are
                # a past-EOS continuation — garbage for acceptance stats.
                continue
            req.spec_tokens += int(counts[slot])
            req.spec_forwards += int(rr[slot])
            if rr[slot] > 0:
                # Drafted-token accounting: each verify round emits its
                # accepted draft prefix + one bonus/corrective token, so
                # accepted drafts = emitted - rounds (the bonus is ordinary
                # decode output, not a draft); the round's remaining spec_k
                # drafts were rejected. Clamped: a row hitting its token
                # limit mid-round can trim emissions below the identity.
                accepted = max(0, int(counts[slot]) - int(rr[slot]))
                drafted = int(rr[slot]) * self.spec_k
                self.metrics.spec_accepted.inc(accepted)
                self.metrics.spec_rejected.inc(max(0, drafted - accepted))
                accs.append(counts[slot] / rr[slot])
        if accs:
            mean = float(np.mean(accs))
            self.spec_acceptance_ema = (
                mean if self.spec_acceptance_ema is None
                else self._spec_ema_w * self.spec_acceptance_ema
                + (1.0 - self._spec_ema_w) * mean
            )
        self._harvest(toks, counts, lp=lp, snapshot=snapshot)

    def _plain_dispatch(self, active: list, alive: jax.Array,
                        sampled: bool) -> tuple:
        """Dispatch one plain decode tick (async); returns the
        pending-fetch record ``_plain_finish`` consumes."""
        import time as _time

        # top_p only matters when something actually samples — greedy rows
        # ignore it, so (False, True) would compile a redundant program.
        key = (sampled, sampled and any(r.top_p < 1.0 for r in active))
        lp_args = (
            (self.lp_chosen, self.lp_ids, self.lp_top)
            if self.logprobs_k else ()
        )
        fsm_args = (
            (self._fsm_device(), self.fstates) if self.guided else ()
        )
        t0 = _time.perf_counter()
        if self.cache_mode == "paged":
            if key not in self._paged_decode:
                self._paged_decode[key] = self._build_paged_decode(*key)
            res = self._paged_decode[key](
                self.params, self.cache, self.cur,
                self.pos, alive, self.temps, self.top_ps, self.keys,
                self._table_device(), self.limits, self.hist, self.adapters,
                *fsm_args, *lp_args,
            )
        else:
            if key not in self._decode_cache:
                self._decode_cache[key] = self._build_decode(*key)
            res = self._decode_cache[key](
                self.params, self.cache, self.cur, self.pos, alive,
                self.temps, self.top_ps, self.keys, self.hist, self.adapters,
                *fsm_args, *lp_args,
            )
        if self.guided:
            (self.cache, self.cur, self.pos, self.keys, self.hist,
             self.fstates, *res_rest) = res
        else:
            (self.cache, self.cur, self.pos, self.keys, self.hist,
             *res_rest) = res
        if self.logprobs_k:
            ((self.lp_chosen, self.lp_ids, self.lp_top), toks, c, i, t) = (
                res_rest
            )
            lp_dev = (c, i, t)
        else:
            (toks,) = res_rest
            lp_dev = None
        return ("plain", key, t0, toks, lp_dev, self._snapshot_slots())

    def _plain_finish(self, rec: tuple) -> None:
        """Fetch a dispatched plain tick's outputs + harvest."""
        import time as _time

        (_, key, t0, toks, lp_dev, snapshot) = rec
        if lp_dev is not None:
            # One fetch for everything (see _spec_finish).
            toks, *lp_np = jax.device_get((toks, *lp_dev))
            lp = tuple(np.asarray(x) for x in lp_np)
            toks = np.asarray(toks)
        else:
            lp = None
            toks = np.asarray(jax.device_get(toks))
        if self.speculative and (not self.pipeline_ticks or self._probe_timing):
            # See _spec_finish: pipelined intervals are not device cost,
            # but serial probe-tick intervals are.
            self._record_tick_time(key, (_time.perf_counter() - t0) * 1e3)
        self._harvest(toks, lp=lp, snapshot=snapshot)

    def _finish_tick(self, rec: tuple) -> None:
        (self._spec_finish if rec[0] == "spec" else self._plain_finish)(rec)

    def _serial_probe_due(self) -> bool:
        """Should this pipelined tick run serially to calibrate the
        speculation threshold? Only while the adaptive threshold is still
        unmeasured, within the warmup budget, and only for lookup drafting
        (model drafting speculates unconditionally, so the threshold is
        never consulted). Pod serving freezes the threshold at
        construction (``freeze_spec_threshold``), which disables probing —
        serial ticks on one replica would desync the pod's tick cadence
        assumptions and per-host timings must not steer pod decisions."""
        return (
            self.pipeline_ticks
            and self.speculative
            and self.spec_draft == "lookup"
            and self._spec_threshold_cfg is None
            and self._probe_ticks_left > 0
            and not (self._plain_step_ms and self._spec_round_ms)
        )

    @hot_path
    def step(self) -> None:
        """One scheduler tick: admit queued requests, advance one chunk of
        every in-progress chunked prefill, decode one chunk (speculatively
        when armed and predicted to win — see ``_use_spec_tick``).

        ``pipeline_ticks``: the tick dispatched here is NOT fetched here —
        it is fetched (and harvested) on the NEXT step, after that step has
        already dispatched its own tick. The host's dispatch+fetch round
        trips overlap with device compute; admission and harvest lag one
        tick; a finished request's slot decodes one dead chunk before being
        freed (masked out by the harvest snapshot). Token streams are
        identical to serial ticks — per-slot RNG derives from the request
        seed, never from tick alignment."""
        # Chaos seam: `delay`/`hang` stall the scheduler (TTFT/stall
        # drills); `error` surfaces through the driver as an engine death.
        self.tick_count += 1
        maybe_inject("engine.tick", step=self.tick_count)
        prev, self._pending_fetch = self._pending_fetch, None
        probe = self._serial_probe_due()
        if probe and prev is not None:
            # Drain the pipeline first so the probe's dispatch→fetch
            # interval times a quiet device, not the tail of tick N.
            self._finish_tick(prev)
            prev = None
        self._expire_deadlines()
        # Interference attribution (ISSUE 6): requests that were ALREADY
        # decode-ready before this tick's admissions and prefill chunks are
        # the victims whose next decode chunk every prefill below delays —
        # the "long prefill monopolizes the tick, co-running streams' TPOT
        # spikes" effect the chunked-prefill refactor will be judged on.
        decode_ready = [
            r for r in self._slots
            if r is not None and not r.prefilling
            and not r.finished and not r.cancelled
        ]
        self._tick_prefills = []
        # Token budget (ISSUE 8): this tick's decode work is fixed
        # (decode_ready slots x decode_chunk steps); whatever the budget
        # leaves over is the prefill allowance admission and the chunk
        # advances below draw from. None = unbudgeted (historical).
        self._tick_prefill_spent = 0
        self._tick_prefill_left = (
            max(0, self.token_budget - len(decode_ready) * self.decode_chunk)
            if self.token_budget else None
        )
        # In-flight prefill chunks draw the allowance BEFORE admission
        # (Sarathi's order: decode > ongoing prefill > new work) — letting
        # admission spend first would burn each tick's at-least-one-chunk
        # free pass on fresh arrivals and park an older mid-prefill request
        # indefinitely behind a stream of new admissions. Newly admitted
        # chunked requests still advance their first chunk this tick
        # (second pass below) when allowance remains.
        inflight = [
            r for r in self._slots if r is not None and r.prefilling
        ]
        self._advance_prefill_chunks(inflight)
        self._admit()
        seen = {id(r) for r in inflight}
        self._advance_prefill_chunks([
            r for r in self._slots
            if r is not None and r.prefilling and id(r) not in seen
        ])
        prefill_s = sum(dt for _, _, dt in self._tick_prefills)
        if self._tick_prefills and prefill_s > 0 and decode_ready:
            # One histogram observation per victim per tick (the aggregate
            # answer "how much decode delay is prefill causing"), plus a
            # per-victim annotation naming the biggest culprit — consumed
            # by the next harvest's decode span.
            culprit_id, culprit_tokens, _ = max(
                self._tick_prefills, key=lambda e: e[2]
            )
            self.interference_max_s = max(self.interference_max_s, prefill_s)
            for victim in decode_ready:
                if victim.finished or victim.cancelled:
                    continue
                self.metrics.tpot_interference.observe(prefill_s)
                cls_hist = self.metrics.interference_by_class.get(
                    victim.slo_class
                )
                if cls_hist is not None:
                    cls_hist.observe(prefill_s)
                self.interference_max_by_class[victim.slo_class] = max(
                    self.interference_max_by_class.get(victim.slo_class, 0.0),
                    prefill_s,
                )
                victim.interference_s += prefill_s
                victim.interference_pending.append(
                    (culprit_id, culprit_tokens, prefill_s)
                )
        if self.tracer.armed:
            self.tracer.instant(
                "engine.tick",
                tick=self.tick_count,
                slots_busy=sum(r is not None for r in self._slots),
                prefilling=sum(
                    1 for r in self._slots
                    if r is not None and r.prefilling
                ),
                queue_depth=len(self._queue),
                prefill_s=round(prefill_s, 6),
            )
        self._topup_pages()  # optimistic paged admission; may preempt
        occupied = [r is not None and not r.prefilling for r in self._slots]
        rec = None
        if any(occupied):  # host-side check: no device sync on idle ticks
            alive = jnp.asarray(occupied, bool)
            active = [
                r for r in self._slots if r is not None and not r.prefilling
            ]
            sampled = any(r.temperature > 0.0 for r in active)
            if probe:
                # Warmup forces the UNMEASURED path so both costs get two
                # timed samples (the first call per program is excluded as
                # compile) no matter what the workload's acceptance would
                # choose — spec and plain ticks are interchangeable for
                # correctness (greedy bit-exact, sampled exact in
                # distribution), so forcing the choice only affects speed.
                use_spec = self._spec_round_ms is None
            else:
                use_spec = self._use_spec_tick(active)
            if use_spec:
                rec = self._spec_dispatch(alive, sampled)
            else:
                rec = self._plain_dispatch(active, alive, sampled)
        if probe and rec is not None:
            self._probe_ticks_left -= 1
            self._probe_timing = True
            try:
                self._finish_tick(rec)
            finally:
                self._probe_timing = False
        elif self.pipeline_ticks:
            self._pending_fetch = rec
            if prev is not None:
                self._finish_tick(prev)
        elif rec is not None:
            self._finish_tick(rec)
        if self.host_tier is not None:
            # Host-tier spill batch (ISSUE 13): the tick's evicted pages
            # move to host RAM in one batched fetch, AFTER dispatch/harvest
            # so the transfer overlaps nothing on the dispatch stream.
            self._process_spills()
        # Flight recorder (ISSUE 10): one host-dict row per tick into the
        # bounded ring — the black box an incident bundle dumps. Host state
        # only (no device sync); counters are the cumulative values the
        # metrics bundle already holds, so a ring reader can difference
        # adjacent rows to see exactly which ticks expired/429'd whom.
        m = self.metrics
        by_class = collections.Counter(r.slo_class for r in self._queue)
        self.flight.ring(TICK_RING).record(
            tick=self.tick_count,
            queue_depth=len(self._queue),
            # One O(queue) pass, not one per class — this runs every tick.
            queue_by_class={cls: by_class.get(cls, 0)
                            for cls in SLO_CLASSES},
            slots_busy=sum(r is not None for r in self._slots),
            prefilling=sum(
                1 for r in self._slots if r is not None and r.prefilling
            ),
            prefill_tokens=self._tick_prefill_spent,
            budget_left=self._tick_prefill_left,
            preemptions=int(getattr(self, "preemptions", 0)),
            # Registry counters are plain host floats by the registry's own
            # zero-device-sync contract; int() here is cosmetic row shape.
            deadline_expired=int(m.deadline_expired.value),  # ditl: allow(blocking-transfer) -- host-side registry counter, no device sync
            queue_full=int(m.queue_full.value),  # ditl: allow(blocking-transfer) -- host-side registry counter, no device sync
            completed=int(m.completed.value),  # ditl: allow(blocking-transfer) -- host-side registry counter, no device sync
        )
        if (self.anomaly is not None
                and self.tick_count % self.anomaly.check_every == 0):
            # Detector cadence: every check_every ticks, over the stats
            # snapshot + metrics bundle (telemetry/anomaly.py). The monitor
            # never raises into the driver thread.
            self.anomaly.observe_serving(self.stats(), m)

    @property
    def pending(self) -> int:
        return len(self._queue) + sum(r is not None for r in self._slots)

    def scheduler_fingerprint(self) -> int:
        """31-bit digest of the host-side scheduler state that must agree
        across pod processes after every tick: slot occupancy, queue depth,
        and — in paged mode — the page tables plus allocator occupancy.
        Pod replicas run the scheduler deterministically on broadcast
        inputs, so tables SHOULD be identical; a single divergent
        allocation or eviction would desync the SPMD tick programs
        silently (each process would gather different pages), which on TPU
        manifests as wrong tokens or a collective hang. The pod tick's
        status collective exchanges this digest so divergence stops the
        pod loudly instead (infer/podserve.py)."""
        import hashlib

        h = hashlib.sha256()
        h.update(len(self._queue).to_bytes(4, "big"))
        # Queue ORDER is scheduler state now (class-priority admission): a
        # replica whose queue sorted differently would admit a different
        # request next tick.
        h.update(bytes(SLO_CLASSES[r.slo_class] for r in self._queue))
        h.update(bytes(
            0 if r is None else (2 if r.prefilling else 1)
            for r in self._slots
        ))
        if self.cache_mode == "paged":
            h.update(self._table.tobytes())
            h.update(self.allocator.n_free.to_bytes(4, "big"))
            h.update(self.allocator.n_evictable.to_bytes(4, "big"))
            if self.host_tier is not None:
                # Host-tier occupancy steers swap-in-vs-prefill admission
                # decisions, so a replica whose tier drifted must
                # fingerprint differently (spills/swaps are deterministic
                # functions of replicated scheduler state per tick).
                h.update(self.host_tier.n_entries.to_bytes(4, "big"))
            # The anti-thrash mode changes admission decisions, so a
            # replica whose switch drifted must fingerprint differently.
            h.update(bytes([self._degraded]))
        return int.from_bytes(h.digest()[:4], "big") >> 1

    def _prefix_cache_stats(self) -> dict:
        """Measured prefix-reuse accounting (ISSUE 8): lifetime reused vs
        prefilled prompt tokens, their ratio, and LRU evictions — the
        numbers /stats, /health, and the gateway's per-replica aggregation
        all read. Counter-backed, so a shared metrics bundle aggregates
        across engines exactly like the latency histograms do."""
        m = self.metrics
        hit = int(m.prefix_cache_hit_tokens.value)
        miss = int(m.prefix_cache_miss_tokens.value)
        out = {
            "hit_tokens": hit,
            "miss_tokens": miss,
            "evictions": (
                self.allocator.evictions if self.cache_mode == "paged"
                else 0
            ),
        }
        if hit + miss:
            out["hit_ratio"] = round(hit / (hit + miss), 4)
        return out

    def stats(self) -> dict:
        """Operational snapshot (host state only — no device sync): slot
        occupancy, queue depth, and page-pool accounting in paged mode.
        Served at the HTTP layer as /v1/stats."""
        out = {
            "engine": "continuous",
            "cache_mode": self.cache_mode,
            "n_slots": self.n_slots,
            "slots_busy": sum(r is not None for r in self._slots),
            "slots_prefilling": sum(
                r is not None and r.prefilling for r in self._slots
            ),
            "queue_depth": len(self._queue),
            "max_queue": self.max_queue,
            "decode_chunk": self.decode_chunk,
            "max_context": self.smax,
            "token_budget": self.token_budget,
            "max_tick_prefill_tokens": self.max_tick_prefill_tokens,
            "interference_max_s": round(self.interference_max_s, 6),
            "interference_max_by_class": {
                cls: round(v, 6)
                for cls, v in sorted(self.interference_max_by_class.items())
            },
            "queue_by_class": {
                cls: sum(1 for r in self._queue if r.slo_class == cls)
                for cls in SLO_CLASSES
            },
            "prefix_cache": self._prefix_cache_stats(),
        }
        if self.prefill_seconds_total > 0:
            # Measured prefill throughput (ISSUE 13): the re-prefill side
            # of the gateway's KV-handoff transfer-cost model, exposed on
            # /health via the server's load snapshot. Absent until a
            # prefill has run (absent != 0).
            out["prefill_tok_per_s"] = round(
                self.prefill_tokens_total / self.prefill_seconds_total, 1
            )
        if self.cache_mode == "paged":
            out.update({
                "page_size": self.page_size,
                "pages_total": self.n_pages - 1,  # page 0 is the sentinel
                "pages_free": self.allocator.n_free,
                "pages_cached_evictable": self.allocator.n_evictable,
                "admission": self.admission,
                "preemptions": self.preemptions,
                "kv_bytes_per_token": round(
                    self.page_bytes / self.page_size, 2
                ),
            })
            if self.host_tier is not None:
                out["host_tier"] = self.host_tier.stats()
            if self.kv_import_seconds > 0:
                out["kv_transfer"] = {
                    "put_mbps": round(
                        self.kv_import_bytes
                        / self.kv_import_seconds / 1e6, 2
                    ),
                    "imported_bytes": self.kv_import_bytes,
                }
            if self.admission == "optimistic":
                out["admission_degraded"] = self._degraded
                out["admission_degrades"] = self.admission_degrades
                out["resume_prefill_tokens"] = self.resume_prefill_tokens
        if self.multi_lora:
            out["adapters"] = self.n_adapters
        if self.guided:
            out["guided"] = {
                "fsm_capacity": self.fsm_capacity,
                "fsm_rows_used": self._fsm_used,
                "grammars_registered": len(self._grammars),
            }
        if self.speculative:
            out["speculative"] = {
                "drafter": self.spec_draft,
                "k": self.spec_k,
                "rounds_per_tick": self.spec_rounds,
                "threshold": self.spec_threshold,
                "threshold_source": (
                    "configured" if self._spec_threshold_cfg is not None
                    else "measured"
                    if (self._plain_step_ms and self._spec_round_ms)
                    else "prior"
                ),
                "plain_step_ms": self._plain_step_ms,
                "spec_round_ms": self._spec_round_ms,
                "acceptance_ema": self.spec_acceptance_ema,
                "spec_ticks": self.spec_ticks,
                "ticks": self._tick_no,
            }
        return out

    def run(self) -> dict[int, list[int]]:
        """Drive until all submitted requests complete; pops and returns the
        finished requests' token lists by id (no unbounded history kept)."""
        while self.pending:
            self.step()
        out = {rid: req.tokens for rid, req in sorted(self._completed.items())}
        self._completed.clear()
        return out

    def generate(self, prompts: list[str], **submit_kw) -> list[str]:
        """Text in, text out (convenience parity with engine.Generator)."""
        ids = [
            self.submit([self.tokenizer.bos_id] + self.tokenizer.encode(p), **submit_kw)
            for p in prompts
        ]
        results = self.run()
        return [self.tokenizer.decode(results[i]) for i in ids]

    def cancel(self, req_id: int) -> bool:
        """Abandon a queued or in-flight request: its slot frees immediately
        (the next admission's prefill overwrites the stale cache rows, the
        same invariant as normal slot reuse) instead of decoding dead work to
        its full token budget. Streamed requests receive their terminal
        ``None``. Returns True if the request was found."""
        for req in self._queue:
            if req.req_id == req_id:
                self._queue.remove(req)
                if req.finished:
                    # Preempted request that COMPLETED via its pending
                    # tick's lagged harvest while queued: the stream
                    # already got its terminal None and the result sits in
                    # _completed — cancelling now just discards it (no
                    # second sentinel).
                    self._completed.pop(req_id, None)
                    return True
                req.cancelled = True
                self._note_usage_terminal(req, "cancel")
                self._close_spans(req, cancelled=True)
                if req.stream is not None:
                    req.stream.put(None)
                return True
        for slot, req in enumerate(self._slots):
            if req is not None and req.req_id == req_id:
                self._slots[slot] = None
                req.cancelled = True
                self._note_usage_terminal(req, "cancel")
                self._close_spans(req, cancelled=True)
                if self.cache_mode == "paged":
                    self._free_slot_pages(slot)
                if req.stream is not None:
                    req.stream.put(None)
                return True
        return self._completed.pop(req_id, None) is not None

    def take_result(self, req_id: int) -> list[int] | None:
        """Pop a finished request's tokens, or None if still in flight."""
        req = self._completed.pop(req_id, None)
        return None if req is None else req.tokens

    def take_finished(self) -> list[Request]:
        """Pop and return all finished requests."""
        out = list(self._completed.values())
        self._completed.clear()
        return out


class ThreadedEngine:
    """Thread-safe front for ``ContinuousEngine``: HTTP handler threads
    submit and block on their own request while one background driver thread
    ticks the engine — concurrent requests share decode ticks (true
    continuous batching across connections), unlike the lock-step server
    path where each request runs the device exclusively."""

    # The server consults these before passing scheduling extensions
    # through: this front supports both; the pod driver (podserve) sets its
    # own to False and rejects explicit values (reject-don't-drop).
    supports_deadlines = True
    supports_slo_classes = True

    def __init__(self, engine: ContinuousEngine):
        import threading

        self._engine = engine
        self._cond = threading.Condition()
        self._results: dict[int, Request] = {}  # guarded-by: _cond
        self._cancels: set[int] = set()  # guarded-by: _cond
        self._calls: list = []  # guarded-by: _cond
        self._error: BaseException | None = None  # guarded-by: _cond
        self._stop = False  # guarded-by: _cond
        self._thread = threading.Thread(target=self._drive, daemon=True)
        self._thread.start()

    @property
    def tokenizer(self) -> Tokenizer:
        return self._engine.tokenizer

    def stats(self) -> dict:
        return self._engine.stats()

    @property
    def metrics(self) -> ServingMetrics:
        """The engine's telemetry bundle (rendered by /metrics)."""
        return self._engine.metrics

    @property
    def tracer(self) -> Tracer:
        """The engine's span tracer (telemetry/tracing.py) — the HTTP
        server derives its own tracer from this so arming the engine arms
        the whole replica with one knob."""
        return self._engine.tracer

    @property
    def flight(self) -> FlightRecorder:
        """The engine's flight recorder (telemetry/flight.py) — the tick
        ring an incident bundle dumps."""
        return self._engine.flight

    @property
    def usage(self):
        """The engine's per-tenant usage meter (telemetry/usage.UsageMeter,
        ISSUE 15) — the /usage endpoint's source; None when metering is
        unarmed (absent != zero usage)."""
        return self._engine.usage

    @property
    def queue_full(self) -> bool:
        """Best-effort admission-queue check (for pre-stream 429s: once SSE
        headers are out, a QueueFullError can no longer become an HTTP
        status)."""
        eng = self._engine
        return eng.max_queue is not None and len(eng._queue) >= eng.max_queue

    def _drive(self) -> None:
        while True:
            with self._cond:
                while (not self._stop and self._engine.pending == 0
                       and not self._calls):
                    self._cond.wait(timeout=0.05)
                if self._stop:
                    self._cond.notify_all()
                    return
            # Device work runs OUTSIDE the lock: submissions (queue appends,
            # thread-safe deque) land while a chunk decodes and are admitted
            # on the next tick; only result handoff needs the lock. Cancels
            # are applied here because only this thread touches engine state.
            with self._cond:
                cancels, self._cancels = self._cancels, set()
                calls, self._calls = self._calls, []
            try:
                # Driver-thread calls (ISSUE 13: KV handoff export/import)
                # run BEFORE the tick, so a shipped prefill is published
                # before the relayed request's admission looks for it. A
                # call's own exception is delivered to its waiter, never
                # allowed to kill the driver — a torn KV blob must cost one
                # 400, not the replica.
                for fn, box in calls:
                    try:
                        box["result"] = fn()
                    except BaseException as e:
                        box["error"] = e
                if calls:
                    with self._cond:
                        for _, box in calls:
                            box["done"] = True
                        self._cond.notify_all()
                for rid in cancels:
                    self._engine.cancel(rid)
                if self._engine.pending:
                    self._engine.step()
            except BaseException as e:  # device/compile errors must not
                # wedge the server: fail every waiter loudly and stop.
                logger.exception("continuous engine driver died")
                with self._cond:
                    self._error = e
                    self._stop = True
                    self._cond.notify_all()
                return
            with self._cond:
                for req in self._engine.take_finished():
                    # Streamed requests deliver through their queue (the final
                    # None already went out in _harvest); recording them here
                    # would leak entries nobody pops.
                    if req.stream is None:
                        self._results[req.req_id] = req
                self._cond.notify_all()

    def call(self, fn):
        """Run ``fn()`` on the engine driver thread between ticks and
        return its result (its exception re-raises here). Engine state —
        page tables, pools, the allocator, the host tier — is
        single-threaded by design; the KV handoff endpoints (export_kv /
        import_kv) go through this so HTTP handler threads never touch
        device state mid-tick."""
        box: dict = {}
        with self._cond:
            if self._stop:
                raise RuntimeError(
                    "continuous engine stopped"
                ) from self._error
            self._calls.append((fn, box))
            self._cond.notify_all()
            while "done" not in box:
                if self._stop:
                    raise RuntimeError(
                        "continuous engine stopped mid-call"
                    ) from self._error
                self._cond.wait()
        if "error" in box:
            raise box["error"]
        return box.get("result")

    @property
    def logprobs_k(self) -> int:
        """Max top-N logprob alternatives the engine can serve (0 = off)."""
        return self._engine.logprobs_k

    @property
    def guided(self) -> bool:
        """True when the engine can serve grammar-constrained requests."""
        return self._engine.guided

    @property
    def multi_lora(self) -> bool:
        """True when the engine serves a multi-adapter LoRA stack."""
        return self._engine.multi_lora

    @property
    def n_adapters(self) -> int:
        """Rows in the stacked adapter pool (0 = no stack; row 0 is the
        base model) — the capacity the adapter registry manages."""
        return self._engine.n_adapters

    @property
    def adapter_registry(self):
        """The attached adapter lifecycle registry (infer/adapters.py,
        ISSUE 16); None until AdapterRegistry.bind_engine."""
        return self._engine.adapter_registry

    def _wait_one_locked(self, rid: int) -> Request:
        while rid not in self._results:
            if self._stop:
                raise RuntimeError(
                    "continuous engine stopped mid-request"
                ) from self._error
            self._cond.wait()
        return self._results.pop(rid)

    def generate_one(
        self,
        prompt_tokens: list[int],
        *,
        max_new_tokens: int | None = None,
        temperature: float | None = None,
        top_p: float | None = None,
        seed: int | None = None,
        adapter_id: int | None = None,
        grammar: Any = None,
        deadline_s: float | None = None,
        slo_class: str | None = None,
        trace: Any = None,
        tenant: str | None = None,
    ) -> list[int]:
        """Submit one request and block until it completes. Raises if the
        driver has stopped (shutdown or device error) — callers turn that
        into an HTTP 500 instead of hanging the connection — and
        ``DeadlineExceededError`` when ``deadline_s`` expired the request
        before completion (HTTP 504)."""
        with self._cond:
            if self._stop:
                raise RuntimeError("continuous engine is stopped") from self._error
            rid = self._engine.submit(
                prompt_tokens,
                max_new_tokens=max_new_tokens,
                temperature=temperature,
                top_p=top_p,
                seed=seed,
                adapter_id=adapter_id,
                grammar=grammar,
                deadline_s=deadline_s,
                slo_class=slo_class,
                trace=trace,
                tenant=tenant,
            )
            self._cond.notify_all()
            req = self._wait_one_locked(rid)
            if req.expired:
                raise DeadlineExceededError(
                    f"request exceeded its {deadline_s}s deadline "
                    f"({len(req.tokens)} tokens generated before eviction)"
                )
            return req.tokens

    def generate_one_with_logprobs(
        self,
        prompt_tokens: list[int],
        n_top: int,
        *,
        max_new_tokens: int | None = None,
        temperature: float | None = None,
        top_p: float | None = None,
        seed: int | None = None,
        grammar: Any = None,
        deadline_s: float | None = None,
        slo_class: str | None = None,
        trace: Any = None,
        tenant: str | None = None,
    ) -> tuple[list[int], dict]:
        """``generate_one`` + per-token logprob stats (same dict layout as
        engine.Generator.generate_tokens_with_logprobs: ``token_logprobs``,
        ``top_ids``, ``top_logprobs``). The request rides ordinary decode
        ticks — logprobs no longer force the lock-step path that stalled
        the continuous engine's throughput."""
        with self._cond:
            if self._stop:
                raise RuntimeError("continuous engine is stopped") from self._error
            rid = self._engine.submit(
                prompt_tokens,
                max_new_tokens=max_new_tokens,
                temperature=temperature,
                top_p=top_p,
                seed=seed,
                logprobs=n_top,
                grammar=grammar,
                deadline_s=deadline_s,
                slo_class=slo_class,
                trace=trace,
                tenant=tenant,
            )
            self._cond.notify_all()
            req = self._wait_one_locked(rid)
            if req.expired:
                raise DeadlineExceededError(
                    f"request exceeded its {deadline_s}s deadline "
                    f"({len(req.tokens)} tokens generated before eviction)"
                )
            return req.tokens, {
                "token_logprobs": req.lp_token,
                "top_ids": [row[:n_top] for row in req.lp_top_ids],
                "top_logprobs": [row[:n_top] for row in req.lp_top],
            }

    def generate_many(
        self,
        prompt_tokens: list[int],
        n: int,
        *,
        max_new_tokens: int | None = None,
        temperature: float | None = None,
        top_p: float | None = None,
        seed: int | None = None,
        adapter_id: int | None = None,
        grammar: Any = None,
        logprobs: int | None = None,
        slo_class: str | None = None,
        trace: Any = None,
        tenant: str | None = None,
    ) -> list[Request]:
        """Submit ``n`` copies of one prompt (distinct derived seeds) and
        block until all complete; returns the finished Request objects in
        submission order. The copies share decode ticks with each other and
        with everything else in flight — OpenAI ``n``/``best_of`` serving
        costs one batched decode, not n sequential generations."""
        with self._cond:
            if self._stop:
                raise RuntimeError("continuous engine is stopped") from self._error
            if seed is None:
                # Fresh randomness per CALL when unseeded (OpenAI sampling
                # semantics) — a constant base would replay the same n-set
                # for every identical prompt.
                import random as _random

                seed = _random.getrandbits(31)
            rids: list[int] = []
            try:
                for i in range(n):
                    rids.append(self._engine.submit(
                        prompt_tokens,
                        max_new_tokens=max_new_tokens,
                        temperature=temperature,
                        top_p=top_p,
                        seed=derive_copy_seed(seed, i),
                        adapter_id=adapter_id,
                        grammar=grammar,
                        logprobs=logprobs,
                        slo_class=slo_class,
                        trace=trace,
                        tenant=tenant,
                    ))
            except BaseException:
                # A mid-loop failure (e.g. QueueFullError on copy k) must
                # not orphan copies 0..k-1: cancel them so their decode
                # work stops and no unconsumed Request parks in _results.
                for rid in rids:
                    self._cancels.add(rid)
                    self._results.pop(rid, None)
                self._cond.notify_all()
                raise
            self._cond.notify_all()
            return [self._wait_one_locked(rid) for rid in rids]

    def stream_one(
        self,
        prompt_tokens: list[int],
        *,
        max_new_tokens: int | None = None,
        temperature: float | None = None,
        top_p: float | None = None,
        seed: int | None = None,
        adapter_id: int | None = None,
        grammar: Any = None,
        deadline_s: float | None = None,
        slo_class: str | None = None,
        trace: Any = None,
        tenant: str | None = None,
    ):
        """Submit one request and return an iterator of per-chunk token-id
        lists as they are decoded (SSE streaming). The submit happens
        EAGERLY — ``QueueFullError`` raises here, while the HTTP layer can
        still answer 429; once the SSE headers are out there is no status
        left to send (ADVICE r2). A ``deadline_s`` expiry simply ends the
        stream (the terminal None — headers are long gone). Raises if the
        driver stops mid-stream."""
        import queue as _queue

        stream: _queue.Queue = _queue.Queue()
        with self._cond:
            if self._stop:
                raise RuntimeError("continuous engine is stopped") from self._error
            rid = self._engine.submit(
                prompt_tokens,
                max_new_tokens=max_new_tokens,
                temperature=temperature,
                top_p=top_p,
                seed=seed,
                stream=stream,
                adapter_id=adapter_id,
                grammar=grammar,
                deadline_s=deadline_s,
                slo_class=slo_class,
                trace=trace,
                tenant=tenant,
            )
            self._cond.notify_all()

        def chunks():
            try:
                while True:
                    try:
                        chunk = stream.get(timeout=1.0)
                    except _queue.Empty:
                        # Read _stop/_error as a consistent pair under the
                        # condition (lock-discipline): once per idle second,
                        # so the lock costs nothing on a flowing stream.
                        with self._cond:
                            stopped, err = self._stop, self._error
                        if stopped:
                            raise RuntimeError(
                                "continuous engine stopped mid-stream"
                            ) from err
                        continue
                    if chunk is None:
                        return
                    yield chunk
            finally:
                # Consumer stopped early (stop sequence hit, client
                # disconnect): cancel so the engine doesn't decode the
                # abandoned budget.
                self.cancel(rid)

        return chunks()

    def stream_one_with_logprobs(
        self,
        prompt_tokens: list[int],
        n_top: int,
        *,
        max_new_tokens: int | None = None,
        temperature: float | None = None,
        top_p: float | None = None,
        seed: int | None = None,
        grammar: Any = None,
        deadline_s: float | None = None,
        slo_class: str | None = None,
        trace: Any = None,
        tenant: str | None = None,
    ):
        """``stream_one`` + per-chunk logprob stats: yields
        ``(token_ids, lp_dict)`` pairs where ``lp_dict`` carries the chunk's
        ``token_logprobs``/``top_ids``/``top_logprobs`` (OpenAI semantics,
        sliced to ``n_top``)."""
        import queue as _queue

        stream: _queue.Queue = _queue.Queue()
        with self._cond:
            if self._stop:
                raise RuntimeError("continuous engine is stopped") from self._error
            rid = self._engine.submit(
                prompt_tokens,
                max_new_tokens=max_new_tokens,
                temperature=temperature,
                top_p=top_p,
                seed=seed,
                stream=stream,
                logprobs=n_top,
                grammar=grammar,
                deadline_s=deadline_s,
                slo_class=slo_class,
                trace=trace,
                tenant=tenant,
            )
            self._cond.notify_all()

        def chunks():
            try:
                while True:
                    try:
                        item = stream.get(timeout=1.0)
                    except _queue.Empty:
                        # Same consistent-pair read as stream_one.
                        with self._cond:
                            stopped, err = self._stop, self._error
                        if stopped:
                            raise RuntimeError(
                                "continuous engine stopped mid-stream"
                            ) from err
                        continue
                    if item is None:
                        return
                    yield item
            finally:
                self.cancel(rid)

        return chunks()

    def cancel(self, req_id: int) -> None:
        """Request cancellation; applied by the driver thread on its next
        tick (only it touches engine state)."""
        with self._cond:
            self._cancels.add(req_id)
            self._cond.notify_all()

    def close(self) -> None:
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        self._thread.join(timeout=5)
