"""Paged-KV wire format for prefill->decode handoff (ISSUE 13).

DistServe/Splitwise-style disaggregation needs a finished prefill's KV to
MOVE: a ``prefill_heavy`` replica serializes the prompt's full pages with
this module, the gateway ships the blob intra-host, and the decode
replica's engine imports it (``ContinuousEngine.import_kv``) — publishing
the pages into its own content cache so the relayed request's admission
prefix-matches them instead of re-prefilling.

Format (all integers little-endian):

``DKV1`` magic | u16 version | u16 flags (0) | u32 meta length |
meta JSON (utf-8) | u32 meta crc32 | then per page x per pool part:
u32 part length | part bytes | u32 part crc32.

The meta block pins everything an importer must refuse to mis-apply:
page size, layer/head/dim geometry, pool dtype + quantization, adapter
root, part order, and the exact token blocks the pages hold (the content
keys republish under — so the no-hash-collision chain invariant survives
the process boundary: the importer publishes ``(parent_pid,
exact_tokens)`` keys from these blocks, it never trusts a digest).

Integrity is non-optional: a short read, a truncated tail, a length that
runs past the buffer, or any crc mismatch raises
:class:`KVTransferError` — a torn blob is rejected whole, never partially
installed. The import side maps that to an HTTP 400 and the gateway falls
back to plain relay (the decode replica re-prefills; zero client-visible
failures — the ``kv.handoff`` chaos drill pins exactly this path).

numpy + stdlib only: importable by the gateway-side tests without jax.
"""

from __future__ import annotations

import json
import struct
import zlib

import numpy as np

__all__ = ["KVTransferError", "deserialize_pages", "serialize_pages"]

MAGIC = b"DKV1"
VERSION = 1


class KVTransferError(ValueError):
    """A KV blob failed validation (torn/short read, crc mismatch, version
    or geometry mismatch) — reject-don't-install."""


def serialize_pages(meta: dict, pages: list[dict[str, np.ndarray]]) -> bytes:
    """Serialize ``pages`` (one dict of per-pool arrays per page, every
    page holding the same part names) under ``meta`` (JSON-serializable;
    ``blocks`` must list each page's exact tokens). Part order is pinned in
    the meta so both sides agree without trusting dict order on the wire."""
    if not pages:
        raise ValueError("nothing to serialize: pages is empty")
    part_names = sorted(pages[0])
    meta = dict(meta)
    meta["version"] = VERSION
    meta["n_pages"] = len(pages)
    meta["parts"] = part_names
    meta["part_dtypes"] = {
        # dtype NAME, not .str: extension dtypes (ml_dtypes bfloat16) have
        # an opaque '<V2' .str that np.dtype() rebuilds as raw void —
        # silent KV corruption; the name round-trips via _dtype below.
        name: np.ascontiguousarray(pages[0][name]).dtype.name
        for name in part_names
    }
    meta["part_shapes"] = {
        name: list(np.asarray(pages[0][name]).shape) for name in part_names
    }
    mbytes = json.dumps(meta, sort_keys=True).encode("utf-8")
    out = bytearray()
    out += MAGIC
    out += struct.pack("<HH", VERSION, 0)
    out += struct.pack("<I", len(mbytes))
    out += mbytes
    out += struct.pack("<I", zlib.crc32(mbytes))
    for page in pages:
        if sorted(page) != part_names:
            raise ValueError(
                f"page part names differ: {sorted(page)} vs {part_names}"
            )
        for name in part_names:
            part = np.ascontiguousarray(page[name]).tobytes()
            out += struct.pack("<I", len(part))
            out += part
            out += struct.pack("<I", zlib.crc32(part))
    return bytes(out)


def _dtype(name) -> np.dtype:
    """dtype from its wire NAME, tolerating jax's ml_dtypes extensions
    (bfloat16 etc. register with numpy only once ml_dtypes is imported).
    Any failure — including attacker-chosen garbage reaching np.dtype —
    is a KVTransferError, never a stray TypeError out of the endpoint."""
    if not isinstance(name, str):
        raise KVTransferError(f"part dtype is not a string: {name!r}")
    try:
        return np.dtype(name)
    except TypeError:
        try:
            import ml_dtypes

            return np.dtype(getattr(ml_dtypes, name))
        except (ImportError, AttributeError, TypeError) as e:
            raise KVTransferError(
                f"unknown part dtype {name!r} in KV blob"
            ) from e


def _take(blob: bytes, off: int, n: int, what: str) -> tuple[bytes, int]:
    if off + n > len(blob):
        raise KVTransferError(
            f"torn KV blob: {what} runs past the buffer "
            f"({off + n} > {len(blob)} bytes)"
        )
    return blob[off: off + n], off + n


def deserialize_pages(blob: bytes) -> tuple[dict, list[dict[str, np.ndarray]]]:
    """Parse and VERIFY a :func:`serialize_pages` blob; returns
    ``(meta, pages)``. Any integrity failure raises
    :class:`KVTransferError` before a single array is returned."""
    head, off = _take(blob, 0, 12, "header")
    if head[:4] != MAGIC:
        raise KVTransferError(
            f"bad magic {head[:4]!r} (not a DKV1 KV blob)"
        )
    version, flags = struct.unpack("<HH", head[4:8])
    if version != VERSION:
        raise KVTransferError(
            f"unsupported KV blob version {version} (this side speaks "
            f"{VERSION})"
        )
    if flags != 0:
        raise KVTransferError(f"unsupported KV blob flags {flags:#x}")
    (mlen,) = struct.unpack("<I", head[8:12])
    mbytes, off = _take(blob, off, mlen, "meta")
    crc_raw, off = _take(blob, off, 4, "meta crc")
    if zlib.crc32(mbytes) != struct.unpack("<I", crc_raw)[0]:
        raise KVTransferError("meta crc32 mismatch (corrupt KV blob)")
    try:
        meta = json.loads(mbytes)
    except json.JSONDecodeError as e:
        raise KVTransferError(f"meta is not valid JSON: {e}") from e
    part_names = meta.get("parts")
    n_pages = meta.get("n_pages")
    blocks = meta.get("blocks")
    if (not isinstance(part_names, list) or not part_names
            or not isinstance(n_pages, int) or n_pages < 1
            or not isinstance(blocks, list) or len(blocks) != n_pages):
        raise KVTransferError("meta missing parts/n_pages/blocks")
    # The dtype/shape tables are as much attack/skew surface as the bytes:
    # a crc-valid blob from a patched or fuzzing peer must still fail as a
    # KVTransferError (the endpoint's 400 contract), never a KeyError.
    dtypes = meta.get("part_dtypes")
    shapes = meta.get("part_shapes")
    if not isinstance(dtypes, dict) or not isinstance(shapes, dict):
        raise KVTransferError("meta missing part dtype/shape tables")
    part_meta: dict[str, tuple[np.dtype, tuple[int, ...]]] = {}
    for name in part_names:
        if name not in dtypes or name not in shapes:
            raise KVTransferError(f"meta missing dtype/shape for {name!r}")
        shape = shapes[name]
        if (not isinstance(shape, list) or not shape
                or not all(isinstance(x, int) and x > 0 for x in shape)):
            raise KVTransferError(
                f"bad shape for part {name!r}: {shape!r}"
            )
        part_meta[name] = (_dtype(dtypes[name]), tuple(shape))
    pages: list[dict[str, np.ndarray]] = []
    for p in range(n_pages):
        page: dict[str, np.ndarray] = {}
        for name in part_names:
            lraw, off = _take(blob, off, 4, f"page {p} part {name} length")
            (plen,) = struct.unpack("<I", lraw)
            part, off = _take(blob, off, plen, f"page {p} part {name}")
            craw, off = _take(blob, off, 4, f"page {p} part {name} crc")
            if zlib.crc32(part) != struct.unpack("<I", craw)[0]:
                raise KVTransferError(
                    f"crc32 mismatch on page {p} part {name} "
                    "(corrupt KV blob)"
                )
            dt, shape = part_meta[name]
            want = int(np.prod(shape)) * dt.itemsize
            if plen != want:
                raise KVTransferError(
                    f"page {p} part {name}: {plen} bytes for shape "
                    f"{shape} dtype {dt} (want {want})"
                )
            page[name] = np.frombuffer(part, dtype=dt).reshape(shape)
        pages.append(page)
    if off != len(blob):
        raise KVTransferError(
            f"trailing garbage: {len(blob) - off} bytes past the last page"
        )
    return meta, pages
