"""Device-mesh construction (L2).

The reference's only notion of topology is ``(rank, world_size)`` handed to a
sampler (ref ``src/distributed_inference.py:46-47,58``). The TPU-native
equivalent is an explicit N-d ``jax.sharding.Mesh`` whose axes name the
parallelism strategies; GSPMD lowers shardings over it to XLA collectives that
ride ICI within a slice and DCN across slices.

Axis order is chosen so that the *innermost* (fastest-varying, most
ICI-adjacent under default device order) axes carry the highest-bandwidth
traffic: tensor parallelism needs per-layer all-reduces every microsecond,
FSDP needs per-layer all-gathers, data parallelism needs one gradient
reduction per step, so the mesh is laid out data-outermost / tensor-innermost.
"""

from __future__ import annotations

import numpy as np

from ditl_tpu.config import MeshConfig
from ditl_tpu.utils.logging import get_logger

logger = get_logger(__name__)

# Outer -> inner. DCN-friendly axes first, ICI-hungry axes last. Pipeline
# stages exchange one activation per microbatch per stage (point-to-point,
# modest bandwidth) so "stage" sits on the DCN-friendly side.
AXIS_ORDER = ("data", "stage", "fsdp", "sequence", "expert", "tensor")


def build_mesh(config: MeshConfig | None = None, devices=None) -> "jax.sharding.Mesh":
    """Build the global mesh from a MeshConfig (resolving any -1 axis)."""
    import jax
    from jax.sharding import Mesh

    config = config or MeshConfig()
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    by_name = dict(zip(config.axis_names, config.resolve(n)))
    shape = tuple(by_name[a] for a in AXIS_ORDER)
    # Auto axis types: GSPMD infers intermediate shardings from the constraints
    # we annotate (with_sharding_constraint / in_shardings), which is the
    # propagation model this framework is designed around. Older jax has no
    # AxisType at all — every axis is implicitly Auto there.
    axis_type = getattr(jax.sharding, "AxisType", None)
    kwargs = (
        {"axis_types": (axis_type.Auto,) * len(AXIS_ORDER)}
        if axis_type is not None
        else {}
    )
    try:
        # Topology-aware layout when available (real TPU slices).
        mesh = jax.make_mesh(shape, AXIS_ORDER, devices=devices, **kwargs)
    except (AttributeError, TypeError, ValueError):
        device_grid = np.asarray(devices).reshape(shape)
        mesh = Mesh(device_grid, AXIS_ORDER, **kwargs)
    logger.info("mesh: %s", dict(zip(AXIS_ORDER, shape)))
    return mesh


def batch_axes() -> tuple[str, ...]:
    """Mesh axes over which the global batch is split. FSDP shards both params
    and batch (it is data parallelism with sharded state)."""
    return ("data", "fsdp")


def data_parallel_size(mesh) -> int:
    """Number of distinct data shards (product of batch axes)."""
    return int(np.prod([mesh.shape[a] for a in batch_axes()]))
