from ditl_tpu.runtime.distributed import (  # noqa: F401
    barrier,
    enable_compile_cache,
    init_runtime,
    is_coordinator,
    shutdown_runtime,
)
from ditl_tpu.runtime.mesh import build_mesh  # noqa: F401
from ditl_tpu.runtime.consistency import check_cross_host_consistency  # noqa: F401

# NOTE: runtime.elastic (PodController) is intentionally NOT imported here —
# it is jax-free by design and used by the launcher before any backend
# configuration; import it explicitly as `from ditl_tpu.runtime import elastic`.
