"""Distributed runtime bring-up / teardown (L2).

TPU-native replacement for the reference's NCCL process-group lifecycle:
``setup(rank, world_size)`` — env mutation + ``dist.init_process_group("nccl")``
+ ``dist.barrier()`` (ref ``src/distributed_inference.py:14-18``) — and
``cleanup()`` — ``dist.destroy_process_group()`` (ref ``:20-21``).

Design differences (TPU-first, SURVEY.md §5 'Distributed communication
backend'):

- Rendezvous is ``jax.distributed.initialize``: coordinator = process 0
  (the analog of ``MASTER_ADDR:MASTER_PORT``); on TPU pods all arguments are
  autodetected from the TPU metadata, so a single launcher serves every host
  (collapsing ``run_node0.sh``/``run_node1.sh``).
- Collectives are emitted by GSPMD/XLA over ICI/DCN; user code never issues
  them. The startup-health ``barrier()`` analog is
  ``multihost_utils.sync_global_devices``.
- CPU simulation: ``simulate_devices=N`` forces N virtual host devices via
  ``xla_force_host_platform_device_count``, which is how multi-node behavior
  is tested without a cluster (repairs the reference's deadlocking distributed
  test fixture, SURVEY.md §3.5).
"""

from __future__ import annotations

import os

from ditl_tpu.config import RuntimeConfig
from ditl_tpu.utils.logging import get_logger, setup_logging

logger = get_logger(__name__)

_initialized = False


def simulate_devices(n: int) -> None:
    """Request ``n`` virtual CPU devices. Must run before the first JAX
    *backend* touch (first ``jax.devices()``/array op). Env vars alone are not
    enough if something imported jax before us (jax snapshots env into its
    config at import time), so the config is also set directly."""
    flags = os.environ.get("XLA_FLAGS", "")
    flag = f"--xla_force_host_platform_device_count={n}"
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (flags + " " + flag).strip()
    os.environ["JAX_NUM_CPU_DEVICES"] = str(n)  # newer-JAX equivalent
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", n)


def init_runtime(config: RuntimeConfig | None = None) -> None:
    """Bring up the distributed runtime (idempotent).

    Order matters: simulation flags must be set before JAX initializes its
    backends, and ``jax.distributed.initialize`` must run before any
    device access on multi-host.
    """
    global _initialized
    config = config or RuntimeConfig()
    if _initialized:
        return
    if config.simulate_devices > 0:
        simulate_devices(config.simulate_devices)

    import jax

    if config.distributed:
        # Explicit args for CPU/GPU clusters; all-None autodetects on TPU pods.
        jax.distributed.initialize(
            coordinator_address=config.coordinator_address,
            num_processes=config.num_processes,
            process_id=config.process_id,
        )
    setup_logging(config.log_level)
    if config.profiler_port > 0 and jax.process_index() == 0:
        jax.profiler.start_server(config.profiler_port)
        logger.info("jax.profiler server on port %d", config.profiler_port)
    logger.info(
        "runtime up: process %d/%d, %d local / %d global devices (%s)",
        jax.process_index(),
        jax.process_count(),
        jax.local_device_count(),
        jax.device_count(),
        jax.devices()[0].platform,
    )
    _initialized = True


def barrier(name: str = "startup") -> None:
    """Block until all processes reach this point — the health-check analog of
    the reference's lone ``dist.barrier()`` (ref ``src/distributed_inference.py:18``).
    Implemented as an all-reduce over every global device, so it also verifies
    that cross-host collectives actually work."""
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)


def is_coordinator() -> bool:
    """True on process 0 — the reference's ``rank == 0`` gate (ref ``:71``)."""
    import jax

    return jax.process_index() == 0


def shutdown_runtime() -> None:
    """Tear down cleanly (analog of ``cleanup()``, ref ``:20-21``): final
    barrier so no host exits while peers are mid-collective, then release the
    distributed client."""
    global _initialized
    if not _initialized:
        return
    import jax

    try:
        if jax.process_count() > 1:
            barrier("shutdown")
            jax.distributed.shutdown()
    finally:
        _initialized = False
    logger.info("runtime shut down")
