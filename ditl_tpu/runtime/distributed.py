"""Distributed runtime bring-up / teardown (L2).

TPU-native replacement for the reference's NCCL process-group lifecycle:
``setup(rank, world_size)`` — env mutation + ``dist.init_process_group("nccl")``
+ ``dist.barrier()`` (ref ``src/distributed_inference.py:14-18``) — and
``cleanup()`` — ``dist.destroy_process_group()`` (ref ``:20-21``).

Design differences (TPU-first, SURVEY.md §5 'Distributed communication
backend'):

- Rendezvous is ``jax.distributed.initialize``: coordinator = process 0
  (the analog of ``MASTER_ADDR:MASTER_PORT``); on TPU pods all arguments are
  autodetected from the TPU metadata, so a single launcher serves every host
  (collapsing ``run_node0.sh``/``run_node1.sh``).
- Collectives are emitted by GSPMD/XLA over ICI/DCN; user code never issues
  them. The startup-health ``barrier()`` analog is
  ``multihost_utils.sync_global_devices``.
- CPU simulation: ``simulate_devices=N`` forces N virtual host devices via
  ``xla_force_host_platform_device_count``, which is how multi-node behavior
  is tested without a cluster (repairs the reference's deadlocking distributed
  test fixture, SURVEY.md §3.5).
"""

from __future__ import annotations

import os

from ditl_tpu.config import RuntimeConfig
from ditl_tpu.utils.logging import get_logger, setup_logging

logger = get_logger(__name__)

_initialized = False
_active_coordinator: str | None = None


def simulate_devices(n: int) -> None:
    """Request ``n`` virtual CPU devices. Must run before the first JAX
    *backend* touch (first ``jax.devices()``/array op). Env vars alone are not
    enough if something imported jax before us (jax snapshots env into its
    config at import time), so the config is also set directly."""
    # REPLACE any inherited device-count flag rather than keeping it: an
    # explicit simulate request must win over a parent process's env (e.g. a
    # supervisor child launched from the 8-device test harness).
    parts = [
        p
        for p in os.environ.get("XLA_FLAGS", "").split()
        if not p.startswith("--xla_force_host_platform_device_count")
    ]
    parts.append(f"--xla_force_host_platform_device_count={n}")
    os.environ["XLA_FLAGS"] = " ".join(parts)
    os.environ["JAX_NUM_CPU_DEVICES"] = str(n)  # newer-JAX equivalent
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", n)
    except AttributeError:
        # Older jax: no such option; the env settings above (applied before
        # the first backend touch) carry the device count alone.
        pass


def enable_compile_cache(path: str, *, force: bool = False) -> bool:
    """Point JAX's persistent compilation cache at ``path`` (idempotent;
    VERDICT r5 item 9: compile+first-window is 85.6 s per session and pays
    on every restart, drill, and bench run — the cache amortizes it to one
    cold run per program).

    The thresholds are dropped to zero so every program is cached — the
    big training step is one entry; the small host-side programs cost
    nothing. Returns True when the cache was enabled. On CPU the cache is
    only honored for single-device processes unless ``force``: this
    jaxlib's XLA:CPU intermittently aborts (SIGABRT) when deserializing
    cached executables under the multi-device host platform (the 8-device
    test sim — see tests/conftest.py and docs/troubleshooting.md §20)."""
    if not path:
        return False
    import jax

    if (not force and jax.default_backend() == "cpu"
            and (jax.local_device_count() > 1 or jax.process_count() > 1)):
        logger.debug(
            "compile cache skipped: multi-device/multi-process CPU host "
            "platform (known-bad executable deserialization in this jaxlib "
            "— worker SIGSEGV/SIGABRT in the pod drills)"
        )
        return False
    full = os.path.abspath(os.path.expanduser(path))
    os.makedirs(full, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", full)
    # Cache everything: the default thresholds skip exactly the small
    # programs whose re-compiles add up across drills and restarts.
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    logger.info("persistent compilation cache at %s", full)
    return True


def init_runtime(config: RuntimeConfig | None = None) -> None:
    """Bring up the distributed runtime (idempotent).

    Order matters: simulation flags must be set before JAX initializes its
    backends, and ``jax.distributed.initialize`` must run before any
    device access on multi-host.
    """
    global _initialized, _active_coordinator
    config = config or RuntimeConfig()
    if _initialized:
        if (
            config.distributed
            and config.coordinator_address
            and _active_coordinator is not None
            and config.coordinator_address != _active_coordinator
        ):
            # Elastic relaunch in-process: the pod came back on a bumped
            # coordinator port (runtime/elastic.py restarts a generation
            # against a fresh port), so the old distributed client — whose
            # rendezvous state is generation-scoped — must be replaced, not
            # reused.
            reinit_distributed(config)
        return
    if config.simulate_devices > 0:
        simulate_devices(config.simulate_devices)

    import jax

    if config.distributed:
        _enable_cpu_cross_process_collectives()
        # Explicit args for CPU/GPU clusters; all-None autodetects on TPU pods.
        jax.distributed.initialize(
            coordinator_address=config.coordinator_address,
            num_processes=config.num_processes,
            process_id=config.process_id,
        )
        _active_coordinator = config.coordinator_address
    setup_logging(config.log_level)
    if config.compile_cache_dir:
        enable_compile_cache(config.compile_cache_dir)
    if config.profiler_port > 0 and jax.process_index() == 0:
        jax.profiler.start_server(config.profiler_port)
        logger.info("jax.profiler server on port %d", config.profiler_port)
    logger.info(
        "runtime up: process %d/%d, %d local / %d global devices (%s)",
        jax.process_index(),
        jax.process_count(),
        jax.local_device_count(),
        jax.device_count(),
        jax.devices()[0].platform,
    )
    _initialized = True


def _enable_cpu_cross_process_collectives() -> None:
    """Select the Gloo transport for CPU cross-process collectives. The
    default in-process CPU backend refuses multiprocess computations
    ("Multiprocess computations aren't implemented on the CPU backend"), so
    any distributed CPU pod — the multi-process drills, or a CPU cluster —
    needs this set BEFORE the backend initializes. No-ops on TPU/GPU
    platforms and on jax versions without the option."""
    import jax

    platforms = jax.config.jax_platforms or ""
    # Unset platforms means auto-detection, which on a plain CPU host picks
    # the very backend that needs this flag — only skip when the operator
    # explicitly selected a non-CPU platform.
    if platforms and "cpu" not in platforms.split(","):
        return
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except (AttributeError, ValueError):
        pass  # older jax (env/XLA flags decide) or gloo not compiled in


def reinit_distributed(config: RuntimeConfig) -> None:
    """Replace the distributed client for a new pod generation (elastic
    relaunch on a bumped coordinator port).

    Only possible BEFORE this process has executed any JAX computation —
    jax refuses to re-initialize an already-computed process (drilled in
    tests/elastic_drill.py, both polarities), because the backend's
    collective channels were created against the old generation's store. A
    process that has already computed must be RELAUNCHED to rejoin — which
    is exactly what the pod controller does; this path serves workers that
    brought the client up but died/rewired before touching a device. The
    refusal is translated into an actionable error instead of jax's
    generic one."""
    global _active_coordinator
    import jax

    logger.info(
        "re-initializing distributed runtime: coordinator %s -> %s",
        _active_coordinator,
        config.coordinator_address,
    )
    try:
        jax.distributed.shutdown()
    except RuntimeError:
        pass  # old client already gone (e.g. coordinator died with the pod)
    # The rejoin can only succeed when the backend has NOT initialized yet —
    # which means the CPU collectives transport can (and must) still be
    # selected for the new generation's first computation.
    _enable_cpu_cross_process_collectives()
    try:
        jax.distributed.initialize(
            coordinator_address=config.coordinator_address,
            num_processes=config.num_processes,
            process_id=config.process_id,
        )
    except RuntimeError as e:
        raise RuntimeError(
            "cannot rejoin a new pod generation in-process: this process "
            "already executed JAX computations against the old generation's "
            "collective channels. Relaunch the process to rejoin (the pod "
            "controller in runtime/elastic.py does this automatically)."
        ) from e
    _active_coordinator = config.coordinator_address


def barrier(name: str = "startup") -> None:
    """Block until all processes reach this point — the health-check analog of
    the reference's lone ``dist.barrier()`` (ref ``src/distributed_inference.py:18``).
    Implemented as an all-reduce over every global device, so it also verifies
    that cross-host collectives actually work."""
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)


def is_coordinator() -> bool:
    """True on process 0 — the reference's ``rank == 0`` gate (ref ``:71``)."""
    import jax

    return jax.process_index() == 0


def shutdown_runtime() -> None:
    """Tear down cleanly (analog of ``cleanup()``, ref ``:20-21``): final
    barrier so no host exits while peers are mid-collective, then release the
    distributed client."""
    global _initialized, _active_coordinator
    if not _initialized:
        return
    import jax

    try:
        if jax.process_count() > 1:
            barrier("shutdown")
            jax.distributed.shutdown()
    finally:
        _initialized = False
        _active_coordinator = None
    logger.info("runtime shut down")
