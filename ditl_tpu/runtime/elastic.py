"""Elastic pod-training subsystem (L2/L6).

The reference's failure story for a dying node is a troubleshooting-doc
paragraph; `launch.run_supervised` upgraded that to an in-process retry, and
`launch --supervise` to a single-child process supervisor. Neither survives
the scenario a real pod actually faces: N worker *processes* mid-collective,
one of which is SIGKILLed (OOM-killer, host crash, preemption). The
survivors then sit inside a collective that will never complete — the dead
peer cannot be healed by restarting it alone, because `jax.distributed`
rendezvous state and in-flight collectives are pod-global.

This module is the pod-level answer:

- :class:`PodController` launches N worker processes, watches liveness two
  ways (process exit codes, and per-worker heartbeat files the trainer
  touches every step), and on any worker death tears down the survivors and
  relaunches the FULL pod against a fresh coordinator port (the old one can
  linger in TIME_WAIT, and the distributed client's rendezvous state is
  generation-scoped anyway).
- Recovery correctness comes from multi-host Orbax checkpointing
  (`train/checkpoint.py`): every process of the relaunched pod restores
  params + optimizer state + data-iterator position and continues training
  where the committed history left off.

The controller is deliberately jax-free (stdlib only): it must stay
responsive while its children are wedged inside native collectives, and it
must be importable by the launcher before any backend is configured.
"""

from __future__ import annotations

import dataclasses
import enum
import json
import os
import signal
import socket
import statistics
import subprocess
import time
from typing import Callable, Sequence

from ditl_tpu.chaos import maybe_inject
from ditl_tpu.telemetry import (
    LIVENESS_RING,
    Anomaly,
    AnomalyPlane,
    EventJournal,
    FlightRecorder,
    IncidentManager,
    controller_journal_path,
    write_pod_timeline,
)
from ditl_tpu.utils.logging import get_logger

logger = get_logger(__name__)

__all__ = [
    "PodState",
    "PodResult",
    "PodController",
    "free_port",
    "heartbeat_path",
    "emit_heartbeat",
    "read_heartbeat",
]


class PodState(enum.Enum):
    """Controller lifecycle. Transitions are logged (and printed by
    ``launch --supervise``) so a wedged pod is debuggable from the outside."""

    IDLE = "IDLE"
    LAUNCHING = "LAUNCHING"
    RUNNING = "RUNNING"
    STOPPING = "STOPPING"
    RESTARTING = "RESTARTING"
    DONE = "DONE"
    FAILED = "FAILED"


def _heartbeat_files(directory: str) -> list[str]:
    """Every worker heartbeat file in ``directory`` — the single place
    (besides :func:`heartbeat_path`) that knows the filename scheme."""
    import glob

    return glob.glob(os.path.join(directory, "worker-*.heartbeat"))


def _describe_rc(rc: int) -> str:
    """Human-readable death cause. Signal numbers without an enum member
    (real-time signals) must not crash the controller mid-teardown."""
    if rc >= 0:
        return f"rc={rc}"
    try:
        return f"signal {signal.Signals(-rc).name}"
    except ValueError:
        return f"signal {-rc}"


def free_port() -> int:
    """A currently-free TCP port on localhost. Each pod generation binds a
    fresh one: a crashed coordinator's port can sit in TIME_WAIT for minutes
    (troubleshooting.md §1), and reusing it makes relaunch racy."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def heartbeat_path(directory: str, process_index: int) -> str:
    """Per-worker heartbeat file. Keyed by process index (not PID) so the
    config stays identical across workers — the cross-host consistency check
    fingerprints the config, and per-worker paths would trip it."""
    return os.path.join(directory, f"worker-{process_index}.heartbeat")


def emit_heartbeat(directory: str, process_index: int, step: int) -> None:
    """Atomically publish liveness (called by the trainer once per step
    window, and once before the first step so compile time reads as alive)."""
    # Chaos seam: `delay`/`hang` here starve the controller's staleness
    # watchdog (drilling stall-detection), `error` crashes the beat path.
    maybe_inject("elastic.heartbeat", step=step)
    os.makedirs(directory, exist_ok=True)
    path = heartbeat_path(directory, process_index)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump({"step": int(step), "time": time.time(), "pid": os.getpid()}, f)
    os.replace(tmp, path)


def read_heartbeat(path: str) -> dict | None:
    """Last published heartbeat, or None if absent/corrupt (a torn write is
    impossible — emit is atomic — but a worker may die before its first,
    and a foreign/hand-edited file must read as corrupt, not crash the
    controller)."""
    try:
        with open(path) as f:
            hb = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(hb, dict) or not isinstance(hb.get("time"), (int, float)):
        return None
    return hb


@dataclasses.dataclass
class PodResult:
    """Outcome of :meth:`PodController.run`."""

    state: PodState
    restarts: int
    returncodes: list[int | None]  # final generation's exit codes
    ports: list[int]  # coordinator port per generation (len == restarts + 1)
    transitions: list[str]  # "STATE -> STATE (why)" in order
    # Exit code of the worker whose death triggered the LAST teardown — the
    # actual failure, as opposed to the -SIGTERM codes the controller's own
    # survivor teardown writes into ``returncodes``.
    failure_rc: int | None = None

    @property
    def ok(self) -> bool:
        return self.state is PodState.DONE

    @property
    def returncode(self) -> int:
        if self.ok:
            return 0
        if self.failure_rc not in (0, None):
            return self.failure_rc
        for rc in self.returncodes:
            if rc not in (0, None):
                return rc
        return 1


class PodController:
    """Launch, watch, and elastically relaunch a pod of worker processes.

    ``build_argv(proc_id, nproc, port, attempt)`` produces each worker's
    command line; the controller owns the coordinator port so every
    generation rendezvouses on a fresh one. Liveness is judged by process
    exit first (a nonzero exit is a death; exit 0 is completion) and by
    heartbeat staleness second (``heartbeat_timeout_s > 0``): a worker that
    is alive as a process but has stopped making training progress — wedged
    in a collective whose peer died some other way — is treated as dead too.
    """

    def __init__(
        self,
        num_workers: int,
        build_argv: Callable[[int, int, int, int], Sequence[str]],
        *,
        env: dict[str, str] | None = None,
        max_pod_restarts: int = 0,
        heartbeat_dir: str = "",
        heartbeat_timeout_s: float = 0.0,
        heartbeat_ids: Sequence[int | None] | None = None,
        grace_s: float = 5.0,
        completion_grace_s: float = 60.0,
        poll_s: float = 0.2,
        port_factory: Callable[[], int] = free_port,
        log: Callable[[str], None] | None = None,
        on_restart: Callable[[int, int, int], None] | None = None,
        journal_dir: str = "",
        journal_max_bytes: int | None = None,
        straggler_lag_steps: int = 0,
        straggler_relaunch: bool = False,
        incident_dir: str = "",
        incident_kwargs: dict | None = None,
    ):
        if num_workers < 1:
            raise ValueError(f"num_workers must be >= 1, got {num_workers}")
        self.num_workers = num_workers
        self.build_argv = build_argv
        self.env = env
        self.max_pod_restarts = max_pod_restarts
        self.heartbeat_dir = heartbeat_dir
        self.heartbeat_timeout_s = heartbeat_timeout_s
        # Worker slot -> heartbeat file index. The trainer emits under its
        # jax.process_index(), which equals the slot for a controller-owned
        # pod but NOT for a single supervised member of a larger pod (its
        # --process-id can be anything); the launcher passes the mapping. A
        # None entry is a wildcard — "any heartbeat file in the dir counts"
        # — for workers whose rank is autodetected and unknowable here.
        self.heartbeat_ids: list[int | None] = (
            list(heartbeat_ids) if heartbeat_ids is not None
            else list(range(num_workers))
        )
        if len(self.heartbeat_ids) != num_workers:
            raise ValueError(
                f"heartbeat_ids must have one entry per worker "
                f"({num_workers}), got {self.heartbeat_ids}"
            )
        self.grace_s = grace_s
        # Once any worker exits 0 (SPMD: training completed pod-wide — the
        # final barrier passed everywhere), stragglers get this long to
        # finish their own teardown before being reaped as wedged-in-
        # shutdown. Without it a hung (not crashed) straggler would spin
        # the supervisor forever when no heartbeats/deadline are armed.
        self.completion_grace_s = completion_grace_s
        self.poll_s = poll_s
        self.port_factory = port_factory
        self._log = log or (lambda msg: logger.info("%s", msg))
        self.on_restart = on_restart
        self.state = PodState.IDLE
        self.restarts = 0
        self.transitions: list[str] = []
        self.ports: list[int] = []
        self._procs: list[subprocess.Popen] = []
        self._spawned_at = 0.0
        self._failure_rc: int | None = None
        # Cross-process event journal (telemetry/journal.py): the controller
        # appends its lifecycle events to events-controller.jsonl and merges
        # every participant's journal into pod_timeline.jsonl when the run
        # ends — the ordered answer to "what happened when the worker died".
        self.journal_dir = journal_dir
        self._journal: EventJournal | None = (
            EventJournal(controller_journal_path(journal_dir),
                         source="controller",
                         max_bytes=journal_max_bytes)
            if journal_dir else None
        )
        # Straggler escalation (ISSUE 5): _stale_workers only sees
        # dead-or-silent workers; a slow-NOT-dead worker (thermal throttle,
        # noisy neighbor, degraded NIC) heartbeats on time while its STEP
        # falls behind the pod — in SPMD that drags every peer to its pace.
        # A worker lagging the pod-median heartbeat step by more than
        # straggler_lag_steps is journaled (`pod.straggler`) once per
        # generation, and with straggler_relaunch=True escalated to the
        # same teardown-and-relaunch path as a death.
        self.straggler_lag_steps = straggler_lag_steps
        self.straggler_relaunch = straggler_relaunch
        self._straggler_flagged: set[int] = set()
        # Flight recorder + anomaly plane (ISSUE 10): every controller
        # lifecycle event also lands in the always-on liveness ring (the
        # pod's black box); with ``incident_dir`` set, worker deaths,
        # heartbeat stalls, and straggler escalations additionally
        # assemble incident bundles (ring dump + journal tail + config-
        # free manifest) through the shared manager.
        self.flight = FlightRecorder()
        self._incidents: IncidentManager | None = (
            IncidentManager(
                incident_dir, flight=self.flight, journal_dir=journal_dir,
                source="pod-controller", **(incident_kwargs or {}),
            )
            if incident_dir else None
        )
        self._anomaly = AnomalyPlane(
            incidents=self._incidents, journal=self._journal,
        )

    def _jevent(self, event: str, **attrs) -> None:
        self.flight.ring(LIVENESS_RING).record(event=event, **attrs)
        if self._journal is not None:
            self._journal.event(event, **attrs)

    def _trigger(self, kind: str, **detail) -> None:
        """Route a liveness failure into the anomaly plane (journal +
        bundle); fingerprinted per kind so a crash-looping pod dedupes
        into one bundle per cooldown window."""
        self._anomaly.trigger(Anomaly(kind, detail=detail))

    # -- state machine ------------------------------------------------------

    def _transition(self, new: PodState, why: str) -> None:
        line = f"pod-controller: {self.state.value} -> {new.value} ({why})"
        self.transitions.append(line)
        self.state = new
        self._log(line)

    # -- lifecycle ----------------------------------------------------------

    def _spawn(self, attempt: int) -> None:
        # Chaos seam: `delay` slows generation bring-up, `error` fails the
        # spawn (run()'s teardown still reaps earlier workers), `kill`
        # drills losing the controller itself.
        maybe_inject("elastic.spawn", step=attempt)
        self._straggler_flagged = set()
        port = self.port_factory()
        self.ports.append(port)
        self._transition(
            PodState.LAUNCHING,
            f"generation {attempt}: {self.num_workers} workers, "
            f"coordinator port {port}",
        )
        self._jevent("pod.spawn", generation=attempt, port=port,
                     num_workers=self.num_workers)
        if self.heartbeat_dir:
            # Stale heartbeats from the previous generation must not mask a
            # worker that dies before its first step. Wildcard slots clear
            # every heartbeat file in the dir.
            stale_files = set()
            for hb_id in self.heartbeat_ids:
                if hb_id is None:
                    stale_files.update(_heartbeat_files(self.heartbeat_dir))
                else:
                    stale_files.add(heartbeat_path(self.heartbeat_dir, hb_id))
            for f in stale_files:
                try:
                    os.remove(f)
                except OSError:
                    pass
        # Append as we go (not a comprehension): if a later Popen fails, the
        # already-launched workers must remain referenced so the run()-level
        # teardown can reap them instead of leaking them into rendezvous.
        self._procs = []
        for i in range(self.num_workers):
            self._procs.append(
                subprocess.Popen(
                    list(self.build_argv(i, self.num_workers, port, attempt)),
                    env=self.env,
                )
            )
        # Wall clock, not monotonic: heartbeats carry time.time() stamps.
        self._spawned_at = time.time()
        self._transition(PodState.RUNNING, f"all {self.num_workers} workers spawned")

    def _teardown(self, why: str) -> None:
        """SIGTERM the survivors, then SIGKILL stragglers after ``grace_s``.
        A worker wedged in a native collective never runs Python signal
        handlers, but SIGTERM's default disposition still terminates it; the
        SIGKILL backstop covers processes that installed handlers."""
        self._transition(PodState.STOPPING, why)
        self._jevent("pod.teardown", why=why)
        for p in self._procs:
            if p.poll() is None:
                try:
                    p.terminate()
                except OSError:
                    pass
        deadline = time.monotonic() + self.grace_s
        for p in self._procs:
            if p.poll() is None:
                try:
                    p.wait(timeout=max(0.0, deadline - time.monotonic()))
                except subprocess.TimeoutExpired:
                    try:
                        p.kill()
                        p.wait(timeout=self.grace_s)
                    except (OSError, subprocess.TimeoutExpired):
                        pass

    def _stale_workers(self) -> list[int]:
        """Workers whose heartbeat is older than the timeout (wall clock —
        heartbeats carry ``time.time()`` stamps). The clock for a worker with
        no heartbeat yet starts at spawn time (first-step compile can
        dominate, so callers size the timeout above worst-case compile)."""
        if not (self.heartbeat_dir and self.heartbeat_timeout_s > 0):
            return []
        now = time.time()
        stale = []
        for i, p in enumerate(self._procs):
            if p.poll() is not None:
                continue
            hb_id = self.heartbeat_ids[i]
            if hb_id is None:
                # Wildcard slot (autodetected rank): the freshest heartbeat
                # in the dir stands in for this worker.
                times = [
                    hb["time"]
                    for f in _heartbeat_files(self.heartbeat_dir)
                    if (hb := read_heartbeat(f)) is not None
                ]
                last = max(times, default=None)
            else:
                hb = read_heartbeat(heartbeat_path(self.heartbeat_dir, hb_id))
                last = hb["time"] if hb else None
            base = max(last, self._spawned_at) if last else self._spawned_at
            if now - base > self.heartbeat_timeout_s:
                stale.append(i)
        return stale

    def _straggler_workers(self) -> list[tuple[int, int, int, int]]:
        """(worker, step, lag, pod_median) for live workers whose heartbeat
        STEP trails the pod median by more than ``straggler_lag_steps`` —
        the slow-not-dead class the liveness checks cannot see. Needs >= 2
        live step-reporting workers (a median of one is the worker itself)
        and attributable heartbeat slots (wildcard slots cannot be blamed)."""
        if not (self.heartbeat_dir and self.straggler_lag_steps > 0):
            return []
        steps: dict[int, int] = {}
        for i, p in enumerate(self._procs):
            if p.poll() is not None:
                continue
            hb_id = self.heartbeat_ids[i]
            if hb_id is None:
                continue
            hb = read_heartbeat(heartbeat_path(self.heartbeat_dir, hb_id))
            if hb is None or not isinstance(hb.get("step"), (int, float)):
                continue
            steps[i] = int(hb["step"])
        if len(steps) < 2:
            return []
        med = int(statistics.median(steps.values()))
        return [
            (i, s, med - s, med)
            for i, s in sorted(steps.items())
            if med - s > self.straggler_lag_steps
        ]

    def run(self, timeout_s: float | None = None) -> PodResult:
        """Drive the pod to DONE or FAILED. ``timeout_s`` is a hard wall-clock
        deadline over ALL generations (drills use it so a wedged pod fails
        the test instead of hanging the suite). Any exception escaping the
        controller itself (spawn failure, bug) still tears the workers down
        — leaking them wedged in rendezvous is never acceptable."""
        try:
            return self._run(timeout_s)
        except BaseException:
            self._teardown("controller error; tearing down workers")
            raise

    def _run(self, timeout_s: float | None) -> PodResult:
        start = time.monotonic()
        attempt = 0
        first_zero_at: float | None = None
        self._spawn(attempt)
        while True:
            time.sleep(self.poll_s)
            # The deadline is checked UNCONDITIONALLY (not only on idle
            # iterations): a fast-crash-looping pod with a deep restart
            # budget must still stop at the deadline, not minutes past it.
            timed_out = (
                timeout_s is not None and time.monotonic() - start > timeout_s
            )
            rcs = [p.poll() for p in self._procs]
            failure: str | None = None
            if all(rc == 0 for rc in rcs):
                self._transition(PodState.DONE, "all workers exited 0")
                return self._result()
            if any(rc == 0 for rc in rcs):
                if first_zero_at is None:
                    first_zero_at = time.monotonic()
                elif (
                    time.monotonic() - first_zero_at > self.completion_grace_s
                    and any(rc is None for rc in rcs)
                ):
                    # Training completed (a worker exited 0 ⇒ the final
                    # barrier passed pod-wide) but a straggler is wedged in
                    # its own shutdown: reap it and finish rather than spin
                    # forever (no death, no heartbeat, maybe no deadline).
                    self._teardown(
                        "straggler(s) still alive "
                        f"{self.completion_grace_s:.0f}s after a peer "
                        "completed; reaping"
                    )
                    self._transition(
                        PodState.DONE,
                        "training completed; wedged straggler(s) reaped "
                        "post-completion",
                    )
                    return self._result()
            dead = [(i, rc) for i, rc in enumerate(rcs) if rc not in (0, None)]
            if dead:
                i, rc = dead[0]
                if any(r == 0 for r in rcs):
                    # SPMD: a worker exits 0 only when training completed
                    # pod-wide, so a peer dying AFTER that is a
                    # teardown-time death (e.g. an XLA shutdown abort), not
                    # a training failure — relaunching would retrain the
                    # tail and print a second summary. Reap stragglers and
                    # finish.
                    self._teardown(
                        f"worker {i} died ({_describe_rc(rc)}) after a peer "
                        "completed; reaping stragglers"
                    )
                    self._transition(
                        PodState.DONE,
                        f"training completed; worker {i} death "
                        f"({_describe_rc(rc)}) was post-completion",
                    )
                    return self._result()
                failure = f"worker {i} died ({_describe_rc(rc)})"
                self._failure_rc = rc
                self._jevent("pod.worker_died", worker=i, rc=rc,
                             cause=_describe_rc(rc))
                self._trigger("elastic.worker_death", worker=i, rc=rc,
                              cause=_describe_rc(rc),
                              restarts=self.restarts)
            else:
                stale = self._stale_workers()
                if stale and any(r == 0 for r in rcs):
                    # Same post-completion rule as the exit-code branch: a
                    # worker wedged in SHUTDOWN after a peer exited 0 is not
                    # a training failure — reap it and finish, don't retrain
                    # the completed tail.
                    self._teardown(
                        f"worker {stale[0]} heartbeat stale after a peer "
                        "completed; reaping stragglers"
                    )
                    self._transition(
                        PodState.DONE,
                        f"training completed; worker {stale[0]} stale "
                        "heartbeat was post-completion",
                    )
                    return self._result()
                if stale:
                    failure = (
                        f"worker {stale[0]} heartbeat stale "
                        f"(> {self.heartbeat_timeout_s:.1f}s)"
                    )
                    # No exit code exists for a stall; don't let the
                    # teardown's own SIGTERM codes masquerade as one.
                    self._failure_rc = 1
                    self._jevent("pod.heartbeat_stale", worker=stale[0],
                                 timeout_s=self.heartbeat_timeout_s)
                    self._trigger("elastic.heartbeat_stale",
                                  worker=stale[0],
                                  timeout_s=self.heartbeat_timeout_s)
                else:
                    stragglers = self._straggler_workers()
                    for i, step_i, lag_i, med in stragglers:
                        if i in self._straggler_flagged:
                            continue
                        # Journal once per (worker, generation): the lag
                        # persists poll after poll and must not spam the
                        # timeline.
                        self._straggler_flagged.add(i)
                        self._jevent(
                            "pod.straggler", worker=i, step=step_i,
                            lag=lag_i, median=med,
                            escalate=self.straggler_relaunch,
                        )
                        self._log(
                            f"pod-controller: worker {i} straggling "
                            f"(step {step_i}, {lag_i} behind pod median "
                            f"{med}; escalate="
                            f"{'relaunch' if self.straggler_relaunch else 'log-only'})"
                        )
                    if stragglers and self.straggler_relaunch:
                        i, step_i, lag_i, _med = stragglers[0]
                        failure = (
                            f"worker {i} straggling "
                            f"({lag_i} steps behind pod median)"
                        )
                        # A straggler has no exit code either.
                        self._failure_rc = 1
                        self._trigger("elastic.straggler", worker=i,
                                      step=step_i, lag=lag_i)
            if failure is None:
                if timed_out:
                    # Like the stale branch: no worker failed — don't let
                    # the teardown's own SIGTERM codes masquerade as the
                    # failure returncode.
                    self._failure_rc = 1
                    self._teardown(f"pod deadline exceeded ({timeout_s:.0f}s)")
                    self._transition(PodState.FAILED, "deadline exceeded")
                    return self._result()
                continue
            self._teardown(f"{failure}; tearing down survivors")
            if timed_out:
                self._transition(
                    PodState.FAILED,
                    f"{failure}; pod deadline exceeded ({timeout_s:.0f}s)",
                )
                return self._result()
            if self.restarts >= self.max_pod_restarts:
                self._transition(
                    PodState.FAILED,
                    f"{failure}; restart budget exhausted "
                    f"({self.restarts}/{self.max_pod_restarts})",
                )
                return self._result()
            self.restarts += 1
            attempt += 1
            self._transition(
                PodState.RESTARTING,
                f"{failure}; relaunching full pod "
                f"(restart {self.restarts}/{self.max_pod_restarts}, "
                "bumping coordinator port)",
            )
            self._jevent("pod.relaunch", restart=self.restarts,
                         max_restarts=self.max_pod_restarts, why=failure)
            if self.on_restart is not None:
                self.on_restart(self._failure_rc or 1, self.restarts,
                                self.max_pod_restarts)
            self._spawn(attempt)

    def _result(self) -> PodResult:
        result = PodResult(
            state=self.state,
            restarts=self.restarts,
            returncodes=[p.poll() for p in self._procs],
            ports=list(self.ports),
            transitions=list(self.transitions),
            failure_rc=self._failure_rc,
        )
        if self._journal is not None:
            self._jevent(
                "pod.done" if result.ok else "pod.failed",
                restarts=self.restarts, returncode=result.returncode,
            )
            self._journal.close()
            self._journal = None
            # Merge every participant's journal (controller + workers across
            # all generations) into the ordered pod timeline.
            path = write_pod_timeline(self.journal_dir)
            self._log(f"pod-controller: merged pod timeline at {path}")
        return result
