"""Cross-host consistency checking (race-detection analog).

The reference handles de-synchronized nodes only as documentation — the
'Nodes out of sync' troubleshooting entry tells the operator to manually verify
identical seeds/datasets/versions (ref ``docs/troubleshooting.md:53-63``).
Here that advice is executed in code at startup: every process contributes a
fingerprint of its (config, seed, data-shard assignment, library versions) and
an all-gather proves they agree. A mismatched host fails fast at step 0 with a
precise diff instead of corrupting a run with silently divergent SPMD programs
(which on TPU typically manifests as a hang inside a collective — the hardest
failure mode to debug, SURVEY.md §5).
"""

from __future__ import annotations

import hashlib
import json
from typing import Any, Mapping

from ditl_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def _fingerprint(payload: Mapping[str, Any]) -> int:
    blob = json.dumps(payload, sort_keys=True, default=str).encode("utf-8")
    return int.from_bytes(hashlib.sha256(blob).digest()[:8], "big") >> 1


def host_payload(config=None, extra: Mapping[str, Any] | None = None) -> dict[str, Any]:
    """What must agree across hosts for an SPMD run to be sound."""
    import jax

    payload: dict[str, Any] = {
        "jax_version": jax.__version__,
        "process_count": jax.process_count(),
        "global_device_count": jax.device_count(),
    }
    if config is not None:
        cfg = config.to_dict()
        # process_id is per-process BY CONSTRUCTION (the launcher assigns a
        # distinct one to every worker), so it must not poison the pod-wide
        # fingerprint — without this, the first real multi-process training
        # run would fail its own startup check. Everything else in the
        # config (including coordinator_address) must genuinely agree.
        if isinstance(cfg.get("runtime"), dict):
            cfg["runtime"].pop("process_id", None)
        payload["config"] = cfg
    if extra:
        payload.update(extra)
    return payload


def check_cross_host_consistency(
    config=None, extra: Mapping[str, Any] | None = None
) -> None:
    """All-gather every host's fingerprint; raise if any disagree.

    Uses ``process_allgather`` so it works on any mesh/topology; cost is one
    tiny collective at startup.
    """
    import jax
    import numpy as np
    from jax.experimental import multihost_utils

    payload = host_payload(config, extra)
    fp = _fingerprint(payload)
    gathered = multihost_utils.process_allgather(np.asarray(fp, dtype=np.int64))
    gathered = np.atleast_1d(gathered)
    if not bool(np.all(gathered == gathered[0])):
        bad = {i: int(v) for i, v in enumerate(gathered)}
        raise RuntimeError(
            "cross-host consistency check FAILED: hosts disagree on "
            f"(config, seed, shard assignment, versions): {bad}. "
            f"This host (process {jax.process_index()}) computed {fp} from "
            f"{json.dumps(payload, sort_keys=True, default=str)[:500]}"
        )
    logger.info("cross-host consistency check passed (fingerprint %d)", int(fp))
