"""Signal-driven anomaly detectors (ISSUE 10 tentpole leg b).

The repo already *produces* every signal a 3 a.m. incident needs —
``grad_norm`` is computed on every train step, deadline expiries and 429s
are counted, TTFT/TPOT land in histograms, SLO burn rates are evaluated —
but nothing *checks* them: the metrics are passive until a scraper asks.
This module is the checking layer: small host-side detectors over values
the callers already hold, producing :class:`Anomaly` records that the
incident plane (telemetry/incident.py) turns into black-box bundles.

Detector discipline (the registry's rules, inherited):

- **jax-free, zero device syncs**: detectors consume host floats and
  counter values. The training detector runs inside the MetricsLogger's
  existing ``log_every`` flush — the ONE place loss/grad_norm are already
  on the host — so arming it adds no blocking transfer (tier-1-pinned).
- **cheap when healthy**: one observe is a handful of subtractions and a
  bounded-window median; serving observes run every
  ``anomaly_check_every_ticks`` scheduler ticks, not per request.
- **rolling baselines, not absolute thresholds**: a latency "jump" is
  measured against the workload's own recent p95 (EMA over windowed
  histogram deltas), so the same config serves a CPU simulation and a v5e
  pod without retuning. Storm detectors (deadline expiry, 429s,
  preemption thrash) are per-window deltas — absolute rates ARE the right
  shape there.
- **detectors detect, the incident plane decides**: fingerprint dedupe,
  cooldown rate-limiting, and bundle assembly all live in
  ``IncidentManager`` — a detector may fire every window during a sustained
  storm and still produce exactly one bundle.
"""

from __future__ import annotations

import collections
import dataclasses
import hashlib
import math
import statistics
import time
from typing import Any

from ditl_tpu.utils.logging import get_logger

logger = get_logger(__name__)

__all__ = [
    "Anomaly",
    "AnomalyPlane",
    "GatewayAnomalyMonitor",
    "GatewayDetector",
    "NOISY_NEIGHBOR_KINDS",
    "NonFiniteMetricError",
    "ServingAnomalyMonitor",
    "ServingDetector",
    "TrainingDetector",
    "slo_alert_anomaly",
]


class NonFiniteMetricError(RuntimeError):
    """Raised by the trainer AFTER a fatal non-finite detection has been
    journaled and bundled — the crash the incident bundle precedes. A
    RuntimeError on purpose: it rides the same elastic-recovery path a
    genuine training failure would (launch.run_supervised restart
    budget), never the client-error path."""


def _finite(v: Any) -> bool:
    return isinstance(v, (int, float)) and math.isfinite(v)


@dataclasses.dataclass(frozen=True)
class Anomaly:
    """One detected anomaly. ``kind`` is the dotted trigger name
    (``train.loss_nonfinite``, ``serving.deadline_storm``, ...);
    ``severity`` is ``"fatal"`` (the run is about to crash — dump NOW)
    or ``"warning"`` (degradation worth a bundle, run continues).
    ``detail`` carries the evidence (host scalars only — it is JSON-dumped
    into the bundle manifest verbatim)."""

    kind: str
    severity: str = "warning"
    detail: dict = dataclasses.field(default_factory=dict)
    ts: float = dataclasses.field(default_factory=time.time)

    def fingerprint(self) -> str:
        """Stable identity for dedupe: the same KIND of failure maps to the
        same fingerprint no matter how its evidence varies per occurrence
        (a deadline storm's expiry count differs every window; it is still
        one incident). ``detail["fingerprint_key"]`` refines it when one
        kind covers distinguishable failures (e.g. per-objective SLO
        alerts)."""
        key = f"{self.kind}/{self.detail.get('fingerprint_key', '')}"
        return hashlib.sha256(key.encode()).hexdigest()[:12]


# ---------------------------------------------------------------------------
# Training-side detectors
# ---------------------------------------------------------------------------


class TrainingDetector:
    """Non-finite loss/grad plus rolling-window loss-spike and grad-norm
    explosion. Fed from host floats the metrics flush already fetched —
    never from device arrays."""

    def __init__(self, *, window: int = 32, min_history: int = 8,
                 loss_spike_factor: float = 4.0,
                 grad_explosion_factor: float = 10.0):
        self.min_history = max(2, min_history)
        self.loss_spike_factor = loss_spike_factor
        self.grad_explosion_factor = grad_explosion_factor
        self._losses: collections.deque = collections.deque(maxlen=window)
        self._grads: collections.deque = collections.deque(maxlen=window)

    def observe_step(self, step: int, loss: Any,
                     grad_norm: Any = None) -> list[Anomaly]:
        out: list[Anomaly] = []
        if loss is not None and not _finite(loss):
            out.append(Anomaly(
                "train.loss_nonfinite", severity="fatal",
                detail={"step": step, "loss": repr(loss)},
            ))
        if grad_norm is not None and not _finite(grad_norm):
            out.append(Anomaly(
                "train.grad_nonfinite", severity="fatal",
                detail={"step": step, "grad_norm": repr(grad_norm)},
            ))
        if _finite(loss):
            if len(self._losses) >= self.min_history:
                base = statistics.median(self._losses)
                if loss > self.loss_spike_factor * max(base, 1e-8):
                    out.append(Anomaly("train.loss_spike", detail={
                        "step": step, "loss": float(loss),
                        "rolling_median": round(base, 6),
                        "factor": self.loss_spike_factor,
                    }))
            self._losses.append(float(loss))
        if _finite(grad_norm):
            if len(self._grads) >= self.min_history:
                base = statistics.median(self._grads)
                if grad_norm > self.grad_explosion_factor * max(base, 1e-8):
                    out.append(Anomaly("train.grad_explosion", detail={
                        "step": step, "grad_norm": float(grad_norm),
                        "rolling_median": round(base, 6),
                        "factor": self.grad_explosion_factor,
                    }))
            self._grads.append(float(grad_norm))
        return out


# ---------------------------------------------------------------------------
# Serving-side detectors
# ---------------------------------------------------------------------------


class _HistWindow:
    """Windowed view over a cumulative fixed-bucket histogram: each
    ``advance`` returns (observations since last advance, their p95) by
    diffing bucket-count snapshots — the same counter-delta shape the SLO
    monitor uses, kept local so detectors never mutate the instrument."""

    def __init__(self):
        self._prev: list[int] | None = None
        self._prev_sum = 0.0

    def advance(self, hist) -> tuple[int, float | None]:
        counts = list(hist._counts)
        if self._prev is None or len(self._prev) != len(counts):
            delta = list(counts)
        else:
            delta = [c - p for c, p in zip(counts, self._prev)]
        self._prev = counts
        n = sum(delta)
        if n <= 0:
            return 0, None
        # Re-use Histogram.quantile over the delta counts via a shell
        # instance sharing the bucket ladder (no observations re-played).
        from ditl_tpu.telemetry.registry import Histogram

        shell = Histogram("_window", buckets=hist.buckets)
        shell._counts = delta
        shell._count = n
        return n, shell.quantile(0.95)


class ServingDetector:
    """Detectors over the continuous engine's stats snapshot + metrics
    bundle, observed once per ``anomaly_check_every_ticks`` ticks:

    - **deadline storm / 429 storm / preemption thrash**: per-window
      counter deltas >= ``storm_threshold``.
    - **queue-depth growth**: depth >= ``queue_depth_limit`` AND still
      growing vs the previous observation (a deep-but-draining queue is
      backlog, not pathology).
    - **TTFT/TPOT p95 jump**: windowed histogram p95 >
      ``latency_factor`` x the EMA of previous windows' p95s (needs
      ``min_samples`` observations in the window and one prior window).
    - **prefix-hit-ratio collapse**: windowed hit ratio <
      ``hit_ratio_floor`` x the EMA baseline, once the baseline is
      meaningful (>= 0.1) and the window saw >= ``min_hit_tokens``
      prompt tokens.
    """

    _EMA_ALPHA = 0.3

    def __init__(self, *, storm_threshold: int = 8,
                 queue_depth_limit: int = 64,
                 latency_factor: float = 3.0, min_samples: int = 16,
                 hit_ratio_floor: float = 0.5, min_hit_tokens: int = 64):
        self.storm_threshold = max(1, storm_threshold)
        self.queue_depth_limit = max(1, queue_depth_limit)
        self.latency_factor = latency_factor
        self.min_samples = max(1, min_samples)
        self.hit_ratio_floor = hit_ratio_floor
        self.min_hit_tokens = max(1, min_hit_tokens)
        self._prev_counters: dict[str, float] = {}
        self._prev_queue_depth: int | None = None
        self._ttft_w = _HistWindow()
        self._tpot_w = _HistWindow()
        self._ttft_ema: float | None = None
        self._tpot_ema: float | None = None
        self._ratio_ema: float | None = None
        self._prev_hit = 0.0
        self._prev_miss = 0.0

    def _delta(self, name: str, value: float) -> float:
        prev = self._prev_counters.get(name, 0.0)
        self._prev_counters[name] = value
        return value - prev

    def observe(self, stats: dict, metrics) -> list[Anomaly]:
        out: list[Anomaly] = []
        # -- storms: per-window counter deltas ---------------------------
        for counter, kind in (
            (metrics.deadline_expired, "serving.deadline_storm"),
            (metrics.queue_full, "serving.429_storm"),
            (metrics.preemptions, "serving.preemption_thrash"),
        ):
            d = self._delta(kind, counter.value)
            if d >= self.storm_threshold:
                out.append(Anomaly(kind, detail={
                    "window_count": int(d),
                    "lifetime_total": int(counter.value),
                    "threshold": self.storm_threshold,
                }))
        # -- queue growth ------------------------------------------------
        depth = int(stats.get("queue_depth", 0))
        if (depth >= self.queue_depth_limit
                and self._prev_queue_depth is not None
                and depth > self._prev_queue_depth):
            out.append(Anomaly("serving.queue_growth", detail={
                "queue_depth": depth,
                "previous_depth": self._prev_queue_depth,
                "limit": self.queue_depth_limit,
                "queue_by_class": stats.get("queue_by_class", {}),
            }))
        self._prev_queue_depth = depth
        # -- latency jumps vs rolling baseline ---------------------------
        for window, hist, ema_attr, kind in (
            (self._ttft_w, metrics.ttft, "_ttft_ema", "serving.ttft_jump"),
            (self._tpot_w, metrics.decode_token, "_tpot_ema",
             "serving.tpot_jump"),
        ):
            n, p95 = window.advance(hist)
            if n < self.min_samples or p95 is None:
                continue
            ema = getattr(self, ema_attr)
            if ema is not None and p95 > self.latency_factor * ema:
                out.append(Anomaly(kind, detail={
                    "window_p95_s": round(p95, 6),
                    "baseline_p95_s": round(ema, 6),
                    "factor": self.latency_factor,
                    "window_samples": n,
                }))
            setattr(self, ema_attr,
                    p95 if ema is None
                    else ema + self._EMA_ALPHA * (p95 - ema))
        # -- prefix-hit-ratio collapse -----------------------------------
        hit = metrics.prefix_cache_hit_tokens.value
        miss = metrics.prefix_cache_miss_tokens.value
        d_hit, d_miss = hit - self._prev_hit, miss - self._prev_miss
        self._prev_hit, self._prev_miss = hit, miss
        if d_hit + d_miss >= self.min_hit_tokens:
            ratio = d_hit / (d_hit + d_miss)
            ema = self._ratio_ema
            if ema is not None and ema >= 0.1 and (
                    ratio < self.hit_ratio_floor * ema):
                out.append(Anomaly("serving.hit_ratio_collapse", detail={
                    "window_hit_ratio": round(ratio, 4),
                    "baseline_hit_ratio": round(ema, 4),
                    "floor": self.hit_ratio_floor,
                    "window_tokens": int(d_hit + d_miss),
                }))
            self._ratio_ema = (
                ratio if ema is None
                else ema + self._EMA_ALPHA * (ratio - ema)
            )
        return out


# ---------------------------------------------------------------------------
# Gateway-side detectors
# ---------------------------------------------------------------------------


class GatewayDetector:
    """Fleet-level detectors over the gateway's metrics bundle:

    - **replica death rate**: >= ``death_threshold`` replica deaths inside
      ``death_window_s`` (the FleetSupervisor reports each death via
      :meth:`note_death`; a single crash self-heals, a crash LOOP is an
      incident).
    - **spill storm**: fleet-saturation 429s + no-live-replica 503s per
      observe window >= ``storm_threshold``.
    - **relay-error storm**: retried attempts + mid-stream aborts per
      window >= ``storm_threshold``.
    """

    def __init__(self, *, storm_threshold: int = 8,
                 death_threshold: int = 2, death_window_s: float = 60.0):
        self.storm_threshold = max(1, storm_threshold)
        self.death_threshold = max(1, death_threshold)
        self.death_window_s = death_window_s
        self._deaths: collections.deque = collections.deque(maxlen=64)
        self._prev: dict[str, float] = {}

    def note_death(self, replica_id: str,
                   now: float | None = None) -> list[Anomaly]:
        now = time.time() if now is None else now
        self._deaths.append((now, replica_id))
        recent = [r for t, r in self._deaths
                  if now - t <= self.death_window_s]
        if len(recent) >= self.death_threshold:
            return [Anomaly("gateway.replica_death_storm", detail={
                "deaths_in_window": len(recent),
                "window_s": self.death_window_s,
                "replicas": recent[-8:],
            })]
        return []

    def _delta(self, name: str, value: float) -> float:
        prev = self._prev.get(name, 0.0)
        self._prev[name] = value
        return value - prev

    def observe(self, gw_metrics) -> list[Anomaly]:
        out: list[Anomaly] = []
        spill = (self._delta("saturated", gw_metrics.saturated.value)
                 + self._delta("no_replica", gw_metrics.no_replica.value))
        if spill >= self.storm_threshold:
            out.append(Anomaly("gateway.spill_storm", detail={
                "window_count": int(spill),
                "threshold": self.storm_threshold,
            }))
        errors = (self._delta("retries", gw_metrics.retries.value)
                  + self._delta("aborts", gw_metrics.stream_aborts.value))
        if errors >= self.storm_threshold:
            out.append(Anomaly("gateway.relay_error_storm", detail={
                "window_count": int(errors),
                "threshold": self.storm_threshold,
            }))
        return out


# ---------------------------------------------------------------------------
# The plane: detectors -> journal -> incident bundles
# ---------------------------------------------------------------------------


def slo_alert_anomaly(objective: str, entry: dict) -> Anomaly:
    """The SLO burn monitor's false->true alert transition as an anomaly —
    fingerprinted per objective so a TTFT burn and an availability burn are
    distinct incidents."""
    return Anomaly("slo.burn_alert", detail={
        "fingerprint_key": objective,
        "objective": objective,
        "target": entry.get("target"),
        "windows": {
            w: {"burn_rate": v.get("burn_rate"),
                "error_rate": v.get("error_rate")}
            for w, v in entry.get("windows", {}).items()
        },
    })


class AnomalyPlane:
    """The sink every leg routes detections through: count, journal, and
    hand to the incident manager (which dedupes/rate-limits/assembles).
    ``trigger`` never raises — a broken bundle write must not take down
    the scheduler or trainer it is observing."""

    def __init__(self, incidents=None, journal=None):
        self.incidents = incidents
        self.journal = journal
        self.detected: dict[str, int] = {}

    def trigger(self, anomaly: Anomaly) -> str | None:
        """Returns the bundle path when one was assembled (None when
        deduped/cooled down/unarmed)."""
        self.detected[anomaly.kind] = self.detected.get(anomaly.kind, 0) + 1
        try:
            if self.journal is not None:
                self.journal.event(
                    "anomaly.detected", kind=anomaly.kind,
                    severity=anomaly.severity,
                    fingerprint=anomaly.fingerprint(), **{
                        k: v for k, v in anomaly.detail.items()
                        if isinstance(v, (int, float, str, bool))
                    },
                )
            if self.incidents is not None:
                return self.incidents.trigger(anomaly)
        except Exception:  # noqa: BLE001 - observability must not crash work
            logger.exception("anomaly plane: trigger failed for %s",
                             anomaly.kind)
        return None

    def on_slo_alert(self, objective: str, entry: dict) -> None:
        """The ``BurnRateMonitor(on_alert=...)`` hook shape."""
        self.trigger(slo_alert_anomaly(objective, entry))


class GatewayAnomalyMonitor:
    """What the fleet supervisor holds: replica-death notes (fired from
    the supervisor's recovery path) plus per-poll observes over the
    gateway metrics bundle. With an ``slo`` attached each observe also
    samples the fleet burn-rate windows, so gateway burn alerts journal
    and trigger headlessly too."""

    def __init__(self, plane: AnomalyPlane, gw_metrics,
                 detector: GatewayDetector | None = None,
                 slo=None, flight=None, check_every: int = 4):
        self.plane = plane
        self.gw_metrics = gw_metrics
        self.detector = detector if detector is not None else GatewayDetector()
        self.slo = slo
        self.flight = flight
        self.check_every = max(1, check_every)
        self._polls = 0
        self._broken = False

    def note_replica_death(self, replica_id: str) -> None:
        """The supervisor increments the ``replica_deaths`` counter itself
        (unconditionally); this hook only owns the detector + ring side."""
        try:
            if self.flight is not None:
                self.flight.ring("replica_lifecycle").record(
                    event="replica.died", replica=replica_id,
                )
            for anomaly in self.detector.note_death(replica_id):
                self.plane.trigger(anomaly)
        except Exception:  # noqa: BLE001 - never break replica recovery
            logger.exception("gateway anomaly monitor: death note failed")

    def poll(self) -> None:
        """Called once per supervisor poll; observes every
        ``check_every``-th call."""
        self._polls += 1
        if self._broken or self._polls % self.check_every:
            return
        try:
            if self.slo is not None:
                self.slo.report()
            for anomaly in self.detector.observe(self.gw_metrics):
                self.plane.trigger(anomaly)
        except Exception:  # noqa: BLE001 - never break the health loop
            logger.exception("gateway anomaly monitor failed; disarming")
            self._broken = True


# Anomaly kinds a noisy-neighbor conviction attaches to (ISSUE 15): the
# latency storms whose usual cause IS one tenant's prefill burden
# monopolizing the scheduler (interference is what TPOT/TTFT jumps
# measure). Storm counters (429s, deadline expiries) are fleet-level
# symptoms with many causes and are deliberately NOT convicted on.
NOISY_NEIGHBOR_KINDS = ("serving.tpot_jump", "serving.ttft_jump")


class ServingAnomalyMonitor:
    """What the continuous engine holds: observe cadence + the serving
    detector + (optionally) the SLO monitor, all feeding one plane. The
    engine calls :meth:`observe_serving` every ``check_every`` ticks;
    with an ``slo`` attached each observe also samples the burn-rate
    windows — so a headless fleet with no Prometheus scraper still
    evaluates (and journals) burn alerts (ISSUE 10 satellite).

    With a ``usage`` meter attached (telemetry/usage.UsageMeter,
    ISSUE 15), every observe also advances the meter's per-tenant
    prefill-token/device-time window, and when a TPOT/TTFT storm fires
    the dominant tenant is CONVICTED — the anomaly's detail gains a
    ``noisy_neighbor`` block (tenant, window shares, lifetime usage
    snapshot) that rides verbatim into the incident-bundle manifest,
    turning "the fleet is slow" into "tenant t_3fa21b's batch job is"
    (docs/troubleshooting.md §33)."""

    def __init__(self, plane: AnomalyPlane,
                 detector: ServingDetector | None = None,
                 slo=None, check_every: int = 32,
                 usage=None, conviction_share: float = 0.6,
                 conviction_min_tokens: int = 256):
        self.plane = plane
        self.detector = detector if detector is not None else ServingDetector()
        self.slo = slo
        self.check_every = max(1, check_every)
        self.usage = usage
        self.conviction_share = conviction_share
        self.conviction_min_tokens = conviction_min_tokens
        self._broken = False

    def observe_serving(self, stats: dict, metrics) -> None:
        if self._broken:
            return
        try:
            if self.slo is not None:
                # Headless burn evaluation: report() samples the windows
                # and fires the monitor's alert-transition hook (slo.py),
                # which routes back into this plane.
                self.slo.report()
            window = (
                self.usage.advance_window() if self.usage is not None
                else None
            )
            for anomaly in self.detector.observe(stats, metrics):
                if window is not None and anomaly.kind in \
                        NOISY_NEIGHBOR_KINDS:
                    from ditl_tpu.telemetry.usage import (
                        convict_noisy_neighbor,
                    )

                    verdict = convict_noisy_neighbor(
                        window, self.conviction_share,
                        self.conviction_min_tokens,
                        snapshot=self.usage.snapshot(),
                    )
                    if verdict is not None:
                        # detail is a plain dict on the (frozen) Anomaly;
                        # enriching it here, BEFORE trigger, is what puts
                        # the conviction into the journal event and the
                        # bundle manifest. The fingerprint is unchanged —
                        # the same storm stays one incident whether or
                        # not a culprit was nameable.
                        anomaly.detail["noisy_neighbor"] = verdict
                self.plane.trigger(anomaly)
        except Exception:  # noqa: BLE001 - never kill the engine driver
            logger.exception("serving anomaly monitor failed; disarming")
            self._broken = True
