"""Goodput / badput accounting (ISSUE 3 tentpole leg 2).

Buckets a run's total wall time into where it actually went — the Google
ML-goodput convention: **goodput** is the fraction of wall time spent in
productive training steps; everything else is attributed **badput** (compile,
data-wait, checkpoint save/restore, eval, profiler overhead, work lost to a
restart) or ``other`` (the measured remainder: startup, teardown, untracked
host work).

Conservation is the design invariant: ``productive_step`` plus every badput
bucket plus ``other`` equals total tracked wall time EXACTLY by construction
(``other`` is the remainder), and the tier-1 test asserts the tracked buckets
themselves (everything except ``other``) stay within the total — a span
accounted twice would push the sum past it.

All timers are host wall clocks; nothing here touches a device value, so
always-on goodput tracking adds zero device syncs (the no-device-sync rule
shared with telemetry/registry.py).
"""

from __future__ import annotations

import contextlib
import time

__all__ = ["GoodputTracker", "BADPUT_BUCKETS", "lost_work_from_journal"]

# Canonical bucket names (report keys are f"{name}_s"). "productive_step" is
# the goodput bucket; the rest are badput. "other" is computed, not added.
BADPUT_BUCKETS = (
    "startup",
    "compile",
    "data_wait",
    "checkpoint_save",
    "checkpoint_restore",
    "eval",
    "profiler",
    "restart_lost_work",
)


class GoodputTracker:
    """Accumulate wall-time buckets; ``report()`` closes the books.

    Spans may not nest into the same wall time twice: the caller wraps
    disjoint phases (the trainer's loop structure guarantees this — data
    fetch, step window, checkpoint, eval are sequential on the host).
    """

    def __init__(self):
        self._t0: float | None = None
        self._t_end: float | None = None
        self._buckets: dict[str, float] = {}
        self.steps = 0

    def start(self) -> None:
        if self._t0 is None:
            self._t0 = time.perf_counter()

    def add(self, bucket: str, seconds: float) -> None:
        if seconds < 0:
            seconds = 0.0
        self._buckets[bucket] = self._buckets.get(bucket, 0.0) + seconds

    def add_step(self, seconds: float, n_steps: int = 1) -> None:
        """One productive step window's wall time."""
        self.add("productive_step", seconds)
        self.steps += n_steps

    @contextlib.contextmanager
    def span(self, bucket: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add(bucket, time.perf_counter() - t0)

    def stop(self) -> None:
        """Pin the total-wall endpoint (report() calls it implicitly once;
        later report() calls reuse the same endpoint so summaries agree)."""
        if self._t_end is None:
            self._t_end = time.perf_counter()

    def report(self) -> dict:
        """Bucketed wall-time report. Keys: ``total_wall_s``, one
        ``{bucket}_s`` per non-empty bucket, ``other_s`` (remainder),
        ``goodput_fraction`` (productive / total), ``badput_fraction``
        (attributed badput / total; ``other`` excluded so the two fractions
        name ATTRIBUTED time only), and ``steps``."""
        if self._t0 is None:
            return {"total_wall_s": 0.0, "goodput_fraction": 0.0, "steps": 0}
        self.stop()
        total = max(self._t_end - self._t0, 1e-9)
        tracked = sum(self._buckets.values())
        productive = self._buckets.get("productive_step", 0.0)
        out: dict = {"total_wall_s": round(total, 6), "steps": self.steps}
        for name, v in sorted(self._buckets.items()):
            out[f"{name}_s"] = round(v, 6)
        # Remainder, floored at 0: tracked spans can (rarely) overshoot the
        # total by timer granularity; conservation tests bound that at 1%.
        out["other_s"] = round(max(0.0, total - tracked), 6)
        out["goodput_fraction"] = round(productive / total, 4)
        out["badput_fraction"] = round(
            max(0.0, tracked - productive) / total, 4
        )
        return out


def lost_work_from_journal(
    records: list[dict], resume_step: int, before_ts: float
) -> float:
    """Wall-clock seconds of training lost to the restart we are resuming
    from, computed from a previous generation's journal ``records``
    (telemetry/journal.py): the span between the checkpoint save we are
    resuming at and the last sign of life before ``before_ts`` (this
    process's start). Returns 0.0 when the journal carries no usable pair —
    lost work is then simply unattributed (``other``), never guessed."""
    prior = [r for r in records if r["ts"] < before_ts]
    if not prior:
        return 0.0
    save_ts = None
    for r in prior:
        if (
            r["event"] == "checkpoint.save"
            and isinstance(r.get("step"), int)
            and r["step"] <= resume_step
        ):
            save_ts = r["ts"] if save_ts is None else max(save_ts, r["ts"])
    if save_ts is None:
        return 0.0
    last_ts = max(r["ts"] for r in prior)
    return max(0.0, last_ts - save_ts)
