"""HBM accounting (ISSUE 7 tentpole leg 2).

Per-device memory gauges from ``device.memory_stats()`` — allocator-level
host reads, zero device syncs — plus a live-buffer top-k dump journaled
when an OOM-class allocation failure unwinds through the training loop.

Degradation contract (tier-1-tested): backends without memory stats (CPU,
some plugin runtimes return ``None`` or lack the method entirely) produce
**no gauges and no crash** — the ``ditl_memory_*`` families are simply
absent from /metrics, never zero-valued lies.

Unlike the rest of telemetry/ this module is *about* the device, so its
functions import jax lazily — importing the module (or the telemetry
package) still never touches jax, preserving the package contract that the
jax-free gateway relies on.
"""

from __future__ import annotations

import contextlib
from typing import Any

from ditl_tpu.telemetry.registry import MetricsRegistry

__all__ = [
    "PREFIX",
    "MemoryWatcher",
    "device_memory_stats",
    "live_buffer_topk",
    "is_oom_error",
    "memory_metrics_lines",
]

PREFIX = "ditl_memory"

# The allocator-stat keys worth exposing, when present.
_STAT_KEYS = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
              "largest_alloc_size")

# Substrings that identify an allocation failure across jax/XLA spellings
# (XlaRuntimeError RESOURCE_EXHAUSTED, Mosaic/TPU "out of memory" variants).
# "oom" is matched as a whole word separately (below): the substring would
# false-positive on "zoom"/"bloom"-class messages.
_OOM_MARKERS = (
    "resource_exhausted", "resource exhausted", "out of memory",
    "failed to allocate", "allocation failure", "exceeds the memory",
)


def device_memory_stats(device: Any) -> dict[str, float] | None:
    """``device.memory_stats()`` filtered to the exposed keys; None when
    the backend has no stats (absent method, None return, or a raising
    plugin) — the caller's signal to emit nothing."""
    fn = getattr(device, "memory_stats", None)
    if fn is None:
        return None
    try:
        stats = fn()
    except Exception:  # noqa: BLE001 - plugin backends; advisory telemetry
        return None
    if not isinstance(stats, dict):
        return None
    out = {k: float(stats[k]) for k in _STAT_KEYS if k in stats}
    return out or None


def live_buffer_topk(k: int = 8) -> dict:
    """The ``k`` largest live device buffers (shape/dtype/sharding/nbytes)
    plus the totals — the "what is actually holding HBM" answer an OOM
    post-mortem starts with. Host-only reads of buffer metadata; the
    arrays' bytes are never touched."""
    # ditl: allow(import-layering) -- memwatch is jax-free ON IMPORT; this runs only when an armed watcher samples, and jax is already live in that process
    import jax

    arrays = [a for a in jax.live_arrays() if not getattr(a, "is_deleted",
                                                          lambda: False)()]
    infos = []
    total = 0
    for a in arrays:
        try:
            nbytes = int(a.nbytes)
            infos.append({
                "shape": list(a.shape),
                "dtype": str(a.dtype),
                "nbytes": nbytes,
                "sharding": _sharding_str(a),
            })
            total += nbytes
        except Exception:  # noqa: BLE001 - deleted/donated mid-walk
            continue
    infos.sort(key=lambda i: i["nbytes"], reverse=True)
    return {
        "n_live_buffers": len(infos),
        "live_bytes_total": total,
        "top": infos[: max(1, k)],
    }


def _sharding_str(a: Any) -> str:
    try:
        sh = a.sharding
        spec = getattr(sh, "spec", None)
        if spec is not None:
            return f"{type(sh).__name__}{tuple(spec)}"
        return type(sh).__name__
    except Exception:  # noqa: BLE001
        return "unknown"


def is_oom_error(exc: BaseException) -> bool:
    """True for OOM-class allocation failures — matched on the message and
    type name, since jaxlib's XlaRuntimeError carries the status code only
    in text form."""
    import re

    text = f"{type(exc).__name__}: {exc}".lower()
    if any(m in text for m in _OOM_MARKERS):
        return True
    return re.search(r"\boom\b", text) is not None


class MemoryWatcher:
    """Sampled HBM gauges + OOM dump hook for one process's devices.

    ``sample()`` refreshes per-device ``ditl_memory_device{i}_*`` gauges
    (and a local high-watermark that survives allocator counter resets);
    ``guard()`` wraps device work and journals a ``memory.oom_dump`` event
    — top-k live buffers with shapes and shardings, plus the last sampled
    stats — before re-raising an OOM-class failure. Everything degrades to
    a silent no-op when the backend exposes no stats."""

    def __init__(self, registry: MetricsRegistry | None = None,
                 journal=None, topk: int = 8):
        self.registry = registry if registry is not None else MetricsRegistry()
        self.journal = journal
        self.topk = topk
        self._peaks: dict[int, float] = {}
        self._last: dict[int, dict[str, float]] = {}
        self.available: bool | None = None  # None until the first sample

    def sample(self, devices=None) -> dict[int, dict[str, float]]:
        """Read every device's allocator stats and refresh the gauges.
        Returns ``{device_index: stats}`` (empty on statless backends)."""
        if devices is None:
            # ditl: allow(import-layering) -- lazy by design: sampling implies an armed watcher in a process that already initialized jax
            import jax

            devices = jax.local_devices()
        out: dict[int, dict[str, float]] = {}
        for i, d in enumerate(devices):
            stats = device_memory_stats(d)
            if stats is None:
                continue
            in_use = stats.get("bytes_in_use", 0.0)
            peak = max(self._peaks.get(i, 0.0),
                       stats.get("peak_bytes_in_use", 0.0), in_use)
            self._peaks[i] = peak
            stats["peak_bytes_in_use"] = peak
            for key, v in stats.items():
                self.registry.gauge(
                    f"{PREFIX}_device{i}_{key}",
                    f"device {i} allocator {key}",
                ).set(v)
            out[i] = stats
        self.available = bool(out)
        self._last = out
        return out

    def report(self) -> dict:
        """Summary for bench/trainer JSON: per-device last sample +
        high-watermark + utilization; ``{}`` on statless backends (the
        absent-not-zero rule)."""
        out: dict = {}
        for i, stats in sorted(self._last.items()):
            row = {k: int(v) for k, v in stats.items()}
            limit = stats.get("bytes_limit", 0.0)
            if limit > 0:
                row["peak_utilization"] = round(self._peaks[i] / limit, 4)
            out[f"device{i}"] = row
        return out

    def oom_dump(self, exc: BaseException | None = None) -> dict:
        """Build (and journal, when armed) the OOM post-mortem record."""
        dump = live_buffer_topk(self.topk)
        if exc is not None:
            dump["error"] = f"{type(exc).__name__}: {str(exc)[:500]}"
        if self._last:
            dump["device_stats"] = {
                f"device{i}": {k: int(v) for k, v in s.items()}
                for i, s in sorted(self._last.items())
            }
        if self.journal is not None:
            self.journal.event("memory.oom_dump", **dump)
        return dump

    @contextlib.contextmanager
    def guard(self):
        """Re-raise everything; journal the top-k live-buffer dump first
        when the failure is OOM-class. The dump runs before the exception
        unwinds frames holding array references, so the buffer list still
        shows the step's working set."""
        try:
            yield
        except Exception as e:  # noqa: BLE001 - classify, dump, re-raise
            if is_oom_error(e):
                with contextlib.suppress(Exception):
                    self.oom_dump(e)
            raise


# Module-level watcher for the serving path: infer/server.py appends these
# lines to /metrics. One sample per scrape (allocator reads are cheap), and
# the scrape never breaks on a statless backend.
_scrape_watcher: MemoryWatcher | None = None


def memory_metrics_lines() -> list[str]:
    global _scrape_watcher
    try:
        if _scrape_watcher is None:
            _scrape_watcher = MemoryWatcher()
        if not _scrape_watcher.sample():
            return []
        return _scrape_watcher.registry.render().splitlines()
    except Exception:  # noqa: BLE001 - /metrics must never 500 over gauges
        return []
