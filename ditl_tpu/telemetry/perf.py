"""Training/hardware performance observatory (ISSUE 7 tentpole leg 1+3).

Three pieces, all host-only like the rest of telemetry/ (nothing here
imports jax at module scope; the compiled-cost helpers take the objects the
caller already holds):

- **Step-time anatomy** (``StepAnatomy``): decomposes the training step
  path's wall clock into the phases the trainer can actually measure —
  ``data_wait`` (host blocked in the data iterator), ``host_dispatch``
  (host wall inside the async step call: argument staging, dispatch, and —
  because donated input buffers backpressure the dispatch — any device time
  the host caught up to there), ``device_compute`` (host wall blocked in
  the metrics flush sync, i.e. the device finishing work the host had
  already dispatched), and ``checkpoint_overlap`` (the blocking portion of
  async checkpoint saves that interleaves the step stream). Conservation is
  the same design invariant as goodput.py: buckets + the measured ``other``
  remainder equal the tracked wall EXACTLY by construction, and the tier-1
  test asserts the attributed buckets land within 5% of the wall the
  trainer measured independently.

- **Compiled-function cost analysis** (``compiled_cost``, ``roofline``):
  pulls XLA's own flops / bytes-accessed numbers from
  ``jitted.lower(...).compile().cost_analysis()`` and turns them into an
  achieved-vs-roofline report: arithmetic intensity (flops/byte), the
  roofline's MFU ceiling at that intensity, and whether the program sits on
  the compute or memory side of the ridge. This is the per-step complement
  to bench.py's analytic end-of-run MFU scalar.

- **Versioned sweep records** (``new_sweep_record`` / ``load_sweep_record``
  / ``record_sweep_cell``): the one JSON format every grid-shaped
  measurement writes — ``bench.py --sweep``, ``experiments/bwd_kernels.py``,
  ``experiments/bwd_levers.py`` — so ``perf_compare`` can diff any two of
  them. Records are **resumable**: one file holds a ``cells`` map keyed by
  the cell's override spec; a crashed sweep reruns only the missing cells.
"""

from __future__ import annotations

import json
import os
import subprocess
from typing import Any, Mapping

__all__ = [
    "ANATOMY_BUCKETS",
    "SWEEP_SCHEMA",
    "StepAnatomy",
    "compiled_cost",
    "roofline",
    "peak_hbm_bw",
    "git_rev",
    "cell_key",
    "new_sweep_record",
    "load_sweep_record",
    "record_sweep_cell",
    "pop_out_arg",
    "run_recorded_cells",
]

# Canonical anatomy bucket names (report keys are f"{name}_s"); "other" is
# computed as the remainder, never added.
ANATOMY_BUCKETS = (
    "data_wait",
    "host_dispatch",
    "device_compute",
    "checkpoint_overlap",
)

# Version stamped into every bench/sweep record. Bump when a field changes
# meaning; perf_compare refuses to diff across schema versions.
SWEEP_SCHEMA = 1

# Peak HBM bandwidth (bytes/s) per device_kind, same EXACT-match discipline
# as bench._PEAK_FLOPS: unknown kinds omit the roofline instead of guessing.
_PEAK_HBM_BW = {
    "tpu v5 lite": 819e9,
    "tpu v5e": 819e9,
    "tpu v5litepod": 819e9,
    "tpu v6 lite": 1640e9,
    "tpu v6e": 1640e9,
    "tpu v5p": 2765e9,
    "tpu v5": 2765e9,
    "tpu v4": 1228e9,
    "tpu v4 lite": 614e9,
}


class StepAnatomy:
    """Accumulate the step path's wall-time decomposition.

    The caller owns two clocks: per-bucket host walls (``add``) and the
    independently measured step-path wall (``add_wall``) the buckets are
    conserved against. The two must cover the SAME interval set — the
    trainer adds one wall span per step window (data wait + window body)
    and one per checkpoint save, and feeds the buckets from the phase
    columns train/metrics.py already measures.
    """

    def __init__(self):
        self._buckets: dict[str, float] = {}
        self._wall = 0.0
        self.steps = 0

    def add(self, bucket: str, seconds: float) -> None:
        if bucket not in ANATOMY_BUCKETS:
            raise ValueError(
                f"unknown anatomy bucket {bucket!r} (one of {ANATOMY_BUCKETS})"
            )
        if seconds > 0:
            self._buckets[bucket] = self._buckets.get(bucket, 0.0) + seconds

    def add_wall(self, seconds: float, n_steps: int = 0) -> None:
        """One independently measured step-path wall span (the interval the
        buckets above decompose)."""
        if seconds > 0:
            self._wall += seconds
        self.steps += n_steps

    @property
    def wall_s(self) -> float:
        return self._wall

    def report(self) -> dict:
        """Keys: ``wall_step_s`` (measured), one ``{bucket}_s`` per
        non-empty bucket, ``other_s`` (floored remainder),
        ``conservation_error`` (signed attributed-vs-wall mismatch as a
        fraction of wall — the number the 5% tier-1 invariant pins),
        per-step means when ``steps`` is known, and ``steps``."""
        out: dict = {"wall_step_s": round(self._wall, 6), "steps": self.steps}
        tracked = sum(self._buckets.values())
        for name in ANATOMY_BUCKETS:
            if name in self._buckets:
                out[f"{name}_s"] = round(self._buckets[name], 6)
        out["other_s"] = round(max(0.0, self._wall - tracked), 6)
        if self._wall > 0:
            out["conservation_error"] = round(
                (tracked - self._wall) / self._wall, 4
            )
            if self.steps > 0:
                out["per_step_ms"] = {
                    name: round(v / self.steps * 1e3, 3)
                    for name, v in sorted(self._buckets.items())
                }
                out["per_step_ms"]["wall"] = round(
                    self._wall / self.steps * 1e3, 3
                )
        return out


def compiled_cost(compiled: Any, n_steps: int = 1) -> dict | None:
    """Flops + bytes accessed of a compiled XLA executable, per step.

    ``compiled`` is what ``jitted.lower(*args).compile()`` returns;
    ``n_steps`` divides the program's totals when one program runs a whole
    step window (train/step.make_multi_step). Returns None when the backend
    exposes no cost model (some plugin runtimes) — callers omit the
    roofline rather than guessing. Never raises: cost analysis is advisory
    telemetry, not a correctness dependency."""
    try:
        ca = compiled.cost_analysis()
    except Exception:  # noqa: BLE001 - backend-dependent, advisory only
        return None
    if isinstance(ca, (list, tuple)):  # older jax: one dict per device
        ca = ca[0] if ca else None
    if not isinstance(ca, Mapping):
        return None
    flops = float(ca.get("flops", 0.0))
    byts = float(ca.get("bytes accessed", 0.0))
    if flops <= 0:
        return None
    out = {
        "flops_per_step": flops / max(1, n_steps),
        "bytes_per_step": byts / max(1, n_steps) if byts > 0 else None,
    }
    try:
        mem = compiled.memory_analysis()
        out["temp_bytes"] = int(mem.temp_size_in_bytes)
        out["argument_bytes"] = int(mem.argument_size_in_bytes)
        out["output_bytes"] = int(mem.output_size_in_bytes)
    except Exception:  # noqa: BLE001
        pass
    return out


def peak_hbm_bw(device_kind: str) -> float | None:
    return _PEAK_HBM_BW.get(device_kind.lower().strip())


def roofline(
    flops_per_step: float,
    bytes_per_step: float | None,
    step_time_s: float,
    peak_flops: float,
    peak_bw: float | None,
) -> dict:
    """Achieved-vs-roofline report for one compiled step.

    ``mfu_cost`` is XLA-counted flops / wall / peak — the cost-model
    counterpart to bench's analytic MFU (it INCLUDES remat recompute, so
    ``mfu_cost - mfu`` measures the recompute tax). ``ai_flops_per_byte``
    is arithmetic intensity; when the bandwidth peak is known the roofline
    ceiling at that intensity is ``min(1, ai * peak_bw / peak_flops)`` and
    ``bound`` names which side of the ridge the program sits on."""
    out: dict = {
        "flops_per_step": flops_per_step,
        "achieved_tflops": round(flops_per_step / step_time_s / 1e12, 3),
        "mfu_cost": round(flops_per_step / step_time_s / peak_flops, 4),
    }
    if bytes_per_step:
        ai = flops_per_step / bytes_per_step
        out["bytes_per_step"] = bytes_per_step
        out["ai_flops_per_byte"] = round(ai, 2)
        out["achieved_gbps"] = round(bytes_per_step / step_time_s / 1e9, 2)
        if peak_bw:
            ridge = peak_flops / peak_bw
            out["roofline_mfu_cap"] = round(min(1.0, ai / ridge), 4)
            out["bound"] = "memory" if ai < ridge else "compute"
            out["hbm_utilization"] = round(
                bytes_per_step / step_time_s / peak_bw, 4
            )
    return out


def git_rev(repo_dir: str | None = None) -> str:
    """Short git revision of the repo a record was measured at (plus
    ``-dirty`` when the tree has local edits); "unknown" outside a repo —
    records stay writable anywhere."""
    cwd = repo_dir or os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    try:
        rev = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"], cwd=cwd,
            capture_output=True, text=True, timeout=10,
        )
        if rev.returncode != 0:
            return "unknown"
        dirty = subprocess.run(
            ["git", "status", "--porcelain"], cwd=cwd,
            capture_output=True, text=True, timeout=10,
        )
        suffix = "-dirty" if dirty.returncode == 0 and dirty.stdout.strip() else ""
        return rev.stdout.strip() + suffix
    except (OSError, subprocess.SubprocessError):
        return "unknown"


def cell_key(overrides: Mapping[str, Any]) -> str:
    """Deterministic key for one sweep cell: sorted ``k=v`` joined with
    commas (`"(base)"` for the empty cell) — human-greppable in the JSON
    and stable across runs, which is what resumability hangs on."""
    if not overrides:
        return "(base)"
    return ",".join(f"{k}={overrides[k]}" for k in sorted(overrides))


def new_sweep_record(name: str, meta: Mapping[str, Any] | None = None) -> dict:
    return {
        "schema": SWEEP_SCHEMA,
        "git_rev": git_rev(),
        "sweep": name,
        "meta": dict(meta or {}),
        "cells": {},
    }


def load_sweep_record(path: str) -> dict | None:
    """Load an existing sweep record for resumption; None when the file is
    absent, unparseable, or a different schema version (a stale-format file
    is rewritten from scratch rather than appended to incompatibly)."""
    try:
        with open(path) as f:
            rec = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(rec, dict) or rec.get("schema") != SWEEP_SCHEMA:
        return None
    if not isinstance(rec.get("cells"), dict):
        return None
    return rec


def record_sweep_cell(
    path: str, record: dict, key: str, cell: Mapping[str, Any]
) -> dict:
    """Add one finished cell and persist the whole record atomically
    (tmp + rename): a sweep killed mid-write resumes from the last
    complete cell set, never from a torn JSON."""
    record["cells"][key] = dict(cell)
    tmp = f"{path}.tmp"
    with open(tmp, "w") as f:
        json.dump(record, f, indent=2, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return record


def pop_out_arg(args: list, default: str) -> str:
    """Extract a ``--out=PATH`` flag from a positional argv list (mutates
    ``args``) — the experiment scripts' shared spelling."""
    out = default
    for a in list(args):
        if a.startswith("--out="):
            out = a.split("=", 1)[1]
            args.remove(a)
    return out


def run_recorded_cells(path, name, meta, items, runner) -> dict:
    """Shared record-as-you-go loop for A/B and grid scripts
    (experiments/bwd_kernels.py, bwd_levers.py): each ``(key, payload)``
    item runs through ``runner(key, payload) -> cell dict`` and lands in
    the sweep record at ``path`` immediately (atomic write per cell).
    Resume semantics match ``bench.py --sweep``: cells already recorded
    WITHOUT an error are skipped, errored cells are retried (a transient
    failure must not be permanently skipped), and a runner returning an
    ``{"error": ...}`` cell records the failure so perf_compare's
    measured-to-crashing gate sees it. Returns ``{key: cell}`` covering
    both freshly run and resumed cells."""
    record = load_sweep_record(path)
    if record is None:
        record = new_sweep_record(name, meta=meta)
    out: dict = {}
    for key, payload in items:
        prior = record["cells"].get(key)
        if prior is not None and "error" not in prior:
            out[key] = prior
            print(f"[{key}] already recorded in {path} — skipping",
                  flush=True)
            continue
        cell = runner(key, payload)
        if cell is None:
            continue
        record = record_sweep_cell(path, record, key, cell)
        out[key] = cell
    return out
