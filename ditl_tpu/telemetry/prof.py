"""Continuous profiling & stall attribution (ISSUE 18 tentpole).

Every observability layer so far — metrics (ISSUE 3), tracing (ISSUE 6),
the perf observatory (ISSUE 7), the flight-recorder/incident plane
(ISSUE 10) — answers *what* went slow. None answers *what code was
running when it did*. The single-threaded evloop data plane (ISSUE 17)
made that gap existential: one blocking call in a loop callback stalls
every open connection and stream at once, and the static
``event-loop-hygiene`` rule cannot see runtime behavior. This module is
the runtime half of that guard:

- :class:`SamplingProfiler` — a wall-clock sampling profiler: a daemon
  thread reads ``sys._current_frames()`` at a configurable hertz and
  folds each thread's stack into bounded collapsed-stack counters. No
  lock on the sample path (the sampler is the only writer; readers take
  GIL-atomic snapshots), registry-style get-or-create per stack,
  memory-capped with oldest-first eviction. Exports flamegraph-ready
  collapsed text (``collapsed()``) and a Chrome-trace section riding the
  existing ``trace_export`` machinery (``chrome_trace()``).
- :class:`LoopHeartbeat` — the evloop stamps a monotonic heartbeat once
  per iteration: one tuple write (``@hot_path``-cheap), flagged busy
  while the tick processes work and idle while the loop is parked in
  ``selector.select`` (a parked loop is HEALTHY — only busy age counts
  as lag, which is what makes the idle-at-threshold false-positive pin
  hold).
- :class:`LoopWatchdog` — a daemon thread converts heartbeat age into a
  ``ditl_loop_lag_seconds`` histogram; when busy lag crosses the
  threshold it burst-samples the loop thread's stack at high frequency
  for the stall's duration, aggregates the samples into a **convicting
  stack** (modal top frame + file:line), journals ``loop.stall``, and
  feeds the ISSUE 10 anomaly->incident path (fingerprint-deduped,
  cooldown-rate-limited, chaos-attributed like every other trigger).
- :class:`OffloadPoolMonitor` — queue-wait and worker-occupancy for the
  evloop's handler pool, so "the loop is fine but the pool is starved"
  is distinguishable from a blocked loop (troubleshooting §36).

Stdlib-only and jax-free on import, like the rest of ditl_tpu/telemetry
(held by the import-layering rule and the runtime subprocess pin).

CLI: ``python -m ditl_tpu.telemetry.prof --collapse profile.txt
[--top N] [--chrome out.json]`` post-processes a collapsed-stack file
(e.g. a bundle's ``profile.txt`` or a ``/profile`` response saved to
disk).
"""

from __future__ import annotations

import argparse
import collections
import json
import sys
import threading
import time

from ditl_tpu.utils.logging import get_logger

logger = get_logger(__name__)

__all__ = [
    "LoopHeartbeat",
    "LoopWatchdog",
    "OffloadPoolMonitor",
    "SamplingProfiler",
    "active_profiler",
    "collapsed_to_chrome",
    "main",
    "profile_for",
    "top_frames",
]

_PREFIX = "ditl_prof"

# Default sampling rate for transient /profile captures. A prime, so the
# sampler cannot phase-lock with millisecond-periodic work and sample the
# same frame forever (the classic aliasing failure of round-hertz
# profilers).
DEFAULT_HZ = 97.0

# Frames deeper than this are truncated root-side: the leaf frames carry
# the conviction; an unbounded recursion must not grow a stack key
# without bound.
_MAX_DEPTH = 64


def _fold(frame, depth: int = _MAX_DEPTH) -> str:
    """Collapse a frame chain into one ``root;...;leaf`` key, each frame
    ``func (file.py:line)`` with only the basename (full paths differ per
    checkout; basenames keep keys stable and the flamegraph readable)."""
    parts: list[str] = []
    while frame is not None and len(parts) < depth:
        code = frame.f_code
        fname = code.co_filename.rsplit("/", 1)[-1]
        parts.append(f"{code.co_name} ({fname}:{frame.f_lineno})")
        frame = frame.f_back
    parts.reverse()
    return ";".join(parts)


class SamplingProfiler:
    """Wall-clock sampling profiler over ``sys._current_frames()``.

    One daemon thread samples every live thread (its own excluded) at
    ``hz`` into an insertion-ordered map of
    ``"thread;frame;...;leaf" -> count``. The sample thread is the only
    writer and takes no lock: per-key re-hits use ``move_to_end`` /
    item assignment (GIL-atomic on an OrderedDict), and readers snapshot
    with ``dict(...)``. The map is capped at ``max_stacks`` distinct
    stacks; overflow evicts oldest-first (recency order, so a stack that
    keeps firing is never the one dropped) and counts the eviction —
    bounded memory is a hard invariant, not a hope.

    ``phase_thread``/``set_phase`` add coarse phase attribution for ONE
    designated thread (the trainer's step loop): while a phase is set,
    that thread's samples are also folded into a per-phase counter, so
    ``StepAnatomy``'s ``host_dispatch`` bucket can name actual frames in
    the run summary instead of only a duration.
    """

    def __init__(self, hz: float = DEFAULT_HZ, max_stacks: int = 2048,
                 only_thread: int | None = None, registry=None):
        if hz <= 0:
            raise ValueError(f"prof hz must be > 0, got {hz}")
        if max_stacks < 1:
            raise ValueError(f"prof max_stacks must be >= 1, got {max_stacks}")
        self.hz = float(hz)
        self.max_stacks = int(max_stacks)
        self.only_thread = only_thread  # restrict to one ident (burst mode)
        self.samples = 0
        self.evicted = 0
        # Optional /metrics mirror (instruments are lock-free; updated
        # from the sample thread only, once per sweep — never per frame).
        self._samples_c = self._stacks_g = self._evicted_c = None
        if registry is not None:
            self._samples_c = registry.counter(
                f"{_PREFIX}_samples",
                "stack samples the continuous profiler has taken")
            self._stacks_g = registry.gauge(
                f"{_PREFIX}_stacks",
                "distinct collapsed stacks currently held (capped at "
                "telemetry.prof_max_stacks)")
            self._evicted_c = registry.counter(
                f"{_PREFIX}_stacks_evicted",
                "collapsed stacks evicted oldest-first at the "
                "prof_max_stacks memory cap")
        self.started_at: float | None = None
        self.stopped_at: float | None = None
        self._stacks: collections.OrderedDict[str, int] = \
            collections.OrderedDict()
        self._phase: str | None = None
        self._phase_thread: int | None = None
        self._phase_stacks: dict[str, collections.OrderedDict] = {}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "SamplingProfiler":
        if self._thread is not None:
            return self
        self.started_at = time.monotonic()
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="ditl-prof-sampler", daemon=True)
        self._thread.start()
        _register(self)
        return self

    def stop(self) -> None:
        t = self._thread
        if t is None:
            return
        self._stop.set()
        t.join(timeout=5.0)
        self._thread = None
        self.stopped_at = time.monotonic()
        _unregister(self)

    # -- the sample path (no locks) ---------------------------------------

    def _run(self) -> None:
        me = threading.get_ident()
        interval = 1.0 / self.hz
        while not self._stop.wait(interval):
            try:
                frames = sys._current_frames()
            except Exception:  # noqa: BLE001 - sampling must never crash
                continue
            names = {t.ident: t.name for t in threading.enumerate()}
            phase = self._phase
            for ident, frame in frames.items():
                if ident == me:
                    continue
                if self.only_thread is not None and ident != self.only_thread:
                    continue
                stack = _fold(frame)
                if not stack:
                    continue
                thread = names.get(ident, f"thread-{ident}")
                self._note(self._stacks, f"{thread};{stack}")
                if phase is not None and ident == self._phase_thread:
                    bucket = self._phase_stacks.get(phase)
                    if bucket is None:
                        bucket = collections.OrderedDict()
                        self._phase_stacks[phase] = bucket
                    self._note(bucket, stack)
                self.samples += 1
            if self._samples_c is not None:
                self._samples_c.inc(self.samples - self._samples_c.value)
                self._stacks_g.set(float(len(self._stacks)))
                if self.evicted > self._evicted_c.value:
                    self._evicted_c.inc(self.evicted - self._evicted_c.value)

    def _note(self, stacks: collections.OrderedDict, key: str) -> None:
        """One sample into one bounded counter map. Re-hit moves the key
        to the recent end, so eviction (popitem(last=False)) always drops
        the stack that has gone longest without firing."""
        if key in stacks:
            stacks[key] += 1
            stacks.move_to_end(key)
            return
        while len(stacks) >= self.max_stacks:
            stacks.popitem(last=False)
            self.evicted += 1
        stacks[key] = 1

    # -- phase attribution (trainer) --------------------------------------

    def arm_phases(self, thread_ident: int | None = None) -> None:
        """Designate the thread whose samples get per-phase attribution
        (the caller's thread by default — the trainer's step loop)."""
        self._phase_thread = (thread_ident if thread_ident is not None
                              else threading.get_ident())

    def set_phase(self, phase: str | None) -> None:
        """One attribute write — cheap enough for the step loop."""
        self._phase = phase

    def phase_top(self, phase: str, n: int = 5) -> list[dict]:
        """Top leaf frames sampled while ``phase`` was set on the armed
        thread: ``[{"frame": ..., "samples": ...}, ...]``, most first."""
        bucket = self._phase_stacks.get(phase)
        if not bucket:
            return []
        leaves: collections.Counter = collections.Counter()
        for stack, count in dict(bucket).items():
            leaves[stack.rsplit(";", 1)[-1]] += count
        return [{"frame": frame, "samples": count}
                for frame, count in leaves.most_common(n)]

    # -- export ------------------------------------------------------------

    def snapshot(self) -> dict[str, int]:
        return dict(self._stacks)

    def collapsed(self) -> str:
        """Flamegraph-ready collapsed-stack text: one ``stack count``
        line per distinct stack (``flamegraph.pl``/speedscope input)."""
        return "\n".join(f"{stack} {count}"
                         for stack, count in self.snapshot().items())

    def top(self, n: int = 10) -> list[dict]:
        return top_frames(self.snapshot(), n)

    def chrome_trace(self) -> dict:
        """The aggregated profile as a Chrome-trace section, riding the
        existing ``trace_export`` machinery (one lane per thread, each
        stack a span whose duration is its sampled share of the capture
        window)."""
        return collapsed_to_chrome(self.snapshot(), self.hz)


# ---------------------------------------------------------------------------
# active-profiler registry (incident bundles read the newest armed one)
# ---------------------------------------------------------------------------

_ACTIVE: list[SamplingProfiler] = []
_ACTIVE_LOCK = threading.Lock()


def _register(p: SamplingProfiler) -> None:
    with _ACTIVE_LOCK:
        _ACTIVE.append(p)


def _unregister(p: SamplingProfiler) -> None:
    with _ACTIVE_LOCK:
        if p in _ACTIVE:
            _ACTIVE.remove(p)


def active_profiler() -> SamplingProfiler | None:
    """The newest armed profiler, or None. Incident bundles embed its
    collapsed stacks as ``profile.txt`` when one is running — the "what
    was executing" page of the black box."""
    with _ACTIVE_LOCK:
        return _ACTIVE[-1] if _ACTIVE else None


def profile_for(seconds: float, hz: float = DEFAULT_HZ,
                max_stacks: int = 2048) -> str:
    """Run a transient sampler for ``seconds`` and return collapsed
    stacks — the ``/profile?seconds=N`` endpoint body. Blocks the
    calling thread (a handler/offload worker, never the loop)."""
    p = SamplingProfiler(hz=hz, max_stacks=max_stacks).start()
    try:
        time.sleep(max(0.0, seconds))
    finally:
        p.stop()
    return p.collapsed()


# ---------------------------------------------------------------------------
# collapsed-stack post-processing (shared by exports, CLI, tests)
# ---------------------------------------------------------------------------


def parse_collapsed(text: str) -> dict[str, int]:
    out: dict[str, int] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        stack, _, count = line.rpartition(" ")
        try:
            out[stack] = out.get(stack, 0) + int(count)
        except ValueError:
            continue
    return out


def top_frames(stacks: dict[str, int], n: int = 10) -> list[dict]:
    """Top LEAF frames by inclusive sample count — where the time
    actually went, flamegraph-tip view."""
    leaves: collections.Counter = collections.Counter()
    for stack, count in stacks.items():
        leaves[stack.rsplit(";", 1)[-1]] += count
    return [{"frame": frame, "samples": count}
            for frame, count in leaves.most_common(n)]


def collapsed_to_chrome(stacks: dict[str, int], hz: float) -> dict:
    """Convert aggregated collapsed stacks into journal-shaped span
    records and hand them to ``trace_export.to_chrome_trace`` — the
    profile opens in the same viewer as every other trace artifact. Each
    thread is a source (its own process lane); each stack becomes one
    span whose duration is ``count / hz`` (its sampled share of the
    wall), laid end to end."""
    from ditl_tpu.telemetry.trace_export import to_chrome_trace

    cursors: dict[str, float] = {}
    records: list[dict] = []
    for stack, count in stacks.items():
        thread, _, frames = stack.partition(";")
        dur = count / max(hz, 1e-9)
        t0 = cursors.get(thread, 0.0)
        cursors[thread] = t0 + dur
        records.append({
            "event": "trace.span",
            "ts": t0,
            "dur_s": dur,
            "name": frames.rsplit(";", 1)[-1] or stack,
            "source": f"prof:{thread}",
            "trace": "",
            "stack": frames,
            "samples": count,
        })
    return to_chrome_trace(records)


# ---------------------------------------------------------------------------
# event-loop heartbeat + lag watchdog
# ---------------------------------------------------------------------------


class LoopHeartbeat:
    """One tuple write per loop iteration. ``busy()`` as the tick starts
    processing (select returned), ``idle()`` right before the loop parks
    in select. The watchdog reads ``(ts, busy)`` in one GIL-atomic load;
    only BUSY age is lag — a loop parked in select for its full poll
    interval is healthy, not stalled."""

    __slots__ = ("_stamp", "thread_ident")

    def __init__(self):
        self._stamp = (time.monotonic(), False)
        self.thread_ident: int | None = None

    def attach(self) -> None:
        """Record the loop thread's ident (called once, from the loop)."""
        self.thread_ident = threading.get_ident()
        self._stamp = (time.monotonic(), False)

    def busy(self) -> None:
        self._stamp = (time.monotonic(), True)

    def idle(self) -> None:
        self._stamp = (time.monotonic(), False)

    def read(self) -> tuple[float, bool]:
        return self._stamp


class LoopWatchdog:
    """Heartbeat-age watchdog for ONE event loop.

    A daemon thread checks the heartbeat every ``threshold_s / 4``
    (floored at 5 ms): while the loop is busy, the instantaneous age
    lands in ``ditl_loop_lag_seconds``; when it crosses ``threshold_s``
    the watchdog burst-samples the loop thread at ``burst_hz`` until the
    heartbeat advances (or ``max_stall_s`` gives up on a wedged loop),
    then aggregates the burst into a convicting stack — the modal
    deepest frame with its file:line — journals ``loop.stall``, bumps
    ``ditl_loop_stalls_total``, and triggers a ``loop.stall`` anomaly
    through the ISSUE 10 plane (so the bundle carries flight rings, the
    metrics snapshot, chaos attribution, and the profile, exactly like
    every other trigger). One sustained stall is ONE stall event: the
    burst spans it, and the incident plane's fingerprint cooldown
    dedupes repeats.
    """

    def __init__(self, heartbeat: LoopHeartbeat, *,
                 threshold_s: float, burst_hz: float = 200.0,
                 registry=None, plane=None, journal=None,
                 source: str = "evloop", max_stall_s: float = 10.0):
        if threshold_s <= 0:
            raise ValueError(
                f"watchdog threshold_s must be > 0, got {threshold_s}")
        self.heartbeat = heartbeat
        self.threshold_s = float(threshold_s)
        self.burst_hz = max(1.0, float(burst_hz))
        self.plane = plane
        self.journal = journal
        self.source = source
        self.max_stall_s = max_stall_s
        self.stalls = 0
        self.last_stall: dict | None = None
        self._lag_hist = None
        self._stall_counter = None
        if registry is not None:
            from ditl_tpu.telemetry.registry import LATENCY_BUCKETS_S

            self._lag_hist = registry.histogram(
                "ditl_loop_lag_seconds",
                "event-loop heartbeat age while busy (watchdog-sampled; "
                "the excursion a loop.stall convicts)",
                LATENCY_BUCKETS_S)
            self._stall_counter = registry.counter(
                "ditl_loop_stalls",
                "event-loop stalls past telemetry.loop_stall_threshold_s "
                "(each journaled as loop.stall with a convicting stack)")
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "LoopWatchdog":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="ditl-loop-watchdog", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        t = self._thread
        if t is None:
            return
        self._stop.set()
        t.join(timeout=5.0)
        self._thread = None

    def lag_p95(self) -> float | None:
        """p95 busy-lag from the histogram; None before any busy sample
        (absent != 0 — the same discipline as the role p95s on
        /health)."""
        h = self._lag_hist
        if h is None or not h.count:
            return None
        return h.quantile(0.95)

    # -- the watchdog thread ----------------------------------------------

    def _run(self) -> None:
        interval = max(0.005, self.threshold_s / 4.0)
        while not self._stop.wait(interval):
            ts, busy = self.heartbeat.read()
            if not busy:
                continue
            lag = time.monotonic() - ts
            if self._lag_hist is not None:
                self._lag_hist.observe(lag)
            if lag >= self.threshold_s:
                try:
                    self._convict(ts, lag)
                except Exception:  # noqa: BLE001 - diagnosis never kills
                    logger.exception("loop watchdog: conviction failed")

    def _convict(self, stall_ts: float, lag: float) -> None:
        """Burst-sample the loop thread for the stall's remaining
        duration, then aggregate and report."""
        ident = self.heartbeat.thread_ident
        interval = 1.0 / self.burst_hz
        counts: collections.Counter = collections.Counter()
        deadline = time.monotonic() + self.max_stall_s
        while not self._stop.is_set() and time.monotonic() < deadline:
            ts, busy = self.heartbeat.read()
            if ts != stall_ts or not busy:
                break  # heartbeat advanced: the stall is over
            frame = (sys._current_frames().get(ident)
                     if ident is not None else None)
            if frame is not None:
                counts[_fold(frame)] += 1
            if self._stop.wait(interval):
                break
        duration = time.monotonic() - stall_ts
        self.stalls += 1
        if self._stall_counter is not None:
            self._stall_counter.inc()
        if counts:
            stack, hits = counts.most_common(1)[0]
            frame = stack.rsplit(";", 1)[-1]
        else:  # stall ended before the first burst sample landed
            stack, hits, frame = "", 0, "unsampled"
        detail = {
            "duration_s": round(duration, 4),
            "lag_at_detection_s": round(lag, 4),
            "frame": frame,
            "stack": stack,
            "burst_samples": int(sum(counts.values())),
            "modal_samples": int(hits),
            "source": self.source,
            # One fingerprint per convicting frame: a storm of stalls at
            # the same blocking call is ONE incident (cooldown), while
            # stalls at two different call sites are two.
            "fingerprint_key": frame,
        }
        self.last_stall = detail
        logger.warning("loop stall: %.0f ms on %s", duration * 1000, frame)
        if self.journal is not None:
            try:
                self.journal.event("loop.stall", **detail)
            except Exception:  # noqa: BLE001
                logger.exception("loop watchdog: journal write failed")
        if self.plane is not None:
            from ditl_tpu.telemetry.anomaly import Anomaly

            self.plane.trigger(Anomaly(
                "loop.stall", severity="warning", detail=dict(detail)))


# ---------------------------------------------------------------------------
# offload-pool saturation accounting
# ---------------------------------------------------------------------------


class OffloadPoolMonitor:
    """Queue-wait + occupancy for the evloop's handler pool, written from
    the WORKER side only (never the loop): the loop stamps a monotonic t0
    when it frames a dispatch, the worker observes the wait when it picks
    the job up and holds the busy gauge for the handler's duration.
    Sustained queue-wait with a healthy loop-lag histogram reads "pool
    starved, loop fine" — the signature troubleshooting §36 separates
    from a blocked loop."""

    def __init__(self, queue_hist, busy_gauge, size_gauge, workers: int):
        self.queue_hist = queue_hist
        self.busy_gauge = busy_gauge
        self.size_gauge = size_gauge
        self._busy = 0
        if size_gauge is not None:
            size_gauge.set(float(workers))

    def job_started(self, queued_ts: float) -> None:
        if self.queue_hist is not None:
            self.queue_hist.observe(
                max(0.0, time.monotonic() - queued_ts))
        self._busy += 1
        if self.busy_gauge is not None:
            self.busy_gauge.set(float(self._busy))

    def job_finished(self) -> None:
        self._busy = max(0, self._busy - 1)
        if self.busy_gauge is not None:
            self.busy_gauge.set(float(self._busy))


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m ditl_tpu.telemetry.prof",
        description="Post-process a collapsed-stack profile (a bundle's "
                    "profile.txt or a /profile?seconds=N response).")
    ap.add_argument("--collapse", required=True,
                    help="collapsed-stack file ('stack count' lines)")
    ap.add_argument("--top", type=int, default=0, metavar="N",
                    help="print the top N leaf frames by samples")
    ap.add_argument("--chrome", default="", metavar="OUT",
                    help="write a Chrome-trace JSON rendering to OUT")
    ap.add_argument("--hz", type=float, default=DEFAULT_HZ,
                    help="sample rate the profile was captured at "
                         "(scales Chrome-trace span durations)")
    args = ap.parse_args(argv)
    try:
        with open(args.collapse) as f:
            stacks = parse_collapsed(f.read())
    except OSError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if not stacks:
        print("error: no collapsed stacks in input", file=sys.stderr)
        return 2
    if args.chrome:
        with open(args.chrome, "w") as f:
            json.dump(collapsed_to_chrome(stacks, args.hz), f)
        print(f"wrote {args.chrome}")
    if args.top or not args.chrome:
        n = args.top or 10
        total = sum(stacks.values())
        print(f"{total} samples, {len(stacks)} distinct stacks")
        for row in top_frames(stacks, n):
            share = row["samples"] / total
            print(f"{row['samples']:8d}  {share:6.1%}  {row['frame']}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
